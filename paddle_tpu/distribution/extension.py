"""Remaining distribution families (ref: python/paddle/distribution/
{cauchy,chi2,continuous_bernoulli,exponential_family,multivariate_normal,
independent,transformed_distribution,lkj_cholesky,binomial,poisson,
student_t}.py)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..base.tape import apply
from .distribution import Distribution, _as_array
from .gamma import Gamma

__all__ = [
    "Cauchy", "Chi2", "ContinuousBernoulli", "ExponentialFamily",
    "MultivariateNormal", "Independent", "TransformedDistribution",
    "LKJCholesky", "Binomial", "Poisson", "StudentT",
]


class ExponentialFamily(Distribution):
    """ref: exponential_family.py — base class: subclasses expose
    natural parameters + log-normalizer; entropy falls out via the
    Bregman identity H = A(θ) - <θ, ∇A(θ)> + E[-h(x)], computed here
    with jax.grad on the log-normalizer."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def entropy(self):
        nat = [n._data if hasattr(n, "_data") else jnp.asarray(n) for n in self._natural_parameters]

        def f(*ns):
            a = self._log_normalizer(*ns)
            grads = jax.grad(lambda *xs: jnp.sum(self._log_normalizer(*xs)), argnums=tuple(range(len(ns))))(*ns)
            ent = a - sum(n * g for n, g in zip(ns, grads))
            return ent - self._mean_carrier_measure

        from ..base.tensor import Tensor

        return Tensor(f(*nat), _internal=True)


class Cauchy(Distribution):
    """ref: cauchy.py."""

    def __init__(self, loc, scale, name=None):
        self.loc = _as_array(loc)
        self.scale = _as_array(scale)
        shape = np.broadcast_shapes(tuple(self.loc.shape), tuple(self.scale.shape))
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        raise ValueError("Cauchy has no mean")

    @property
    def variance(self):
        raise ValueError("Cauchy has no variance")

    @property
    def stddev(self):
        raise ValueError("Cauchy has no stddev")

    def rsample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(m, s):
            return m + s * jax.random.cauchy(key, out_shape, jnp.float32)

        return apply(f, self.loc, self.scale, op_name="cauchy_rsample")

    def log_prob(self, value):
        def f(v, m, s):
            z = (v - m) / s
            return -jnp.log(np.pi * s * (1 + z * z))

        return apply(f, value, self.loc, self.scale, op_name="cauchy_log_prob")

    def cdf(self, value):
        def f(v, m, s):
            return jnp.arctan((v - m) / s) / np.pi + 0.5

        return apply(f, value, self.loc, self.scale, op_name="cauchy_cdf")

    def entropy(self):
        def f(s):
            return jnp.log(4 * np.pi * s)

        return apply(f, self.scale, op_name="cauchy_entropy")

    def kl_divergence(self, other):
        def f(m0, s0, m1, s1):
            return jnp.log(((s0 + s1) ** 2 + (m0 - m1) ** 2) / (4 * s0 * s1))

        return apply(f, self.loc, self.scale, other.loc, other.scale, op_name="cauchy_kl")


class Chi2(Gamma):
    """ref: chi2.py — Gamma(df/2, rate=1/2)."""

    def __init__(self, df, name=None):
        df_t = _as_array(df)
        half = apply(lambda d: d * 0.5, df_t, op_name="chi2_half_df")
        rate = apply(lambda d: jnp.full_like(d, 0.5), df_t, op_name="chi2_rate")
        super().__init__(half, rate)
        self.df = df_t


class ContinuousBernoulli(Distribution):
    """ref: continuous_bernoulli.py — CB(λ) with normalizer C(λ)."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = _as_array(probs)
        self._lims = lims
        super().__init__(batch_shape=tuple(self.probs.shape))

    def _safe_p(self, p):
        lo, hi = self._lims
        cut = (p > lo) & (p < hi)
        return jnp.where(cut, lo, p), cut

    def _log_C(self, p):
        ps, cut = self._safe_p(p)
        out = jnp.log((2.0 * jnp.arctanh(1.0 - 2.0 * ps)) / (1.0 - 2.0 * ps))
        # Taylor expansion at 1/2 for the unstable window
        x = p - 0.5
        taylor = math.log(2.0) + (4.0 / 3.0 + 104.0 / 45.0 * x * x) * x * x
        return jnp.where(cut, taylor, out)

    @property
    def mean(self):
        def f(p):
            ps, cut = self._safe_p(p)
            m = ps / (2.0 * ps - 1.0) + 1.0 / (2.0 * jnp.arctanh(1.0 - 2.0 * ps))
            x = p - 0.5
            taylor = 0.5 + (1.0 / 3.0 + 16.0 / 45.0 * x * x) * x
            return jnp.where(cut, taylor, m)

        return apply(f, self.probs, op_name="cb_mean")

    @property
    def variance(self):
        def f(p):
            ps, _ = self._safe_p(p)
            m = ps / (2.0 * ps - 1.0) + 1.0 / (2.0 * jnp.arctanh(1.0 - 2.0 * ps))
            v = ps * (ps - 1.0) / (1.0 - 2.0 * ps) ** 2 + 1.0 / (2.0 * jnp.arctanh(1.0 - 2.0 * ps)) ** 2
            return v

        return apply(f, self.probs, op_name="cb_variance")

    def rsample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(p):
            u = jax.random.uniform(key, out_shape, jnp.float32, 1e-6, 1 - 1e-6)
            ps, cut = self._safe_p(p)
            icdf = (jnp.log1p(u * (2.0 * ps - 1.0) / (1.0 - ps)) /
                    (jnp.log(ps) - jnp.log1p(-ps)))
            return jnp.where(cut, u, icdf)

        return apply(f, self.probs, op_name="cb_rsample")

    def log_prob(self, value):
        def f(v, p):
            return (v * jnp.log(p) + (1.0 - v) * jnp.log1p(-p)) + self._log_C(p)

        return apply(f, value, self.probs, op_name="cb_log_prob")

    def entropy(self):
        """-E[log p(x)] computed from mean and log C."""

        def f(p):
            ps, _ = self._safe_p(p)
            mean = ps / (2.0 * ps - 1.0) + 1.0 / (2.0 * jnp.arctanh(1.0 - 2.0 * ps))
            return -(mean * jnp.log(ps) + (1.0 - mean) * jnp.log1p(-ps)) - self._log_C(p)

        return apply(f, self.probs, op_name="cb_entropy")


class MultivariateNormal(Distribution):
    """ref: multivariate_normal.py — parameterized by covariance_matrix,
    precision_matrix, or scale_tril."""

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = _as_array(loc)
        given = [a is not None for a in (covariance_matrix, precision_matrix, scale_tril)]
        if sum(given) != 1:
            raise ValueError("give exactly one of covariance_matrix / precision_matrix / scale_tril")
        if scale_tril is not None:
            self._tril = _as_array(scale_tril)
        elif covariance_matrix is not None:
            cov = _as_array(covariance_matrix)
            self._tril = apply(jnp.linalg.cholesky, cov, op_name="mvn_chol")
        else:
            prec = _as_array(precision_matrix)

            def f(pm):
                return jnp.linalg.cholesky(jnp.linalg.inv(pm))

            self._tril = apply(f, prec, op_name="mvn_chol_from_prec")
        d = self.loc.shape[-1]
        super().__init__(batch_shape=tuple(self.loc.shape[:-1]), event_shape=(d,))

    @property
    def mean(self):
        return self.loc

    @property
    def scale_tril(self):
        return self._tril

    @property
    def covariance_matrix(self):
        def f(L):
            return L @ jnp.swapaxes(L, -1, -2)

        return apply(f, self._tril, op_name="mvn_cov")

    @property
    def variance(self):
        def f(L):
            return jnp.sum(L * L, axis=-1)

        return apply(f, self._tril, op_name="mvn_var")

    def rsample(self, shape=()):
        key = self._next_key()
        d = self._event_shape[0]
        out_shape = tuple(shape) + self._batch_shape + (d,)

        def f(m, L):
            eps = jax.random.normal(key, out_shape, jnp.float32)
            return m + jnp.einsum("...ij,...j->...i", L, eps)

        return apply(f, self.loc, self._tril, op_name="mvn_rsample")

    def log_prob(self, value):
        d = self._event_shape[0]

        def f(v, m, L):
            diff = v - m
            sol = jax.scipy.linalg.solve_triangular(L, diff[..., None], lower=True)[..., 0]
            maha = jnp.sum(sol * sol, -1)
            logdet = jnp.sum(jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)), -1)
            return -0.5 * (d * np.log(2 * np.pi) + maha) - logdet

        return apply(f, value, self.loc, self._tril, op_name="mvn_log_prob")

    def entropy(self):
        d = self._event_shape[0]

        def f(L):
            logdet = jnp.sum(jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)), -1)
            return 0.5 * d * (1 + np.log(2 * np.pi)) + logdet

        return apply(f, self._tril, op_name="mvn_entropy")


class Independent(Distribution):
    """ref: independent.py — reinterpret batch dims as event dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        bshape = tuple(base.batch_shape)
        if self.rank > len(bshape):
            raise ValueError("reinterpreted_batch_rank exceeds batch rank")
        super().__init__(
            batch_shape=bshape[: len(bshape) - self.rank],
            event_shape=bshape[len(bshape) - self.rank:] + tuple(base.event_shape),
        )

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        if self.rank == 0:
            return lp
        return lp.sum(axis=tuple(range(-self.rank, 0)))

    def entropy(self):
        ent = self.base.entropy()
        if self.rank == 0:
            return ent
        return ent.sum(axis=tuple(range(-self.rank, 0)))


class TransformedDistribution(Distribution):
    """ref: transformed_distribution.py — push a base distribution
    through invertible transforms (objects with forward/inverse/
    forward_log_det_jacobian; see distribution.transform)."""

    def __init__(self, base, transforms):
        self.base = base
        self.transforms = list(transforms)
        super().__init__(batch_shape=tuple(base.batch_shape),
                         event_shape=tuple(base.event_shape))

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        lp = 0.0
        y = value
        for t in reversed(self.transforms):
            x = t.inverse(y)
            lp = lp - t.forward_log_det_jacobian(x)
            y = x
        return self.base.log_prob(y) + lp


class LKJCholesky(Distribution):
    """ref: lkj_cholesky.py — prior over Cholesky factors of correlation
    matrices; onion-method sampling."""

    def __init__(self, dim, concentration=1.0, sample_method="onion", name=None):
        if dim < 2:
            raise ValueError("dim must be >= 2")
        self.dim = int(dim)
        self.concentration = _as_array(concentration)
        self.sample_method = sample_method
        super().__init__(batch_shape=tuple(self.concentration.shape),
                         event_shape=(dim, dim))

    def sample(self, shape=()):
        key = self._next_key()
        d = self.dim
        eta = float(np.asarray(jax.device_get(self.concentration._data)).reshape(-1)[0])
        out_shape = tuple(shape)
        k1, k2 = jax.random.split(key)
        # onion method (Lewandowski et al. 2009)
        beta0 = eta + (d - 2) / 2.0
        L = np.zeros(out_shape + (d, d), np.float32)
        L[..., 0, 0] = 1.0
        rng_keys = jax.random.split(k2, d)
        for i in range(1, d):
            beta = beta0 - (i - 1) / 2.0
            ki, kn = jax.random.split(rng_keys[i])
            y = np.asarray(jax.random.beta(ki, i / 2.0, beta, out_shape))
            u = np.asarray(jax.random.normal(kn, out_shape + (i,)))
            u = u / np.linalg.norm(u, axis=-1, keepdims=True)
            w = np.sqrt(y)[..., None] * u
            L[..., i, :i] = w
            L[..., i, i] = np.sqrt(np.clip(1 - y, 0, 1))
        from ..base.tensor import Tensor

        return Tensor(jnp.asarray(L), _internal=True)

    def log_prob(self, value):
        d = self.dim

        def f(L, eta):
            diag = jnp.diagonal(L, axis1=-2, axis2=-1)[..., 1:]
            orders = jnp.arange(2, d + 1, dtype=jnp.float32)
            unnorm = jnp.sum((d - orders + 2.0 * eta[..., None] - 2.0) * jnp.log(diag), -1)
            # normalizer (ref lkj_cholesky.py _log_normalizer)
            alpha = eta[..., None] + 0.5 * (d - orders)
            lognorm = jnp.sum(
                0.5 * (orders - 1) * np.log(np.pi)
                + jax.scipy.special.gammaln(alpha - 0.5 * (orders - 1))
                - jax.scipy.special.gammaln(alpha),
                -1,
            )
            return unnorm - lognorm

        return apply(f, value, self.concentration, op_name="lkj_log_prob")


class Binomial(Distribution):
    """ref: binomial.py — counts in n trials."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = _as_array(total_count, jnp.int32)
        self.probs = _as_array(probs)
        shape = np.broadcast_shapes(tuple(self.total_count.shape), tuple(self.probs.shape))
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return apply(lambda n, p: n * p, self.total_count, self.probs, op_name="binom_mean")

    @property
    def variance(self):
        return apply(lambda n, p: n * p * (1 - p), self.total_count, self.probs, op_name="binom_var")

    def sample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(n, p):
            return jax.random.binomial(key, n.astype(jnp.float32), p, shape=out_shape)

        out = apply(f, self.total_count, self.probs, op_name="binom_sample")
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        def f(v, n, p):
            n = n.astype(jnp.float32)
            comb = (jax.scipy.special.gammaln(n + 1)
                    - jax.scipy.special.gammaln(v + 1)
                    - jax.scipy.special.gammaln(n - v + 1))
            return comb + v * jnp.log(p) + (n - v) * jnp.log1p(-p)

        return apply(f, value, self.total_count, self.probs, op_name="binom_log_prob")

    def entropy(self):
        """Exact entropy by summing over the support (reference does the
        same O(n) sum)."""

        def f(n, p):
            nmax = int(np.asarray(jax.device_get(n)).max())
            k = jnp.arange(nmax + 1, dtype=jnp.float32)
            nf = n.astype(jnp.float32)[..., None]
            comb = (jax.scipy.special.gammaln(nf + 1)
                    - jax.scipy.special.gammaln(k + 1)
                    - jax.scipy.special.gammaln(nf - k + 1))
            logp = comb + k * jnp.log(p[..., None]) + (nf - k) * jnp.log1p(-p[..., None])
            valid = k <= nf
            pmf = jnp.where(valid, jnp.exp(logp), 0.0)
            return -jnp.sum(pmf * jnp.where(valid, logp, 0.0), -1)

        return apply(f, self.total_count, self.probs, op_name="binom_entropy")


class Poisson(Distribution):
    """ref: poisson.py."""

    def __init__(self, rate, name=None):
        self.rate = _as_array(rate)
        super().__init__(batch_shape=tuple(self.rate.shape))

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    def sample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(r):
            return jax.random.poisson(key, r, shape=out_shape).astype(jnp.float32)

        out = apply(f, self.rate, op_name="poisson_sample")
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        def f(v, r):
            return v * jnp.log(r) - r - jax.scipy.special.gammaln(v + 1)

        return apply(f, value, self.rate, op_name="poisson_log_prob")

    def entropy(self):
        """Truncated-support sum (the reference sums to a cutoff too)."""

        def f(r):
            nmax = int(np.asarray(jax.device_get(r)).max() * 10 + 30)
            k = jnp.arange(nmax, dtype=jnp.float32)
            logp = k * jnp.log(r[..., None]) - r[..., None] - jax.scipy.special.gammaln(k + 1)
            pmf = jnp.exp(logp)
            return -jnp.sum(pmf * logp, -1)

        return apply(f, self.rate, op_name="poisson_entropy")


class StudentT(Distribution):
    """ref: student_t.py."""

    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _as_array(df)
        self.loc = _as_array(loc)
        self.scale = _as_array(scale)
        shape = np.broadcast_shapes(
            tuple(self.df.shape), tuple(self.loc.shape), tuple(self.scale.shape)
        )
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        def f(df, m):
            return jnp.where(df > 1, m, jnp.nan)

        return apply(f, self.df, self.loc, op_name="t_mean")

    @property
    def variance(self):
        def f(df, s):
            v = s * s * df / (df - 2)
            return jnp.where(df > 2, v, jnp.where(df > 1, jnp.inf, jnp.nan))

        return apply(f, self.df, self.scale, op_name="t_var")

    def rsample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(df, m, s):
            return m + s * jax.random.t(key, df, out_shape, jnp.float32)

        return apply(f, self.df, self.loc, self.scale, op_name="t_rsample")

    def log_prob(self, value):
        def f(v, df, m, s):
            z = (v - m) / s
            return (jax.scipy.special.gammaln((df + 1) / 2)
                    - jax.scipy.special.gammaln(df / 2)
                    - 0.5 * jnp.log(df * np.pi) - jnp.log(s)
                    - (df + 1) / 2 * jnp.log1p(z * z / df))

        return apply(f, value, self.df, self.loc, self.scale, op_name="t_log_prob")

    def entropy(self):
        def f(df, s):
            half = (df + 1) / 2
            return (jnp.log(s) + 0.5 * jnp.log(df) + 0.5 * np.log(np.pi)
                    + jax.scipy.special.gammaln(df / 2) - jax.scipy.special.gammaln(half)
                    + half * (jax.scipy.special.digamma(half) - jax.scipy.special.digamma(df / 2)))

        return apply(f, self.df, self.scale, op_name="t_entropy")
