"""Exponential / Geometric / Gumbel / Laplace (ref: python/paddle/
distribution/{exponential,geometric,gumbel,laplace}.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base.tape import apply
from .distribution import Distribution, _as_array

__all__ = ["Exponential"]


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate_arr = _as_array(rate)
        super().__init__(batch_shape=self.rate_arr.shape)

    @property
    def rate(self):
        return self.rate_arr

    @property
    def mean(self):
        def f(r):
            return 1.0 / r

        return apply(f, self.rate_arr, op_name="exponential_mean")

    @property
    def variance(self):
        def f(r):
            return 1.0 / (r * r)

        return apply(f, self.rate_arr, op_name="exponential_var")

    def rsample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(r):
            return jax.random.exponential(key, out_shape, jnp.float32) / r

        return apply(f, self.rate_arr, op_name="exponential_rsample")

    def log_prob(self, value):
        def f(v, r):
            return jnp.log(r) - r * v

        return apply(f, value, self.rate_arr, op_name="exponential_log_prob")

    def entropy(self):
        def f(r):
            return 1.0 - jnp.log(r)

        return apply(f, self.rate_arr, op_name="exponential_entropy")

    def cdf(self, value):
        def f(v, r):
            return 1 - jnp.exp(-r * v)

        return apply(f, value, self.rate_arr, op_name="exponential_cdf")
