"""Normal / LogNormal (ref: python/paddle/distribution/normal.py:36,
lognormal.py:25)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base.tape import apply
from ..base.tensor import Tensor
from .distribution import Distribution, _as_array

__all__ = ["Normal", "LogNormal"]

_LOG_2PI = float(np.log(2.0 * np.pi))


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc_arr = _as_array(loc)
        self.scale_arr = _as_array(scale)
        shape = jnp.broadcast_shapes(tuple(self.loc_arr.shape), tuple(self.scale_arr.shape))
        super().__init__(batch_shape=shape)

    @property
    def loc(self):
        def f(l):
            return jnp.broadcast_to(l, self._batch_shape)

        return apply(f, self.loc_arr, op_name="normal_loc")

    mean = loc

    @property
    def scale(self):
        def f(s):
            return jnp.broadcast_to(s, self._batch_shape)

        return apply(f, self.scale_arr, op_name="normal_scale")

    @property
    def stddev(self):
        return self.scale

    @property
    def variance(self):
        def f(s):
            return jnp.broadcast_to(s * s, self._batch_shape)

        return apply(f, self.scale_arr, op_name="normal_var")

    def rsample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(loc, scale):
            eps = jax.random.normal(key, out_shape, jnp.float32)
            return loc + scale * eps

        return apply(f, self.loc_arr, self.scale_arr, op_name="normal_rsample")

    def log_prob(self, value):
        def f(v, loc, scale):
            var = scale * scale
            return -((v - loc) ** 2) / (2 * var) - jnp.log(scale) - 0.5 * _LOG_2PI

        return apply(f, value, self.loc_arr, self.scale_arr, op_name="normal_log_prob")

    def entropy(self):
        def f(scale):
            return jnp.broadcast_to(
                0.5 + 0.5 * _LOG_2PI + jnp.log(scale), self._batch_shape
            )

        return apply(f, self.scale_arr, op_name="normal_entropy")

    def cdf(self, value):
        def f(v, loc, scale):
            return 0.5 * (1 + jax.scipy.special.erf((v - loc) / (scale * np.sqrt(2.0))))

        return apply(f, value, self.loc_arr, self.scale_arr, op_name="normal_cdf")

    def icdf(self, value):
        def f(v, loc, scale):
            return loc + scale * jnp.sqrt(2.0) * jax.scipy.special.erfinv(2 * v - 1)

        return apply(f, value, self.loc_arr, self.scale_arr, op_name="normal_icdf")

    def kl_divergence(self, other):
        from .kl import kl_divergence

        return kl_divergence(self, other)


class LogNormal(Distribution):
    """exp(Normal(loc, scale)) (ref: lognormal.py — TransformedDistribution
    with ExpTransform, flattened here)."""

    def __init__(self, loc, scale, name=None):
        self._base = Normal(loc, scale)
        super().__init__(batch_shape=self._base._batch_shape)

    @property
    def mean(self):
        def f(loc, scale):
            return jnp.exp(loc + scale * scale / 2)

        return apply(f, self._base.loc_arr, self._base.scale_arr, op_name="lognormal_mean")

    @property
    def variance(self):
        def f(loc, scale):
            s2 = scale * scale
            return (jnp.exp(s2) - 1) * jnp.exp(2 * loc + s2)

        return apply(f, self._base.loc_arr, self._base.scale_arr, op_name="lognormal_var")

    def rsample(self, shape=()):
        base = self._base.rsample(shape)

        def f(x):
            return jnp.exp(x)

        return apply(f, base, op_name="exp")

    def log_prob(self, value):
        def f(v, loc, scale):
            logv = jnp.log(v)
            var = scale * scale
            return (
                -((logv - loc) ** 2) / (2 * var)
                - jnp.log(scale)
                - 0.5 * _LOG_2PI
                - logv
            )

        return apply(f, value, self._base.loc_arr, self._base.scale_arr,
                     op_name="lognormal_log_prob")

    def entropy(self):
        def f(loc, scale):
            return jnp.broadcast_to(
                0.5 + 0.5 * _LOG_2PI + jnp.log(scale) + loc, self._batch_shape
            )

        return apply(f, self._base.loc_arr, self._base.scale_arr,
                     op_name="lognormal_entropy")
