"""Beta (ref: python/paddle/distribution/beta.py:25)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import betaln, digamma

from ..base.tape import apply
from .distribution import Distribution, _as_array

__all__ = ["Beta"]


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha_arr = _as_array(alpha)
        self.beta_arr = _as_array(beta)
        shape = jnp.broadcast_shapes(tuple(self.alpha_arr.shape), tuple(self.beta_arr.shape))
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        def f(a, b):
            return a / (a + b)

        return apply(f, self.alpha_arr, self.beta_arr, op_name="beta_mean")

    @property
    def variance(self):
        def f(a, b):
            s = a + b
            return a * b / (s * s * (s + 1))

        return apply(f, self.alpha_arr, self.beta_arr, op_name="beta_var")

    def rsample(self, shape=()):
        key = self._next_key()
        k1, k2 = jax.random.split(key)
        out_shape = self._extend_shape(shape)

        def f(a, b):
            ga = jax.random.gamma(k1, jnp.broadcast_to(a, out_shape))
            gb = jax.random.gamma(k2, jnp.broadcast_to(b, out_shape))
            return ga / (ga + gb)

        return apply(f, self.alpha_arr, self.beta_arr, op_name="beta_rsample")

    sample = Distribution.sample

    def log_prob(self, value):
        def f(v, a, b):
            return (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - betaln(a, b)

        return apply(f, value, self.alpha_arr, self.beta_arr, op_name="beta_log_prob")

    def entropy(self):
        def f(a, b):
            s = a + b
            return (
                betaln(a, b)
                - (a - 1) * digamma(a)
                - (b - 1) * digamma(b)
                + (s - 2) * digamma(s)
            )

        return apply(f, self.alpha_arr, self.beta_arr, op_name="beta_entropy")
