"""Categorical (ref: python/paddle/distribution/categorical.py:35 —
logits-as-unnormalized-probs semantics preserved)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base.tape import apply
from .distribution import Distribution, _as_array

__all__ = ["Categorical"]


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        # paddle semantics: `logits` are unnormalized PROBABILITIES
        self.logits_arr = _as_array(logits)
        super().__init__(batch_shape=self.logits_arr.shape[:-1])
        self._n = self.logits_arr.shape[-1]

    def _probs(self, arr):
        return arr / jnp.sum(arr, axis=-1, keepdims=True)

    def sample(self, shape=()):
        key = self._next_key()
        out_shape = tuple(shape) + self._batch_shape

        def f(logits):
            logp = jnp.log(self._probs(logits))
            return jax.random.categorical(key, logp, shape=out_shape)

        out = apply(f, self.logits_arr, op_name="categorical_sample")
        out.stop_gradient = True
        return out

    def probs(self, value):
        def f(logits, v):
            p = self._probs(logits)
            return jnp.take_along_axis(p, v.astype(jnp.int32)[..., None], -1)[..., 0]

        return apply(f, self.logits_arr, value, op_name="categorical_probs")

    def log_prob(self, value):
        def f(logits, v):
            p = self._probs(logits)
            sel = jnp.take_along_axis(p, v.astype(jnp.int32)[..., None], -1)[..., 0]
            return jnp.log(sel)

        return apply(f, self.logits_arr, value, op_name="categorical_log_prob")

    def entropy(self):
        def f(logits):
            p = self._probs(logits)
            return -jnp.sum(p * jnp.log(p), axis=-1)

        return apply(f, self.logits_arr, op_name="categorical_entropy")

    def kl_divergence(self, other):
        from .kl import kl_divergence

        return kl_divergence(self, other)
