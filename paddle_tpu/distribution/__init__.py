"""paddle_tpu.distribution — probability distributions.

ref: python/paddle/distribution/ — distribution.py (Distribution base),
normal.py, uniform.py, bernoulli.py, categorical.py, beta.py,
dirichlet.py, exponential.py, gamma.py, geometric.py, gumbel.py,
laplace.py, lognormal.py, multinomial.py, kl.py (kl_divergence +
register_kl).

TPU-native: sampling draws keys from the framework generator and lowers
to jax.random (every sampler is jit-traceable); log_prob/entropy are
pure jnp through the tape, so they differentiate like any other op.
"""
from .distribution import Distribution  # noqa: F401
from .normal import LogNormal, Normal  # noqa: F401
from .uniform import Uniform  # noqa: F401
from .bernoulli import Bernoulli  # noqa: F401
from .categorical import Categorical  # noqa: F401
from .multinomial import Multinomial  # noqa: F401
from .beta import Beta  # noqa: F401
from .dirichlet import Dirichlet  # noqa: F401
from .gamma import Gamma  # noqa: F401
from .exponential import Exponential  # noqa: F401
from .geometric import Geometric  # noqa: F401
from .gumbel import Gumbel  # noqa: F401
from .laplace import Laplace  # noqa: F401
from .kl import kl_divergence, register_kl  # noqa: F401
from .extension import (  # noqa: F401
    Binomial,
    Cauchy,
    Chi2,
    ContinuousBernoulli,
    ExponentialFamily,
    Independent,
    LKJCholesky,
    MultivariateNormal,
    Poisson,
    StudentT,
    TransformedDistribution,
)

__all__ = [
    "Distribution", "Normal", "LogNormal", "Uniform", "Bernoulli",
    "Categorical", "Multinomial", "Beta", "Dirichlet", "Gamma",
    "Exponential", "Geometric", "Gumbel", "Laplace",
    "Cauchy", "Chi2", "ContinuousBernoulli", "ExponentialFamily",
    "MultivariateNormal", "Independent", "TransformedDistribution",
    "LKJCholesky", "Binomial", "Poisson", "StudentT",
    "kl_divergence", "register_kl",
]
