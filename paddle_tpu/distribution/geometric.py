"""Geometric (ref: python/paddle/distribution/geometric.py:30 — counts
failures before first success, support {0, 1, 2, ...})."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base.tape import apply
from .distribution import Distribution, _as_array

__all__ = ["Geometric"]


class Geometric(Distribution):
    def __init__(self, probs, name=None):
        self.probs_arr = _as_array(probs)
        super().__init__(batch_shape=self.probs_arr.shape)

    @property
    def mean(self):
        def f(p):
            return (1 - p) / p

        return apply(f, self.probs_arr, op_name="geometric_mean")

    @property
    def variance(self):
        def f(p):
            return (1 - p) / (p * p)

        return apply(f, self.probs_arr, op_name="geometric_var")

    @property
    def stddev(self):
        def f(p):
            return jnp.sqrt((1 - p) / (p * p))

        return apply(f, self.probs_arr, op_name="geometric_std")

    def sample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(p):
            u = jax.random.uniform(key, out_shape, jnp.float32, 1e-7, 1.0)
            return jnp.floor(jnp.log(u) / jnp.log1p(-p))

        out = apply(f, self.probs_arr, op_name="geometric_sample")
        out.stop_gradient = True
        return out

    rsample = sample

    def pmf(self, k):
        def f(k_, p):
            return p * (1 - p) ** k_

        return apply(f, k, self.probs_arr, op_name="geometric_pmf")

    def log_pmf(self, k):
        def f(k_, p):
            return jnp.log(p) + k_ * jnp.log1p(-p)

        return apply(f, k, self.probs_arr, op_name="geometric_log_pmf")

    log_prob = log_pmf

    def entropy(self):
        def f(p):
            q = 1 - p
            return -(q * jnp.log(q) + p * jnp.log(p)) / p

        return apply(f, self.probs_arr, op_name="geometric_entropy")

    def cdf(self, k):
        def f(k_, p):
            return 1 - (1 - p) ** (k_ + 1)

        return apply(f, k, self.probs_arr, op_name="geometric_cdf")
