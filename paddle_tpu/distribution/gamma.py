"""Gamma (ref: python/paddle/distribution/gamma.py:25)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import digamma, gammaln

from ..base.tape import apply
from .distribution import Distribution, _as_array

__all__ = ["Gamma"]


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.conc_arr = _as_array(concentration)
        self.rate_arr = _as_array(rate)
        shape = jnp.broadcast_shapes(tuple(self.conc_arr.shape), tuple(self.rate_arr.shape))
        super().__init__(batch_shape=shape)

    @property
    def concentration(self):
        return self.conc_arr

    @property
    def rate(self):
        return self.rate_arr

    @property
    def mean(self):
        def f(a, b):
            return a / b

        return apply(f, self.conc_arr, self.rate_arr, op_name="gamma_mean")

    @property
    def variance(self):
        def f(a, b):
            return a / (b * b)

        return apply(f, self.conc_arr, self.rate_arr, op_name="gamma_var")

    def rsample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(a, b):
            g = jax.random.gamma(key, jnp.broadcast_to(a, out_shape))
            return g / b

        return apply(f, self.conc_arr, self.rate_arr, op_name="gamma_rsample")

    def log_prob(self, value):
        def f(v, a, b):
            return a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v - gammaln(a)

        return apply(f, value, self.conc_arr, self.rate_arr, op_name="gamma_log_prob")

    def entropy(self):
        def f(a, b):
            return a - jnp.log(b) + gammaln(a) + (1 - a) * digamma(a)

        return apply(f, self.conc_arr, self.rate_arr, op_name="gamma_entropy")
