"""kl_divergence + register_kl dispatch (ref: python/paddle/
distribution/kl.py:33 — same double-dispatch registry resolving the
most specific (type(p), type(q)) pair)."""
from __future__ import annotations

from typing import Callable, Dict, Tuple, Type

import jax.numpy as jnp

from ..base.tape import apply
from .bernoulli import Bernoulli
from .beta import Beta
from .categorical import Categorical
from .dirichlet import Dirichlet
from .distribution import Distribution
from .exponential import Exponential
from .gamma import Gamma
from .geometric import Geometric
from .laplace import Laplace
from .normal import LogNormal, Normal
from .uniform import Uniform

__all__ = ["kl_divergence", "register_kl"]

_REGISTRY: Dict[Tuple[Type, Type], Callable] = {}


def register_kl(p_cls: Type, q_cls: Type):
    """Decorator registering a KL implementation (ref: kl.py register_kl)."""

    def wrap(fn):
        _REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return wrap


def kl_divergence(p: Distribution, q: Distribution):
    best, match = None, None
    for (pc, qc), fn in _REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            # most specific match wins (mro distance)
            score = type(p).__mro__.index(pc) + type(q).__mro__.index(qc)
            if best is None or score < best:
                best, match = score, fn
    if match is None:
        raise NotImplementedError(
            f"no KL registered for ({type(p).__name__}, {type(q).__name__})"
        )
    return match(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    def f(pl, ps, ql, qs):
        var_ratio = (ps / qs) ** 2
        t1 = ((pl - ql) / qs) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))

    return apply(f, p.loc_arr, p.scale_arr, q.loc_arr, q.scale_arr, op_name="kl_normal")


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    def f(pl, ph, ql, qh):
        res = jnp.log((qh - ql) / (ph - pl))
        return jnp.where((ql <= pl) & (ph <= qh), res, jnp.inf)

    return apply(f, p.low_arr, p.high_arr, q.low_arr, q.high_arr, op_name="kl_uniform")


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    def f(pp, qp):
        return pp * (jnp.log(pp) - jnp.log(qp)) + (1 - pp) * (
            jnp.log1p(-pp) - jnp.log1p(-qp)
        )

    return apply(f, p.probs_arr, q.probs_arr, op_name="kl_bernoulli")


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    def f(pa, qa):
        pn = pa / jnp.sum(pa, -1, keepdims=True)
        qn = qa / jnp.sum(qa, -1, keepdims=True)
        return jnp.sum(pn * (jnp.log(pn) - jnp.log(qn)), -1)

    return apply(f, p.logits_arr, q.logits_arr, op_name="kl_categorical")


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    from jax.scipy.special import digamma, gammaln

    def f(pa, qa):
        p0 = jnp.sum(pa, -1)
        return (
            gammaln(p0)
            - jnp.sum(gammaln(pa), -1)
            - gammaln(jnp.sum(qa, -1))
            + jnp.sum(gammaln(qa), -1)
            + jnp.sum((pa - qa) * (digamma(pa) - digamma(p0)[..., None]), -1)
        )

    return apply(f, p.conc_arr, q.conc_arr, op_name="kl_dirichlet")


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    from jax.scipy.special import betaln, digamma

    def f(pa, pb, qa, qb):
        s = pa + pb
        return (
            betaln(qa, qb)
            - betaln(pa, pb)
            + (pa - qa) * digamma(pa)
            + (pb - qb) * digamma(pb)
            + (qa - pa + qb - pb) * digamma(s)
        )

    return apply(f, p.alpha_arr, p.beta_arr, q.alpha_arr, q.beta_arr, op_name="kl_beta")


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    from jax.scipy.special import digamma, gammaln

    def f(pa, pb, qa, qb):
        return (
            (pa - qa) * digamma(pa)
            - gammaln(pa)
            + gammaln(qa)
            + qa * (jnp.log(pb) - jnp.log(qb))
            + pa * (qb / pb - 1)
        )

    return apply(f, p.conc_arr, p.rate_arr, q.conc_arr, q.rate_arr, op_name="kl_gamma")


@register_kl(Exponential, Exponential)
def _kl_exponential_exponential(p, q):
    def f(pr, qr):
        ratio = qr / pr
        return jnp.log(pr) - jnp.log(qr) + ratio - 1

    return apply(f, p.rate_arr, q.rate_arr, op_name="kl_exponential")


@register_kl(Geometric, Geometric)
def _kl_geometric_geometric(p, q):
    def f(pp, qp):
        return (
            jnp.log(pp)
            - jnp.log(qp)
            + (1 - pp) / pp * (jnp.log1p(-pp) - jnp.log1p(-qp))
        )

    return apply(f, p.probs_arr, q.probs_arr, op_name="kl_geometric")


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    def f(pl, ps, ql, qs):
        d = jnp.abs(pl - ql)
        return (
            jnp.log(qs)
            - jnp.log(ps)
            + (ps * jnp.exp(-d / ps) + d) / qs
            - 1
        )

    return apply(f, p.loc_arr, p.scale_arr, q.loc_arr, q.scale_arr, op_name="kl_laplace")


@register_kl(LogNormal, LogNormal)
def _kl_lognormal_lognormal(p, q):
    return _kl_normal_normal(p._base, q._base)
