"""The Tensor facade.

TPU-native counterpart of the reference's eager Tensor
(ref: paddle/fluid/pybind/eager.cc + python/paddle/base/dygraph/
tensor_patch_methods.py). Wraps an immutable ``jax.Array`` plus autograd
metadata (``stop_gradient``, ``.grad``, tape edge). "In-place" methods
rebind the underlying array — sound because saved vjp residuals hold the
old immutable value, which eliminates the reference's tensor version
counter machinery (TensorWrapper, ref: fluid/eager/tensor_wrapper.h).

Registered as a jax pytree node so Tensors flow through jit/shard_map
boundaries (paddle_tpu.jit functionalization relies on this).
"""
from __future__ import annotations

import itertools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import tree_util

from . import dtype as dtypes
from . import tape as _tape
from .device import Place, get_place

_tensor_counter = itertools.count()


class Tensor:
    __slots__ = (
        "_data",
        "stop_gradient",
        "_grad",
        "_grad_node",
        "_out_index",
        "_grad_hooks",
        "_retain_grads",
        "_consumer_nodes",
        "name",
        "persistable",
        "_dist_attr",
        "_piecewise_carry",
        "__weakref__",
    )

    def __init__(
        self,
        data: Any = None,
        dtype=None,
        place: Optional[Place] = None,
        stop_gradient: bool = True,
        name: Optional[str] = None,
        persistable: bool = False,
        _internal: bool = False,
    ):
        if isinstance(data, Tensor):
            data = data._data
        if data is None:
            data = jnp.zeros((), dtypes.get_default_dtype())
        if not _internal or not isinstance(data, (jax.Array, np.ndarray)):
            dt = dtypes.canonical_dtype(dtype) if dtype is not None else None
            if dt is None and isinstance(data, (float,)):
                dt = dtypes.get_default_dtype()
            if dt is None and isinstance(data, (list, tuple)):
                probe = np.asarray(data)
                if probe.dtype == np.float64:
                    dt = dtypes.get_default_dtype()
            data = jnp.asarray(data, dtype=dt)
        elif dtype is not None:
            dt = dtypes.canonical_dtype(dtype)
            if np.result_type(data) != dt:
                data = jnp.asarray(data, dtype=dt)
        self._data = data
        self.stop_gradient = stop_gradient
        self._grad = None
        self._grad_node = None
        self._out_index = 0
        self._grad_hooks = []
        self._retain_grads = False
        self._consumer_nodes = []  # weakrefs to TapeNodes that consumed self
        self.name = name or f"tensor_{next(_tensor_counter)}"
        self.persistable = persistable
        self._dist_attr = None

    # ------------------------------------------------------------------
    # metadata
    # ------------------------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self) -> int:
        return self._data.ndim

    dim = lambda self: self._data.ndim  # noqa: E731 paddle method form
    rank = lambda self: self._data.ndim  # noqa: E731

    @property
    def dtype(self) -> np.dtype:
        return np.dtype(self._data.dtype)

    @property
    def size(self) -> int:
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    def numel(self) -> int:
        return self.size

    @property
    def place(self) -> Place:
        return get_place()

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    def is_dist(self) -> bool:
        return self._dist_attr is not None

    @property
    def dist_attr(self):
        return self._dist_attr

    @property
    def process_mesh(self):
        """ProcessMesh for DistTensors (ref: dist_tensor.h process_mesh);
        None for ordinary tensors."""
        return self._dist_attr["mesh"] if self._dist_attr else None

    @property
    def placements(self):
        """Per-mesh-axis placements for DistTensors (ref:
        dist_tensor.h placements); None for ordinary tensors."""
        return self._dist_attr["placements"] if self._dist_attr else None

    # ------------------------------------------------------------------
    # autograd surface
    # ------------------------------------------------------------------
    @property
    def grad(self) -> Optional["Tensor"]:
        return self._grad

    @grad.setter
    def grad(self, value):
        if value is not None and not isinstance(value, Tensor):
            value = Tensor(value)
        self._grad = value

    def backward(self, grad_tensor: Optional["Tensor"] = None, retain_graph: bool = False):
        _tape.run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        return Tensor(self._data, stop_gradient=True, _internal=True)

    def detach_(self) -> "Tensor":
        self._grad_node = None
        self.stop_gradient = True
        return self

    def retain_grads(self):
        self._retain_grads = True

    def register_hook(self, hook):
        """Grad hook (ref: tensor_patch_methods.py register_hook). Returns a
        removable handle."""
        self._grad_hooks.append(hook)

        class _Handle:
            def remove(_self):
                if hook in self._grad_hooks:
                    self._grad_hooks.remove(hook)

        return _Handle()

    # ------------------------------------------------------------------
    # conversion
    # ------------------------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._data)

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __jax_array__(self):
        return self._data

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        return bool(self.numpy())

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __hash__(self):
        return id(self)

    # ------------------------------------------------------------------
    # device / dtype movement
    # ------------------------------------------------------------------
    def astype(self, dtype) -> "Tensor":
        from . import tape

        dt = dtypes.canonical_dtype(dtype)
        return tape.apply(lambda x: x.astype(dt), self, op_name="cast")

    cast = astype

    def to(self, *args, **kwargs) -> "Tensor":
        """tensor.to(dtype) / to(device) / to(device, dtype) parity."""
        dtype = kwargs.get("dtype")
        for a in args:
            if isinstance(a, (str, np.dtype, type)):
                try:
                    dtype = dtypes.convert_dtype(a)
                    continue
                except TypeError:
                    pass  # it's a device string
        if dtype is not None:
            return self.astype(dtype)
        return self

    def cpu(self) -> "Tensor":
        return Tensor(np.asarray(self._data), stop_gradient=self.stop_gradient)

    def tpu(self) -> "Tensor":
        return self

    cuda = tpu  # parity shim

    def pin_memory(self) -> "Tensor":
        return self

    def clone(self) -> "Tensor":
        from . import tape

        return tape.apply(lambda x: x + 0, self, op_name="clone")

    def contiguous(self) -> "Tensor":
        return self

    def is_contiguous(self) -> bool:
        return True

    # ------------------------------------------------------------------
    # in-place helpers
    # ------------------------------------------------------------------
    def _inplace_from(self, result: "Tensor") -> "Tensor":
        """Adopt result's value+tape edge (functional in-place).

        Any tape node that consumed the *pre-mutation* value — including
        the node producing ``result`` itself (``y += 1``) — must keep an
        edge to that value, or cotangents arriving from earlier consumers
        would be routed to the post-mutation node and silently dropped
        (the reference guards this with a tensor inplace-version counter,
        ref: fluid/eager/tensor_wrapper.h). We snapshot the old value and
        swap ``self``→``snapshot`` in every live consumer's input list;
        if the pre-mutation tensor was a differentiable leaf, a grad hook
        on the snapshot routes its accumulated grad back to ``self.grad``.
        """
        node = result._grad_node
        if self._consumer_nodes and (node is not None or not self.stop_gradient):
            snapshot = Tensor(self._data, stop_gradient=self.stop_gradient, _internal=True)
            snapshot._grad_node = self._grad_node
            snapshot._out_index = self._out_index
            snapshot._consumer_nodes = self._consumer_nodes
            self._consumer_nodes = []
            for ref in snapshot._consumer_nodes:
                n = ref()
                if n is not None and any(inp is self for inp in n.inputs):
                    n.inputs = tuple(
                        snapshot if inp is self else inp for inp in n.inputs
                    )
            if snapshot._grad_node is None and not snapshot.stop_gradient:
                owner = self

                def _route_leaf_grad(g, _owner=owner):
                    _owner._grad = g if _owner._grad is None else _owner._grad + g
                    return None

                snapshot._grad_hooks = list(self._grad_hooks) + [_route_leaf_grad]
        elif node is not None and any(inp is self for inp in node.inputs):
            # no earlier consumers: just break the self-loop
            snapshot = Tensor(self._data, stop_gradient=self.stop_gradient, _internal=True)
            snapshot._grad_node = self._grad_node
            snapshot._out_index = self._out_index
            node.inputs = tuple(
                snapshot if inp is self else inp for inp in node.inputs
            )
        self._data = result._data
        self._grad_node = result._grad_node
        self._out_index = result._out_index
        if node is not None:
            self._consumer_nodes = []
        self.stop_gradient = result.stop_gradient and self.stop_gradient
        return self

    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._data
        # jnp.array copies (asarray would alias — fatal once jit donates
        # the source buffer: the alias would be deleted with it)
        self._data = jnp.array(value, dtype=self._data.dtype, copy=True)
        return self

    def copy_(self, other, *_):
        return self.set_value(other)

    def fill_(self, value):
        self._data = jnp.full_like(self._data, value)
        return self

    def zero_(self):
        return self.fill_(0)

    # ------------------------------------------------------------------
    # misc
    # ------------------------------------------------------------------
    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        try:
            val = np.asarray(self._data)
            body = np.array2string(val, precision=6, suppress_small=True, threshold=64)
        except Exception:
            body = f"<traced {self._data}>"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}{grad_info},\n"
            f"       {body})"
        )

    __str__ = __repr__


# ---------------------------------------------------------------------------
# pytree registration: Tensors flow through jax.jit / shard_map / tree_map.
# aux carries stop_gradient so round-tripping preserves trainability.
# ---------------------------------------------------------------------------


def _tensor_flatten(t: Tensor):
    return (t._data,), (t.stop_gradient, t.name)


def _tensor_unflatten(aux, children):
    stop_gradient, name = aux
    out = Tensor(children[0], stop_gradient=stop_gradient, name=name, _internal=True)
    return out


tree_util.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """paddle.to_tensor parity (ref: python/paddle/tensor/creation.py)."""
    if isinstance(data, Tensor) and dtype is None:
        t = Tensor(data._data, stop_gradient=stop_gradient, _internal=True)
        return t
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)
