"""Device / Place abstraction.

The reference's Place hierarchy (ref: paddle/phi/common/place.h:135) routes
kernels between CPU/GPU/XPU. On TPU via JAX there is one accelerator type
and XLA owns streams, so Place collapses to a thin wrapper over
``jax.Device`` used for API parity (``paddle.set_device`` /
``tensor.place``). No user-visible streams exist (TPU has no user streams;
XLA async dispatch replaces them) — the stream/event API in
``paddle_tpu.device`` is a documented no-op.
"""
from __future__ import annotations

import functools

import jax


class Place:
    """Base place. Compares by (kind, index)."""

    kind = "undefined"

    def __init__(self, index: int = 0):
        self.index = index

    # -- identity ---------------------------------------------------------
    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.kind == other.kind
            and self.index == other.index
        )

    def __hash__(self):
        return hash((self.kind, self.index))

    def __repr__(self):
        return f"Place({self.kind}:{self.index})"

    # -- mapping to jax ---------------------------------------------------
    def jax_device(self):
        devs = [d for d in jax.devices() if _kind_of(d) == self.kind]
        if not devs:
            # Fall back to default backend (e.g. asking for TPU on a CPU-only
            # test host): mirrors the reference's backend fallback rules
            # (ref: paddle/phi/core/kernel_factory.h fallback to CPU).
            devs = jax.devices()
        return devs[self.index % len(devs)]


class CPUPlace(Place):
    kind = "cpu"


class TPUPlace(Place):
    """The TPU analogue of GPUPlace (ref: paddle/phi/common/place.h:135)."""

    kind = "tpu"


class CUDAPlace(TPUPlace):
    """Compat alias: code written against the reference's CUDAPlace maps to
    the accelerator place on TPU."""


class CUDAPinnedPlace(CPUPlace):
    """Compat alias: pinned host memory has no TPU analogue (transfers
    stage through the PJRT host buffer); behaves as CPUPlace."""


def _kind_of(d: jax.Device) -> str:
    plat = d.platform
    if plat in ("tpu", "axon"):
        return "tpu"
    if plat in ("cpu",):
        return "cpu"
    return plat


_current_device = [None]  # type: list


def set_device(device) -> Place:
    """paddle.set_device parity (ref: python/paddle/device/__init__.py).

    Accepts 'tpu', 'tpu:0', 'cpu', 'gpu' (alias of tpu), or a Place.
    """
    place = _parse_place(device)
    _current_device[0] = place
    return place


def get_device() -> str:
    p = get_place()
    return f"{p.kind}:{p.index}"


def get_place() -> Place:
    if _current_device[0] is None:
        _current_device[0] = _default_place()
    return _current_device[0]


@functools.lru_cache(maxsize=None)
def _accelerator_available() -> bool:
    return any(_kind_of(d) == "tpu" for d in jax.devices())


def _default_place() -> Place:
    return TPUPlace(0) if _accelerator_available() else CPUPlace(0)


def _parse_place(device) -> Place:
    if isinstance(device, Place):
        return device
    if isinstance(device, jax.Device):
        return (TPUPlace if _kind_of(device) == "tpu" else CPUPlace)(device.id)
    s = str(device).lower()
    idx = 0
    if ":" in s:
        s, i = s.split(":", 1)
        idx = int(i)
    if s in ("tpu", "gpu", "cuda", "xpu", "npu"):
        return TPUPlace(idx)
    if s == "cpu":
        return CPUPlace(idx)
    raise ValueError(f"unknown device {device!r}")


def device_count() -> int:
    return len(jax.local_devices())


def is_compiled_with_cuda() -> bool:  # parity shim
    return False


def is_compiled_with_tpu() -> bool:
    return _accelerator_available()
