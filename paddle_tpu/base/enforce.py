"""Error enforcement machinery.

ref: paddle/common/enforce.h (PADDLE_ENFORCE_* macros, error codes
paddle/common/errors.h / phi/core/errors.h) and
python/paddle/base/error.py. The reference attaches a typed error code
(InvalidArgument, NotFound, OutOfRange, …) + call-site summary to every
check; Python surfaces them as typed exceptions. Here the same
taxonomy maps onto Python exception subclasses so user code can catch
by category, and ``enforce``/check helpers give ops one-line guards
with consistent messages.
"""
from __future__ import annotations

from typing import Any, Sequence

__all__ = [
    "EnforceNotMet", "InvalidArgumentError", "NotFoundError",
    "OutOfRangeError", "AlreadyExistsError", "PermissionDeniedError",
    "UnimplementedError", "UnavailableError", "PreconditionNotMetError",
    "ExecutionTimeoutError", "enforce", "check_type", "check_dtype",
    "check_shape_match",
]


class EnforceNotMet(RuntimeError):
    """Base of all enforcement failures (ref: enforce.h EnforceNotMet)."""

    code = "LEGACY"


class InvalidArgumentError(EnforceNotMet, ValueError):
    code = "INVALID_ARGUMENT"


class NotFoundError(EnforceNotMet, KeyError):
    code = "NOT_FOUND"


class OutOfRangeError(EnforceNotMet, IndexError):
    code = "OUT_OF_RANGE"


class AlreadyExistsError(EnforceNotMet):
    code = "ALREADY_EXISTS"


class PermissionDeniedError(EnforceNotMet):
    code = "PERMISSION_DENIED"


class UnimplementedError(EnforceNotMet, NotImplementedError):
    code = "UNIMPLEMENTED"


class UnavailableError(EnforceNotMet):
    code = "UNAVAILABLE"


class PreconditionNotMetError(EnforceNotMet):
    code = "PRECONDITION_NOT_MET"


class ExecutionTimeoutError(EnforceNotMet, TimeoutError):
    code = "EXECUTION_TIMEOUT"


def enforce(cond: Any, message: str,
            exc: type = InvalidArgumentError):
    """PADDLE_ENFORCE parity: raise ``exc`` with ``message`` unless
    ``cond`` is truthy."""
    if not cond:
        raise exc(f"[{exc.code}] {message}")


def check_type(value, name: str, expected_types, op_name: str = ""):
    """ref: python/paddle/base/data_feeder.py check_type."""
    if not isinstance(value, expected_types):
        names = (
            expected_types.__name__
            if isinstance(expected_types, type)
            else "/".join(t.__name__ for t in expected_types)
        )
        raise InvalidArgumentError(
            f"[INVALID_ARGUMENT] {op_name or 'op'}: argument '{name}' must "
            f"be {names}, got {type(value).__name__}"
        )


def check_dtype(dtype, name: str, allowed: Sequence[str], op_name: str = ""):
    """ref: data_feeder.py check_dtype — dtype whitelist per op."""
    import numpy as np

    from . import dtype as _dtypes

    dt = _dtypes.canonical_dtype(dtype)
    allowed_np = [np.dtype(_dtypes.canonical_dtype(a)) for a in allowed]
    if np.dtype(dt) not in allowed_np:
        raise InvalidArgumentError(
            f"[INVALID_ARGUMENT] {op_name or 'op'}: argument '{name}' dtype "
            f"{_dtypes.dtype_name(dt)} not in allowed set {list(allowed)}"
        )


def check_shape_match(shape_a, shape_b, name_a: str, name_b: str,
                      op_name: str = ""):
    """InferMeta-style broadcast-compatibility check (ref:
    phi/infermeta/binary.cc patterns) — catches shape errors with op
    context instead of a raw XLA error."""
    a, b = tuple(shape_a), tuple(shape_b)
    for da, db in zip(a[::-1], b[::-1]):
        if da != db and da != 1 and db != 1:
            raise InvalidArgumentError(
                f"[INVALID_ARGUMENT] {op_name or 'op'}: shapes of '{name_a}' "
                f"{list(a)} and '{name_b}' {list(b)} are not "
                "broadcast-compatible"
            )
