"""RNG state management.

TPU-native design: the reference keeps per-device Philox ``Generator``
states (ref: paddle/phi/core/generator.h) and, for model parallelism, a
named-seed ``RNGStatesTracker`` (ref:
python/paddle/distributed/fleet/layers/mpu/random.py:34) so dropout differs
across TP ranks but matches across DP ranks.

Here a ``Generator`` owns a JAX PRNG key that is *split* on every draw.
Because jax arrays are immutable the state is a value, which makes the
generator safe both eagerly and inside a jit trace: the functionalized
train step (paddle_tpu.jit) threads the key through the step state, so
compiled steps get fresh randomness each call, exactly like the
reference's stateful Philox offset.
"""
from __future__ import annotations

import contextlib
import zlib
from typing import Dict

import jax
import numpy as np


class Generator:
    """Splittable PRNG state (Philox-state parity: seed + evolving key)."""

    def __init__(self, seed: int = 0):
        self._seed = int(seed)
        # key creation is LAZY: building a jax PRNG key initializes the
        # XLA backend, and this module is imported by `import paddle_tpu`
        # — which must stay backend-free so multi-controller workers can
        # call jax.distributed.initialize after import
        # (multi_controller.initialize_from_env)
        self._key = None

    @property
    def _k(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)
        return self._key

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        # stay lazy: paddle.seed() at the top of a multi-controller
        # worker must not initialize the backend before
        # jax.distributed.initialize (same invariant as __init__)
        self._key = None
        return self

    def initial_seed(self) -> int:
        return self._seed

    # -- state (for checkpoint / tracker swap) ----------------------------
    def get_state(self):
        return self._k

    def set_state(self, state):
        self._key = state

    # -- drawing ----------------------------------------------------------
    def split(self):
        """Return a fresh subkey, advancing the generator state."""
        self._key, sub = jax.random.split(self._k)
        return sub

    def split_n(self, n: int):
        keys = jax.random.split(self._k, n + 1)
        self._key = keys[0]
        return keys[1:]


_default_generator = Generator(np.random.randint(0, 2**31 - 1))


def default_generator() -> Generator:
    return _default_generator


def seed(value: int) -> Generator:
    """paddle.seed parity (ref: python/paddle/framework/random.py)."""
    _default_generator.manual_seed(value)
    _tracker.reset()
    return _default_generator


def get_rng_state():
    return {"default": _default_generator.get_state(), "tracker": _tracker.get_states_dict()}


def set_rng_state(state):
    _default_generator.set_state(state["default"])
    _tracker.set_states_dict(state["tracker"])


def next_key():
    """Fresh subkey from the default generator (internal op plumbing)."""
    return _default_generator.split()


# -- checkpointable RNG state ------------------------------------------------
# jax typed PRNG keys (key<fry> dtype) cannot pass through np.asarray, so
# checkpoint writers (AutoCheckpoint, the training supervisor's peer
# snapshots) lower them to plain uint32 arrays first. The tag dict keeps
# the encoded form self-describing inside a pickled state tree.
_KEY_TAG = "__paddle_tpu_prng_key__"


def _encode_key(key):
    if isinstance(key, dict) and key.get(_KEY_TAG) == 1:
        return key  # already encoded (encoding is idempotent)
    key_data = getattr(jax.random, "key_data", None)
    raw = key_data(key) if key_data is not None else key
    return {_KEY_TAG: 1, "data": np.asarray(jax.device_get(raw))}


def _decode_key(enc):
    if not (isinstance(enc, dict) and enc.get(_KEY_TAG) == 1):
        return enc  # already a live key (in-memory snapshot path)
    wrap = getattr(jax.random, "wrap_key_data", None)
    data = jax.numpy.asarray(enc["data"])
    # old jax without typed keys: the raw uint32 array IS the key
    return wrap(data) if wrap is not None else data


def encode_rng_state(state):
    """Lower a :func:`get_rng_state`-shaped dict's PRNG keys to plain
    numpy payloads — safe to pickle/``framework.io.save`` and to ship
    across processes (peer-replicated snapshots)."""
    return {
        "default": _encode_key(state["default"]),
        "tracker": {k: _encode_key(v)
                    for k, v in state["tracker"].items()},
    }


def serializable_rng_state():
    """:func:`encode_rng_state` of the CURRENT global RNG state."""
    return encode_rng_state(get_rng_state())


def restore_rng_state(state):
    """Inverse of :func:`serializable_rng_state`; also accepts a live
    :func:`get_rng_state` dict (keys pass through untouched)."""
    set_rng_state({
        "default": _decode_key(state["default"]),
        "tracker": {k: _decode_key(v)
                    for k, v in state["tracker"].items()},
    })


class RNGStatesTracker:
    """Named RNG branches for hybrid parallelism.

    ref: fleet/layers/mpu/random.py:34 — `global_seed` shared across all
    ranks, `local_seed` unique per TP rank so dropout masks decorrelate
    inside a tensor-parallel group while weights stay identical.
    """

    GLOBAL = "global_seed"
    LOCAL = "local_seed"

    def __init__(self):
        self._states: Dict[str, Generator] = {}

    def reset(self):
        self._states.clear()

    def add(self, name: str, seed: int):
        if name in self._states:
            raise ValueError(f"rng state {name!r} already exists")
        self._states[name] = Generator(seed)

    def exists(self, name: str) -> bool:
        return name in self._states

    @contextlib.contextmanager
    def rng_state(self, name: str = GLOBAL):
        """Swap the default generator for the named branch inside the ctx."""
        global _default_generator
        if name not in self._states:
            # lazily branch off the default seed, folding in a deterministic
            # digest of the name — hash() is randomized per process
            # (PYTHONHASHSEED) and would silently desynchronize the
            # documented cross-rank invariant of the global branch
            self._states[name] = Generator(
                (_default_generator.initial_seed() + zlib.crc32(name.encode()))
                % 2**31
            )
        prev = _default_generator
        _default_generator = self._states[name]
        try:
            yield
        finally:
            _default_generator = prev

    def get_states_dict(self):
        return {k: g.get_state() for k, g in self._states.items()}

    def set_states_dict(self, states):
        for k, v in states.items():
            if k not in self._states:
                self._states[k] = Generator(0)
            self._states[k].set_state(v)


_tracker = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _tracker


def model_parallel_random_seed(seed_: int, tp_rank: int = 0):
    """ref: fleet/layers/mpu/random.py:103 — seed global branch identically
    on every rank, local branch offset by TP rank."""
    _tracker.reset()
    _tracker.add(RNGStatesTracker.GLOBAL, seed_)
    _tracker.add(RNGStatesTracker.LOCAL, seed_ + 2718 + tp_rank)
