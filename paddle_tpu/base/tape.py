"""Eager autograd tape.

TPU-native redesign of the reference's dygraph autograd engine
(GradNodeBase graph + RunBackward, ref: paddle/fluid/eager/backward.cc:105,
grad_node_info.h:197). Instead of per-op hand-written grad kernels, every
op records a ``jax.vjp`` closure on a tape. Because jax arrays are
immutable values, this tape works identically in two regimes:

- **eager**: ops execute immediately on device; ``loss.backward()`` walks
  the tape calling the stored vjp closures (each is itself jax-traceable).
- **inside a jit trace** (paddle_tpu.jit): the same Python code runs on
  tracers; the tape composes vjp closures symbolically and XLA fuses the
  whole forward+backward into one program — this is how the framework gets
  "dygraph UX, static-graph performance" without a bespoke IR (the
  reference needed PIR + SOT for this; here jaxpr is the IR).

Topological ordering uses monotone node ids: inputs are always created
before outputs, so descending-id order is a valid reverse-topological
order (replaces the in-degree BFS of backward.cc:23).
"""
from __future__ import annotations

import contextlib
import itertools
import threading
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax import tree_util

from . import amp_state
from . import dtype as dtypes
from .flags import flag

_node_counter = itertools.count()


class _GradState(threading.local):
    def __init__(self):
        self.enabled = True


_grad_state = _GradState()


def is_grad_enabled() -> bool:
    return _grad_state.enabled


class set_grad_enabled(contextlib.ContextDecorator):
    """paddle.set_grad_enabled parity; usable as ctx manager or decorator."""

    def __init__(self, mode: bool):
        self.mode = bool(mode)
        self.prev = None

    def __enter__(self):
        self.prev = _grad_state.enabled
        _grad_state.enabled = self.mode
        return self

    def __exit__(self, *exc):
        _grad_state.enabled = self.prev
        return False


class no_grad(set_grad_enabled):
    """paddle.no_grad parity (ref: python/paddle/base/dygraph/base.py)."""

    def __init__(self):
        super().__init__(False)


class enable_grad(set_grad_enabled):
    def __init__(self):
        super().__init__(True)


class TapeNode:
    """One recorded op: a vjp closure + edges to its differentiable inputs.

    Mirrors GradNodeBase (ref: fluid/eager/grad_node_info.h:197): ``inputs``
    are the Edges, ``vjp_fn`` is ``operator()``, out_avals/out_treedef
    describe the forward outputs so missing cotangents can be zero-filled
    (GradTensorHolder's job in the reference).
    """

    __slots__ = (
        "id",
        "name",
        "vjp_fn",
        "fwd_fn",
        "inputs",
        "out_avals",
        "out_treedef",
        "__weakref__",
    )

    def __init__(self, vjp_fn, inputs, out_avals, out_treedef, name="", fwd_fn=None):
        self.id = next(_node_counter)
        self.name = name
        self.vjp_fn = vjp_fn
        # fwd_fn: closure over the op's constants taking the diff primals;
        # used under create_graph to re-derive the vjp as an explicit
        # function of (cotangents, primals) so double-grad sees the edge.
        self.fwd_fn = fwd_fn
        self.inputs = inputs  # tuple of Tensors (strong refs, like TensorWrapper)
        self.out_avals = out_avals  # list[(shape, dtype)]
        self.out_treedef = out_treedef

    def __repr__(self):
        return f"TapeNode({self.name or 'op'}#{self.id})"


def _is_tensor(x) -> bool:
    from .tensor import Tensor

    return isinstance(x, Tensor)


def _differentiable(x) -> bool:
    return not x.stop_gradient and dtypes.is_floating_point(x.dtype) or (
        not x.stop_gradient and dtypes.is_complex(x.dtype)
    )


def apply(fn: Callable, *args, op_name: str = "", **kwargs):
    """Run ``fn`` (a jnp/lax-level function) on Tensor/array args, recording
    a tape node when differentiation is required.

    This is the single dispatch point every op wrapper goes through — the
    analogue of the generated ``*_ad_func`` layer (ref:
    fluid/eager/auto_code_generator/generator/eager_gen.py:767), with
    jax.vjp standing in for generated GradNodes.
    """
    from .tensor import Tensor

    flat, treedef = tree_util.tree_flatten((args, kwargs), is_leaf=_is_tensor)

    # AMP: per-op input casting at the single dispatch point (the
    # reference does this in every generated ad_func; ref eager_gen.py
    # AMP block). cast itself dispatches through apply with
    # op_name="cast", which amp_state maps to None — no recursion.
    amp_target = amp_state.cast_target(op_name)
    if amp_target is not None:
        flat = [
            x.astype(amp_target)
            if isinstance(x, Tensor)
            and dtypes.is_floating_point(x.dtype)
            and np.dtype(x.dtype) != amp_target
            and np.dtype(x.dtype) != np.dtype(np.float64)
            else x
            for x in flat
        ]

    raw = [x._data if isinstance(x, Tensor) else x for x in flat]

    diff_idx: List[int] = []
    if _grad_state.enabled:
        diff_idx = [
            i
            for i, x in enumerate(flat)
            if isinstance(x, Tensor) and _differentiable(x)
        ]

    if not diff_idx:
        fargs, fkwargs = tree_util.tree_unflatten(treedef, raw)
        out = fn(*fargs, **fkwargs)
        return _wrap_outputs(out, node=None, op_name=op_name)

    def closure(*xs):
        buf = list(raw)
        for i, x in zip(diff_idx, xs):
            buf[i] = x
        cargs, ckwargs = tree_util.tree_unflatten(treedef, buf)
        return fn(*cargs, **ckwargs)

    primals = [raw[i] for i in diff_idx]
    out, vjp_fn = jax.vjp(closure, *primals)

    out_leaves, out_treedef = tree_util.tree_flatten(out)
    out_avals = [(np.shape(o), np.result_type(o)) for o in out_leaves]
    node = TapeNode(
        vjp_fn,
        tuple(flat[i] for i in diff_idx),
        out_avals,
        out_treedef,
        name=op_name or getattr(fn, "__name__", "op"),
        fwd_fn=closure,
    )
    node_ref = weakref.ref(node)
    for inp in node.inputs:
        inp._consumer_nodes.append(node_ref)
    return _wrap_outputs(out, node=node, op_name=op_name)


# Observers at the single dispatch point: callables (op_name, out_leaves)
# invoked on every op's raw outputs, and callables () invoked at each
# run_backward entry (training-step ticks). Empty lists cost one truthiness
# check per op. amp.debugging's operator-stats collector and tensor
# checker register here (the reference instruments its generated ad_func
# layer; ref python/paddle/amp/debugging.py:534 collect_operator_stats).
_op_observers: List[Callable] = []
_backward_tick_callbacks: List[Callable] = []


def _wrap_outputs(out, node, op_name=""):
    from .tensor import Tensor

    if flag("check_nan_inf"):
        _check_nan_inf(out, op_name)

    leaves, treedef = tree_util.tree_flatten(out)
    if _op_observers:
        for obs in list(_op_observers):
            obs(op_name, leaves)
    wrapped = []
    for i, leaf in enumerate(leaves):
        t = Tensor(leaf, stop_gradient=node is None, _internal=True)
        if node is not None:
            t._grad_node = node
            t._out_index = i
        wrapped.append(t)
    if flag("benchmark"):
        for leaf in leaves:
            if hasattr(leaf, "block_until_ready"):
                leaf.block_until_ready()
    return tree_util.tree_unflatten(treedef, wrapped)


def _check_nan_inf(out, op_name):
    """FLAGS_check_nan_inf parity (ref: fluid/eager/nan_inf_utils.cc).

    Only runs eagerly (skipped under trace where values are abstract).
    """
    import jax.core as jcore

    for leaf in tree_util.tree_leaves(out):
        if isinstance(leaf, jcore.Tracer):
            return
        arr = np.asarray(leaf)
        if arr.dtype.kind in "fc" and not np.isfinite(arr).all():
            msg = f"NaN/Inf detected in output of op '{op_name or 'unknown'}'"
            if flag("check_nan_inf_level") == 0:
                raise FloatingPointError(msg)
            print("WARNING:", msg)


# ---------------------------------------------------------------------------
# Backward engine (RunBackward parity, ref: fluid/eager/backward.cc:105)
# ---------------------------------------------------------------------------


def _zeros_cotangent(aval):
    shape, dt = aval
    if np.issubdtype(dt, np.inexact) or dt == dtypes.bfloat16:
        import jax.numpy as jnp

        return jnp.zeros(shape, dt)
    return np.zeros(shape, jax.dtypes.float0)


def _collect_reachable(roots) -> Dict[int, TapeNode]:
    nodes: Dict[int, TapeNode] = {}
    stack = [t._grad_node for t in roots if t._grad_node is not None]
    while stack:
        n = stack.pop()
        if n.id in nodes:
            continue
        nodes[n.id] = n
        for inp in n.inputs:
            if inp._grad_node is not None and inp._grad_node.id not in nodes:
                stack.append(inp._grad_node)
    return nodes


# -- interleaved optimizer updates -------------------------------------
# Params registered here get their optimizer update applied the moment
# their gradient FINALIZES during run_backward (all contributions
# accumulated), instead of in a serial opt.step() tail after backward.
# Inside a traced train step this interleaves the HBM-bound update ops
# with the remaining backward layers in the jaxpr — the basis of the
# fused-optimizer-into-backward schedule (see optimizer.AdamW
# interleave_updates; ref: the reference fuses the same tail into a
# single kernel, paddle/phi/kernels/gpu/adamw_kernel.cu).
import weakref as _weakref

_interleave_registry: Dict[int, Any] = {}  # id(param) -> (wref, opt wref)


def register_interleaved_param(param, opt) -> None:
    key = id(param)
    _interleave_registry[key] = (
        _weakref.ref(param, lambda _: _interleave_registry.pop(key, None)),
        _weakref.ref(opt),
    )


def unregister_interleaved_params(params) -> None:
    """Drop interleave ownership of ``params``. Called by Optimizer
    __init__ for every new optimizer: constructing a replacement
    optimizer over the same parameters must strip a previous
    interleaving optimizer's hooks, or the abandoned optimizer would
    keep applying its updates on every backward."""
    for p in params:
        _interleave_registry.pop(id(p), None)


def run_backward(
    tensors: Sequence,
    grad_tensors: Optional[Sequence] = None,
    retain_graph: bool = False,
    *,
    inputs: Optional[Sequence] = None,
    create_graph: bool = False,
):
    """Reverse-walk the tape from ``tensors``.

    When ``inputs`` is given, returns the cotangents for exactly those
    tensors (paddle.grad semantics); otherwise accumulates into ``.grad``
    of every reachable leaf (loss.backward semantics).

    Cotangents flow as *Tensors* and each vjp closure is invoked through
    :func:`apply`, so with ``create_graph=True`` the backward pass itself
    is recorded on the tape — higher-order autodiff (double grad, the
    reference's ``general_grad.h`` path) falls out of the same mechanism.
    """
    import jax.numpy as jnp

    from .tensor import Tensor

    for cb in list(_backward_tick_callbacks):
        cb()

    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    if len(grad_tensors) != len(tensors):
        raise ValueError("grad_tensors length mismatch")

    # cotangent store keyed by (node_id, out_index); values are Tensors
    cots: Dict[Tuple[int, int], Any] = {}
    # grads for explicitly requested inputs (paddle.grad)
    want: Dict[int, Any] = {}
    want_ids = {id(t) for t in inputs} if inputs is not None else set()
    # interleaved updates: outstanding grad contributions per registered
    # leaf; when a leaf's count hits 0 its update fires immediately.
    # Only for loss.backward() semantics (not paddle.grad/double grad).
    _pending: Dict[int, int] = {}
    _interleave_on = bool(
        _interleave_registry) and inputs is None and not create_graph

    def _interleave_dec(t):
        if not _interleave_on:
            return
        k = id(t)
        if k not in _pending:
            return
        _pending[k] -= 1
        if _pending[k] > 0:
            return
        del _pending[k]
        ref = _interleave_registry.get(k)
        if ref is None:
            return
        param, opt = ref[0](), ref[1]()
        if param is not None and opt is not None:
            opt._interleave_apply(param)

    def _accumulate(t: Tensor, g: Tensor):
        if g is None or (
            isinstance(g, np.ndarray) and g.dtype == jax.dtypes.float0
        ):
            if (isinstance(t, Tensor) and t._grad_node is None
                    and not t.stop_gradient):
                _interleave_dec(t)
            return
        if not isinstance(g, Tensor):
            g = Tensor(g, stop_gradient=not create_graph, _internal=True)
        for hook in t._grad_hooks:
            res = hook(g)
            if res is not None:
                g = res
        if id(t) in want_ids:
            want[id(t)] = g if id(t) not in want else want[id(t)] + g
        if t._grad_node is not None:
            key = (t._grad_node.id, t._out_index)
            cots[key] = g if key not in cots else cots[key] + g
            if t._retain_grads and inputs is None:
                t._grad = g if t._grad is None else t._grad + g
        elif getattr(t, "_piecewise_carry", False):
            # a cotangent reached a tensor carried across a piecewise
            # graph-break split: eager execution would have continued
            # into the prefix's graph, but the carry is a materialized
            # array with no history — silently stopping here would train
            # wrong. Raising demotes the split to whole-function eager
            # (StaticFunction catches any piecewise-path exception).
            raise RuntimeError(
                "backward reached a value carried across a piecewise "
                "graph-break split; the autograd graph cannot span the "
                "compiled prefix"
            )
        elif inputs is None and not t.stop_gradient:
            # leaf accumulation (GradNodeAccumulation parity)
            t._grad = g if t._grad is None else t._grad + g
            _interleave_dec(t)

    with set_grad_enabled(create_graph):
        for t, g in zip(tensors, grad_tensors):
            if g is None:
                if t.size != 1:
                    raise RuntimeError(
                        "grad can be implicitly created only for scalar outputs; "
                        f"got shape {t.shape}"
                    )
                g = Tensor(
                    jnp.ones(t._data.shape, t._data.dtype),
                    stop_gradient=not create_graph,
                    _internal=True,
                )
            _accumulate(t, g if isinstance(g, Tensor) else Tensor(g, _internal=True))

        nodes = _collect_reachable(tensors)
        if _interleave_on:
            for node in nodes.values():
                for inp in node.inputs:
                    if (isinstance(inp, Tensor) and inp._grad_node is None
                            and not inp.stop_gradient
                            and id(inp) in _interleave_registry):
                        _pending[id(inp)] = _pending.get(id(inp), 0) + 1
        for node in sorted(nodes.values(), key=lambda n: n.id, reverse=True):
            out_cots = []
            any_seeded = False
            for i, aval in enumerate(node.out_avals):
                c = cots.pop((node.id, i), None)
                if c is None:
                    c = _zeros_cotangent(aval)  # raw zeros; constant to vjp
                else:
                    any_seeded = True
                out_cots.append(c)
            if not any_seeded:
                # dead branch not on the path from roots: its inputs
                # will never receive a contribution from this node
                if _interleave_on:
                    for inp in node.inputs:
                        if (isinstance(inp, Tensor)
                                and inp._grad_node is None
                                and not inp.stop_gradient):
                            _interleave_dec(inp)
                continue
            if node.vjp_fn is None:
                raise RuntimeError(
                    "Trying to backward through the graph a second time; "
                    "set retain_graph=True if needed."
                )
            cot_tree = tree_util.tree_unflatten(node.out_treedef, out_cots)
            if create_graph and node.fwd_fn is not None:
                # re-derive the vjp with primals as explicit args so the
                # cotangent→primal edges land on the tape (double grad)
                fwd_fn = node.fwd_fn

                def grad_call(ct, *prims, _fwd=fwd_fn):
                    _, vjp = jax.vjp(_fwd, *prims)
                    return tuple(vjp(ct))

                in_cots = apply(
                    grad_call, cot_tree, *node.inputs, op_name=f"grad_{node.name}"
                )
            else:
                vjp_fn = node.vjp_fn
                in_cots = apply(
                    lambda ct: tuple(vjp_fn(ct)), cot_tree, op_name=f"grad_{node.name}"
                )
            if not retain_graph and not create_graph:
                node.vjp_fn = None  # free residuals
            for inp, g in zip(node.inputs, in_cots):
                _accumulate(inp, g)

    if inputs is not None:
        return [want.get(id(t)) for t in inputs]
    return None
