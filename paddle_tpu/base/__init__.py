from . import dtype, device, flags, random  # noqa: F401
from .dtype import (  # noqa: F401
    convert_dtype,
    get_default_dtype,
    set_default_dtype,
)
from .device import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    Place,
    TPUPlace,
    get_device,
    set_device,
)
from .flags import get_flags, set_flags  # noqa: F401
from .random import Generator, get_rng_state_tracker, seed  # noqa: F401
