"""Runtime flag registry.

TPU-native equivalent of the reference's homegrown gflags clone
(ref: paddle/common/flags.h:336 ExportedFlagInfoMap; ~200 flags in
paddle/phi/core/flags.cc) exposed as ``paddle.set_flags/get_flags``
(ref: python/paddle/base/framework.py:109).

Flags are typed, documented, env-overridable (``FLAGS_<name>`` env vars,
parsed lazily), and observable by subsystems via callbacks.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List


@dataclass
class _FlagInfo:
    name: str
    default: Any
    doc: str
    type: type
    value: Any = None
    callbacks: List[Callable[[Any], None]] = field(default_factory=list)


class _FlagRegistry:
    def __init__(self):
        self._flags: Dict[str, _FlagInfo] = {}
        self._lock = threading.RLock()

    def define(self, name: str, default, doc: str = ""):
        with self._lock:
            if name in self._flags:
                return self._flags[name]
            info = _FlagInfo(name, default, doc, type(default))
            env = os.environ.get(f"FLAGS_{name}")
            info.value = self._coerce(info, env) if env is not None else default
            self._flags[name] = info
            return info

    @staticmethod
    def _coerce(info: _FlagInfo, raw):
        if info.type is bool:
            if isinstance(raw, str):
                return raw.lower() in ("1", "true", "yes", "on")
            return bool(raw)
        if info.type in (int, float):
            return info.type(raw)
        return raw

    def set(self, name: str, value):
        with self._lock:
            if name not in self._flags:
                # auto-register unknown flags (matches the reference's lenient
                # phi flag handling for plugin-defined flags)
                self.define(name, value)
                return
            info = self._flags[name]
            info.value = self._coerce(info, value)
            for cb in info.callbacks:
                cb(info.value)

    def get(self, name: str):
        with self._lock:
            if name not in self._flags:
                raise KeyError(f"unknown flag {name!r}")
            return self._flags[name].value

    def on_change(self, name: str, cb: Callable[[Any], None]):
        with self._lock:
            self._flags[name].callbacks.append(cb)

    def all(self) -> Dict[str, Any]:
        with self._lock:
            return {k: v.value for k, v in self._flags.items()}


_registry = _FlagRegistry()
define_flag = _registry.define


def set_flags(flags: Dict[str, Any]):
    """paddle.set_flags parity."""
    if not isinstance(flags, dict):
        raise TypeError("set_flags expects a dict")
    for k, v in flags.items():
        _registry.set(k, v)


def get_flags(flags):
    """paddle.get_flags parity: str or list of str -> dict."""
    if isinstance(flags, str):
        flags = [flags]
    return {k: _registry.get(k) for k in flags}


def flag(name: str):
    return _registry.get(name)


def on_flag_change(name, cb):
    _registry.on_change(name, cb)


# ---------------------------------------------------------------------------
# Core flags (subset of the reference's catalogue that is meaningful on TPU).
define_flag("check_nan_inf", False, "Scan op outputs for NaN/Inf (debug sanitizer; ref FLAGS_check_nan_inf)")
define_flag("check_nan_inf_level", 0, "0: abort on nan/inf, >0: log only (ref FLAGS_check_nan_inf_level)")
define_flag("benchmark", False, "Block-until-ready after each op for timing")
define_flag("host_trace_level", 1, "Host tracer verbosity (ref FLAGS_host_trace_level)")
define_flag("comm_timeout_s", 1800.0, "Collective watchdog deadline seconds per blocking wait (ref comm_task_manager)")
define_flag("comm_abort_on_timeout", True, "Watchdog aborts the process on a timed-out wait so the launcher relaunches (ref async error handling)")
define_flag("comm_warn_fraction", 0.5, "Watchdog ladder: warn when a wait has consumed this fraction of its deadline")
define_flag("comm_dump_fraction", 0.75, "Watchdog ladder: all-thread stack dump at this fraction of the deadline (abort fires at 1.0)")
define_flag("enable_comm_dynamic_check", False, "Cross-rank shape/dtype check before collectives (ref FLAGS_enable_nccl_dynamic_check)")
define_flag("comm_flight_recorder_len", 128, "Collective flight recorder ring size: last-N collective signatures kept per rank (dumped by the watchdog, cross-checked by collective_contract)")
define_flag("use_stream_safe_allocator", True, "no-op on TPU; kept for parity")
define_flag("eager_delete_tensor_gb", 0.0, "no-op on TPU; kept for parity")
define_flag("log_level", 0, "VLOG-style verbosity for paddle_tpu.utils.log")
define_flag(
    "dy2static_while_grad_bound", 0,
    "When > 0, a converted tensor-`while` whose carries need gradients "
    "runs as a bounded differentiable lax.scan of this many iterations "
    "with an early-exit mask (the bound MUST cover the true trip count; "
    "extra iterations are masked no-ops). 0 keeps the non-differentiable "
    "lax.while_loop (ref: while backward, static/nn/control_flow.py:682)")
define_flag("allocator_strategy", "xla", "TPU: XLA owns allocation; kept for parity")
define_flag("cudnn_deterministic", False, "maps to XLA deterministic ops flag semantics")
