"""Thread-local AMP (automatic mixed precision) state.

Lives in ``base`` so the tape dispatch point (base/tape.py apply) can
consult it without importing the user-facing ``paddle_tpu.amp`` package
(which imports base — this module breaks the cycle).

The reference performs per-op auto-casting inside the generated
``*_ad_func`` layer (ref: fluid/eager/auto_code_generator/generator/
eager_gen.py AMP block, fluid/eager/amp_auto_cast.h). Here the single
dispatch point is ``tape.apply``, so the cast decision is a pure lookup:
op name -> target dtype (or None for "leave inputs alone").
"""
from __future__ import annotations

import threading
from typing import Optional, Set

import numpy as np


class _AmpTLS(threading.local):
    def __init__(self):
        self.enable = False
        self.dtype = None  # np.dtype of the low-precision type
        self.level = "O1"  # "OD" | "O1" | "O2"
        self.white: Set[str] = set()
        self.black: Set[str] = set()


_tls = _AmpTLS()
_FP32 = np.dtype(np.float32)


def amp_attrs() -> _AmpTLS:
    return _tls


def amp_enabled() -> bool:
    return _tls.enable


def amp_dtype() -> Optional[np.dtype]:
    return _tls.dtype if _tls.enable else None


def cast_target(op_name: str) -> Optional[np.dtype]:
    """Target dtype for the floating inputs of ``op_name`` under the
    active amp state, or None when no casting applies."""
    if not _tls.enable or not op_name or op_name == "cast":
        return None
    if op_name == "recompute":
        # container op: its body dispatches through apply per-op, where
        # amp policy applies with the right op names — casting the whole
        # argument set here would override the inner per-op decisions
        return None
    if op_name.startswith("grad_"):
        # backward-pass vjp calls (run_backward dispatches them through
        # apply with op_name="grad_<op>"): cotangent dtypes must match the
        # forward residuals exactly — never auto-cast them
        return None
    if op_name in _tls.black:
        return _FP32
    if _tls.level == "O2":
        return _tls.dtype
    if op_name in _tls.white:
        return _tls.dtype
    if _tls.level == "OD":
        return _FP32
    return None  # O1: ops in neither list keep their input dtypes
