"""Auditable op registry — the single-source op table.

ref: the reference generates its op surface from
paddle/phi/ops/yaml/ops.yaml (+ backward.yaml) via build-time codegen
(SURVEY §2.1 item 8). Here the op surface is plain Python functions
dispatching through ``tape.apply``, so the single source is built by
introspection instead of codegen: ``registry()`` walks the public op
namespaces and returns one record per op — name, module, signature,
and doc reference — giving the same auditability (diffable op
inventory, coverage checks in tests) without a parallel YAML that
could drift from the code.
"""
from __future__ import annotations

import dataclasses
import inspect
from typing import Dict, List, Optional

__all__ = ["OpRecord", "registry", "op_names", "lookup"]

# namespaces that constitute the public op surface
_OP_NAMESPACES = [
    "paddle_tpu.tensor.creation",
    "paddle_tpu.tensor.math",
    "paddle_tpu.tensor.linalg",
    "paddle_tpu.tensor.manipulation",
    "paddle_tpu.tensor.logic",
    "paddle_tpu.tensor.random",
    "paddle_tpu.tensor.search",
    "paddle_tpu.tensor.stat",
    "paddle_tpu.tensor.einsum",
    "paddle_tpu.nn.functional.activation",
    "paddle_tpu.nn.functional.common",
    "paddle_tpu.nn.functional.conv",
    "paddle_tpu.nn.functional.loss",
    "paddle_tpu.nn.functional.norm",
    "paddle_tpu.nn.functional.pooling",
    "paddle_tpu.nn.functional.attention",
    "paddle_tpu.fft",
    "paddle_tpu.vision.ops",
    "paddle_tpu.sparse",
    "paddle_tpu.sparse.nn.functional",
    "paddle_tpu.incubate.nn.functional",
    "paddle_tpu.geometric",
    "paddle_tpu.signal",
]


@dataclasses.dataclass(frozen=True)
class OpRecord:
    name: str
    module: str
    signature: str
    doc_ref: Optional[str]  # first "ref:" line from the docstring


_cache: Optional[Dict[str, OpRecord]] = None


def _doc_ref(fn) -> Optional[str]:
    doc = inspect.getdoc(fn) or ""
    for line in doc.splitlines():
        if "ref:" in line:
            return line.strip()
    return None


def registry(refresh: bool = False) -> Dict[str, OpRecord]:
    """name → OpRecord for every public op function."""
    global _cache
    if _cache is not None and not refresh:
        return _cache
    import importlib

    out: Dict[str, OpRecord] = {}
    for mod_name in _OP_NAMESPACES:
        mod = importlib.import_module(mod_name)
        mod_ref = None
        for line in (mod.__doc__ or "").splitlines():
            if "ref:" in line:
                mod_ref = line.strip()
                break
        public = getattr(mod, "__all__", None) or [
            n for n in dir(mod) if not n.startswith("_")
        ]
        for name in public:
            fn = getattr(mod, name, None)
            if not inspect.isfunction(fn):
                continue
            # ops defined elsewhere and re-exported count once, at home
            if fn.__module__ != mod_name:
                continue
            try:
                sig = str(inspect.signature(fn))
            except (TypeError, ValueError):
                sig = "(...)"
            key = name if name not in out else f"{mod_name.rsplit('.', 1)[-1]}.{name}"
            if key in out:
                # two namespaces with the same terminal segment (e.g.
                # *.nn.functional) exporting the same op name would
                # silently clobber an inventory entry — fail loudly
                key = f"{mod_name}.{name}"
                if key in out:  # not an assert: must survive python -O
                    raise RuntimeError(f"op registry collision: {key}")
            out[key] = OpRecord(name, mod_name, sig, _doc_ref(fn) or mod_ref)
    _cache = out
    return out


def op_names() -> List[str]:
    return sorted(registry().keys())


def lookup(name: str) -> Optional[OpRecord]:
    return registry().get(name)
