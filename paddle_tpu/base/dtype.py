"""Data types for paddle_tpu.

TPU-native dtype surface. The reference exposes dtypes both as
``paddle.float32``-style singletons and ``'float32'`` strings
(ref: /root/reference/python/paddle/framework/dtype.py). Here dtypes ARE
numpy/jax dtypes — everything in the framework accepts a string, a numpy
dtype, a jnp scalar type, or these aliases interchangeably.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes  # shipped with jax

# Canonical dtype singletons (np.dtype instances).
bool_ = np.dtype(np.bool_)
uint8 = np.dtype(np.uint8)
int8 = np.dtype(np.int8)
int16 = np.dtype(np.int16)
int32 = np.dtype(np.int32)
int64 = np.dtype(np.int64)
float16 = np.dtype(np.float16)
bfloat16 = np.dtype(ml_dtypes.bfloat16)
float32 = np.dtype(np.float32)
float64 = np.dtype(np.float64)
complex64 = np.dtype(np.complex64)
complex128 = np.dtype(np.complex128)
float8_e4m3fn = np.dtype(ml_dtypes.float8_e4m3fn)
float8_e5m2 = np.dtype(ml_dtypes.float8_e5m2)

_ALIASES = {
    "bool": bool_,
    "paddle.bool": bool_,
    "bfloat16": bfloat16,
    "float8_e4m3fn": float8_e4m3fn,
    "float8_e5m2": float8_e5m2,
}

_FLOAT_DTYPES = (float16, bfloat16, float32, float64, float8_e4m3fn, float8_e5m2)
_INT_DTYPES = (uint8, int8, int16, int32, int64)
_COMPLEX_DTYPES = (complex64, complex128)


def convert_dtype(dtype) -> np.dtype:
    """Normalize any dtype spec (str / np / jnp / paddle-style) to np.dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, np.dtype):
        return dtype
    if isinstance(dtype, str):
        name = dtype.replace("paddle.", "")
        if name in _ALIASES:
            return _ALIASES[name]
        return np.dtype(name)
    return np.dtype(dtype)


def dtype_name(dtype) -> str:
    return convert_dtype(dtype).name


def is_floating_point(dtype) -> bool:
    return convert_dtype(dtype) in _FLOAT_DTYPES


def is_integer(dtype) -> bool:
    d = convert_dtype(dtype)
    return d in _INT_DTYPES or d == bool_


def is_complex(dtype) -> bool:
    return convert_dtype(dtype) in _COMPLEX_DTYPES


def finfo(dtype):
    return jnp.finfo(convert_dtype(dtype))


def iinfo(dtype):
    return jnp.iinfo(convert_dtype(dtype))


_DEFAULT_DTYPE = [float32]


def set_default_dtype(d):
    """ref: python/paddle/framework/framework.py set_default_dtype."""
    d = convert_dtype(d)
    if d not in (float16, bfloat16, float32, float64):
        raise TypeError(
            f"set_default_dtype only supports float16/bfloat16/float32/float64, got {d}"
        )
    _DEFAULT_DTYPE[0] = d


def get_default_dtype() -> np.dtype:
    return _DEFAULT_DTYPE[0]


def promote_types(a, b):
    return jnp.promote_types(convert_dtype(a), convert_dtype(b))


def canonical_dtype(dtype) -> np.dtype:
    """Map a requested dtype to what the backend can hold: without
    jax_enable_x64, 64-bit ints/floats canonicalize to 32-bit (paddle's
    int64 defaults stay API-compatible; storage is int32 on TPU)."""
    import jax

    d = convert_dtype(dtype)
    if not jax.config.jax_enable_x64:
        if d == int64:
            return int32
        if d == float64:
            return float32
        if d == complex128:
            return complex64
    return d


def canonical_int() -> np.dtype:
    return canonical_dtype(int64)
