"""DataLoader (ref: python/paddle/io/reader.py:216 DataLoader,
io/dataloader/dataloader_iter.py, collate.py, worker.py).

TPU-native redesign. The reference's iterator zoo (single-process,
multi-process with shared-memory LoDTensor queues, pin-memory threads)
exists to feed CUDA streams; on TPU the pipeline is:

    sampler → fetch+collate (numpy, worker threads) → [device_put] →
    bounded prefetch queue → training step

Worker *threads* (not processes) run the fetch: decode/augment code is
numpy/PIL/IO-bound and releases the GIL, and threads share the dataset
object so there is no fork/pickle tax. ``prefetch_factor`` batches are
staged ahead so host work overlaps device steps — the role of the
reference's `_DataLoaderIterMultiProcess` double-buffering.
"""
from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, List, Optional

import numpy as np

from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler, RandomSampler, SequenceSampler

__all__ = ["DataLoader", "default_collate_fn", "default_convert_fn"]


def _is_scalar(x):
    return isinstance(x, (int, float, np.integer, np.floating, bool, np.bool_))


def default_convert_fn(batch):
    """Identity for already-batched data (ref: collate.py
    default_convert_fn)."""
    return batch


def default_collate_fn(batch: List[Any]):
    """Stack a list of samples into batched arrays (ref: collate.py
    default_collate_fn — same structure cases: ndarray, number, string,
    Mapping, Sequence)."""
    sample = batch[0]
    from ..base.tensor import Tensor

    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s.numpy()) for s in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if _is_scalar(sample):
        return np.asarray(batch)
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        transposed = list(zip(*batch))
        return type(sample)(default_collate_fn(list(fields)) for fields in transposed)
    raise TypeError(f"batch data can not be collated: {type(sample)}")


class _PrefetchIter:
    """Background-thread pipeline over batch indices.

    Workers pull batch-index lists from the shared sampler iterator,
    fetch+collate, and deposit results keyed by sequence number; the
    consumer emits them in sampler order (the reference preserves order
    the same way via its _task_infos reordering, dataloader_iter.py).
    A condition variable bounds the number of staged batches.
    """

    def __init__(self, loader, batch_iter):
        self._loader = loader
        self._batch_iter = batch_iter
        self._capacity = max(1, loader.num_workers) * loader.prefetch_factor
        self._cv = threading.Condition()
        self._results: dict = {}
        self._next_seq = 0  # next sequence number to hand out
        self._next_out = 0  # next sequence number to emit
        self._error = None
        self._exhausted = False
        self._shutdown = False
        self._live = max(1, loader.num_workers)
        self._workers = [
            threading.Thread(target=self._worker_loop, args=(i,), daemon=True)
            for i in range(self._live)
        ]
        for w in self._workers:
            w.start()

    def _worker_loop(self, worker_id):
        loader = self._loader
        if loader.worker_init_fn is not None:
            try:
                loader.worker_init_fn(worker_id)
            except Exception as e:
                with self._cv:
                    self._error = e
                    self._cv.notify_all()
                    self._live -= 1
                return
        while True:
            with self._cv:
                while (
                    not self._shutdown
                    and not self._exhausted
                    and self._next_seq - self._next_out >= self._capacity
                ):
                    self._cv.wait()
                if self._shutdown or self._exhausted or self._error is not None:
                    break
                try:
                    indices = next(self._batch_iter)
                except StopIteration:
                    self._exhausted = True
                    self._cv.notify_all()
                    break
                except Exception as e:
                    self._error = e
                    self._cv.notify_all()
                    break
                seq = self._next_seq
                self._next_seq += 1
            try:
                out = loader.collate_fn([loader.dataset[i] for i in indices])
                err = None
            except Exception as e:
                out, err = None, e
            with self._cv:
                if err is not None:
                    self._error = err
                else:
                    self._results[seq] = out
                self._cv.notify_all()
        with self._cv:
            self._live -= 1
            self._cv.notify_all()

    def __iter__(self):
        return self

    def __next__(self):
        timeout = self._loader.timeout or None
        with self._cv:
            while True:
                if self._error is not None:
                    self._shutdown = True
                    self._cv.notify_all()
                    raise self._error
                if self._next_out in self._results:
                    item = self._results.pop(self._next_out)
                    self._next_out += 1
                    self._cv.notify_all()
                    break
                # done when no pending seq can still arrive
                if self._live == 0 and self._next_out >= self._next_seq:
                    raise StopIteration
                if not self._cv.wait(timeout) and timeout:
                    self._shutdown = True
                    self._cv.notify_all()
                    raise RuntimeError(
                        f"DataLoader worker timed out after {timeout}s"
                    )
        return self._loader._to_output(item)

    def close(self):
        with self._cv:
            self._shutdown = True
            self._cv.notify_all()

    def __del__(self):
        self.close()


class _SyncIter:
    def __init__(self, loader, batch_iter):
        self._loader = loader
        self._batch_iter = batch_iter

    def __iter__(self):
        return self

    def __next__(self):
        indices = next(self._batch_iter)
        samples = [self._loader.dataset[i] for i in indices]
        return self._loader._to_output(self._loader.collate_fn(samples))


class _StreamPrefetchIter:
    """Single-reader prefetch over an order-sensitive stream iterator."""

    _DONE = object()

    def __init__(self, loader, inner):
        import queue

        self._loader = loader
        self._q: "queue.Queue" = queue.Queue(maxsize=loader.prefetch_factor)
        self._inner = inner
        self._error = None
        self._shutdown = False
        if loader.worker_init_fn is not None:
            loader.worker_init_fn(0)
        self._thread = threading.Thread(target=self._reader, daemon=True)
        self._thread.start()

    def _put(self, item) -> bool:
        import queue

        while not self._shutdown:
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _reader(self):
        try:
            for item in self._inner:
                if not self._put(item):
                    return  # consumer abandoned the iterator
        except Exception as e:
            self._error = e
        finally:
            self._put(self._DONE)

    def __iter__(self):
        return self

    def __next__(self):
        import queue

        timeout = self._loader.timeout or None
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            raise RuntimeError(
                f"DataLoader stream reader timed out after {timeout}s"
            ) from None
        if item is self._DONE:
            if self._error is not None:
                raise self._error
            raise StopIteration
        return item

    def close(self):
        self._shutdown = True
        # drain so a blocked reader can observe shutdown promptly
        import queue

        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass

    def __del__(self):
        self.close()


class _IterableIter:
    """Iterator over an IterableDataset: group into batches + collate."""

    def __init__(self, loader):
        self._loader = loader
        self._it = iter(loader.dataset)

    def __iter__(self):
        return self

    def __next__(self):
        loader = self._loader
        batch = list(itertools.islice(self._it, loader.batch_size))
        if not batch or (loader.drop_last and len(batch) < loader.batch_size):
            raise StopIteration
        collate = loader.collate_fn or default_collate_fn
        return loader._to_output(collate(batch))


class DataLoader:
    """Batched iterator over a Dataset (ref: io/reader.py:216).

    Differences from the reference, by design:
    - ``num_workers`` spawns prefetch *threads* (see module docstring);
      0 means synchronous in-loop fetching.
    - ``return_list`` defaults True (dygraph semantics); outputs are
      Tensors on the default device unless ``return_numpy=True``.
    - ``worker_type='process'`` spawns worker processes that stream
      collated batches through a native C++ POSIX-shm ring
      (io/shm_ring.py); requires ``use_shared_memory=True`` (default).
    - ``use_buffer_reader`` is accepted as a no-op (CUDA plumbing).
    """

    def __init__(
        self,
        dataset: Dataset,
        feed_list=None,
        places=None,
        return_list: bool = True,
        batch_sampler: Optional[BatchSampler] = None,
        batch_size: Optional[int] = 1,
        shuffle: bool = False,
        drop_last: bool = False,
        collate_fn: Optional[Callable] = None,
        num_workers: int = 0,
        use_buffer_reader: bool = True,
        prefetch_factor: int = 2,
        use_shared_memory: bool = True,
        timeout: int = 0,
        worker_init_fn: Optional[Callable] = None,
        persistent_workers: bool = False,
        return_numpy: bool = False,
        worker_type: str = "thread",
    ):
        self.dataset = dataset
        self.return_list = return_list
        self.num_workers = max(0, int(num_workers))
        self.prefetch_factor = max(1, int(prefetch_factor))
        self.return_numpy = return_numpy
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        # accepted for parity; workers are (re)spawned per epoch in both
        # modes. Thread workers make that free; process workers pay a
        # spawn+import per epoch — prefer thread workers for small
        # datasets/epochs until persistent process pools land
        self.persistent_workers = persistent_workers
        if worker_type not in ("thread", "process"):
            raise ValueError("worker_type must be 'thread' or 'process'")
        self.worker_type = worker_type
        self.use_shared_memory = use_shared_memory
        self._iterable = isinstance(dataset, IterableDataset)

        if self._iterable:
            if batch_sampler is not None or shuffle:
                raise ValueError(
                    "IterableDataset does not support batch_sampler/shuffle"
                )
            if worker_type == "process":
                raise ValueError(
                    "worker_type='process' is not supported for "
                    "IterableDataset (streams cannot be index-partitioned); "
                    "use worker_type='thread'"
                )
            self.batch_size = batch_size or 1
            self.drop_last = drop_last
            self.batch_sampler = None
            self.collate_fn = collate_fn or default_collate_fn
            return

        if batch_sampler is not None:
            if batch_size not in (1, None) or shuffle or drop_last:
                raise ValueError(
                    "batch_sampler is mutually exclusive with "
                    "batch_size/shuffle/drop_last"
                )
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
        else:
            if batch_size is None:
                raise ValueError("batch_size=None requires a batch_sampler")
            self.batch_size = int(batch_size)
            self.batch_sampler = BatchSampler(
                dataset=dataset, shuffle=shuffle,
                batch_size=self.batch_size, drop_last=drop_last,
            )
        self.drop_last = drop_last
        self.collate_fn = collate_fn or default_collate_fn

    def _to_output(self, batch):
        if self.return_numpy:
            return batch
        from ..base.tensor import Tensor

        def conv(x):
            if isinstance(x, np.ndarray):
                return Tensor(x, stop_gradient=True, _internal=True)
            if isinstance(x, dict):
                return {k: conv(v) for k, v in x.items()}
            if isinstance(x, (tuple, list)):
                return type(x)(conv(v) for v in x)
            return x

        return conv(batch)

    def __iter__(self):
        if self._iterable:
            it = _IterableIter(self)
            # stream order must be preserved: one background reader
            # thread stages batches ahead (host/device overlap)
            return _StreamPrefetchIter(self, it) if self.num_workers > 0 else it
        batch_iter = iter(self.batch_sampler)
        if self.num_workers > 0 and self.worker_type == "process":
            # spawned workers + C++ shared-memory ring transport (the
            # reference's multiprocess mode; see io/shm_ring.py)
            if not self.use_shared_memory:
                raise ValueError(
                    "worker_type='process' requires use_shared_memory=True "
                    "(the shm ring is the only process transport); use "
                    "worker_type='thread' where POSIX shm is unavailable"
                )
            from .shm_ring import ProcessPrefetchIter, native_available

            if not native_available():
                raise RuntimeError(
                    "worker_type='process' needs the native shm ring "
                    "(g++ + POSIX shm); fall back to worker_type='thread'"
                )
            return ProcessPrefetchIter(self, [list(b) for b in batch_iter])
        if self.num_workers > 0:
            return _PrefetchIter(self, batch_iter)
        return _SyncIter(self, batch_iter)

    def __len__(self):
        if self._iterable:
            raise TypeError("DataLoader over IterableDataset has no len()")
        return len(self.batch_sampler)
