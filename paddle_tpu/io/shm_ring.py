"""ctypes binding + process-worker transport over the C++ shm ring.

ref: the reference's multiprocess DataLoader transport
(fluid/imperative/data_loader.cc + mmap_allocator.h — shm segments,
SIGBUS/SIGSEGV cleanup at :57). Here the native piece is
io/_native/ringbuf.cpp; this module compiles it on first use (g++,
cached .so beside the source), exposes RingBuffer, and implements the
process-worker prefetch iterator DataLoader uses when
``use_shared_memory=True`` with ``worker_type='process'``.
"""
from __future__ import annotations

import atexit
import ctypes
import os
import pickle
import subprocess
import uuid
from typing import Optional

__all__ = ["RingBuffer", "native_available", "ProcessPrefetchIter"]

import threading

_spawn_lock = threading.Lock()

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "_native")
_SRC = os.path.join(_NATIVE_DIR, "ringbuf.cpp")
_SO = os.path.join(_NATIVE_DIR, "_ringbuf.so")

_lib = None
_build_error: Optional[str] = None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_error
    with _spawn_lock:
        return _load_locked()


def _load_locked() -> Optional[ctypes.CDLL]:
    global _lib, _build_error
    if _lib is not None or _build_error is not None:
        return _lib
    try:
        if not os.path.exists(_SO) or (
            os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        ):
            # per-process tmp name: concurrent first-use builds from
            # several processes must not clobber each other's output
            tmp = f"{_SO}.{os.getpid()}.tmp"
            cmd = ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, _SRC,
                   "-lpthread"]
            subprocess.run(cmd, check=True, capture_output=True, text=True)
            os.replace(tmp, _SO)
        lib = ctypes.CDLL(_SO)
        lib.rb_create.restype = ctypes.c_void_p
        lib.rb_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.rb_open.restype = ctypes.c_void_p
        lib.rb_open.argtypes = [ctypes.c_char_p]
        lib.rb_push.restype = ctypes.c_int
        lib.rb_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_uint64, ctypes.c_double]
        lib.rb_pop.restype = ctypes.c_int64
        lib.rb_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                               ctypes.c_uint64, ctypes.c_double]
        lib.rb_peek_len.restype = ctypes.c_int64
        lib.rb_peek_len.argtypes = [ctypes.c_void_p]
        lib.rb_close.argtypes = [ctypes.c_void_p]
        lib.rb_detach.argtypes = [ctypes.c_void_p]
        lib.rb_unlink.argtypes = [ctypes.c_char_p]
        _lib = lib
    except Exception as e:  # g++ missing / sandboxed shm
        _build_error = str(e)
        _lib = None
    return _lib


def native_available() -> bool:
    return _load() is not None


class RingBuffer:
    """Length-prefixed message ring in POSIX shared memory."""

    def __init__(self, name: Optional[str] = None, capacity: int = 64 << 20,
                 create: bool = True):
        lib = _load()
        if lib is None:
            raise RuntimeError(
                f"native ring buffer unavailable: {_build_error}"
            )
        self._lib = lib
        self.name = name or f"/pt_ring_{os.getpid()}_{uuid.uuid4().hex[:8]}"
        self._owner = create
        if create:
            self._h = lib.rb_create(self.name.encode(), capacity)
        else:
            self._h = lib.rb_open(self.name.encode())
        if not self._h:
            raise RuntimeError(f"failed to map shm ring {self.name}")
        self._buf = ctypes.create_string_buffer(1 << 20)
        if create:
            # bind only (lib, name) — not self — so atexit does not pin
            # the instance (close()/unlink() normally runs much earlier)
            atexit.register(lib.rb_unlink, self.name.encode())

    def push(self, payload: bytes, timeout: float = 60.0):
        if not self._h:
            raise BrokenPipeError("ring detached")
        rc = self._lib.rb_push(self._h, payload, len(payload), timeout)
        if rc == -1:
            raise TimeoutError(f"ring push timed out after {timeout}s")
        if rc == -2:
            raise BrokenPipeError("ring closed")
        if rc == -3:
            raise ValueError("message larger than ring capacity")

    def pop(self, timeout: Optional[float] = 60.0) -> Optional[bytes]:
        """bytes, or None when the ring is closed and drained.
        timeout=None blocks indefinitely."""
        if not self._h:
            return None
        if timeout is None:
            while True:
                try:
                    return self.pop(timeout=3600.0)
                except TimeoutError:
                    continue
        n = self._lib.rb_pop(self._h, self._buf, len(self._buf), timeout)
        if n == -4:  # grow the local receive buffer and retry
            need = self._lib.rb_peek_len(self._h)
            self._buf = ctypes.create_string_buffer(int(need))
            n = self._lib.rb_pop(self._h, self._buf, len(self._buf), timeout)
        if n == -1:
            raise TimeoutError(f"ring pop timed out after {timeout}s")
        if n == -2:
            return None
        return self._buf.raw[: int(n)]

    def close(self):
        if self._h:
            self._lib.rb_close(self._h)

    def detach(self):
        if self._h:
            self._lib.rb_detach(self._h)
            self._h = None

    def unlink(self):
        try:
            self._lib.rb_unlink(self.name.encode())
        except Exception:
            pass


def _worker_main(ring_name, dataset, my_batches, worker_id,
                 collate_fn, worker_init_fn, num_workers=1):
    """Worker process: produces its stride-slice of batches IN ORDER on
    its own ring — the parent pops ring (seq % N) so sampler order is
    preserved with no reordering buffer, and each ring's capacity
    backpressures its worker. ``my_batches`` is only this worker's
    slice (batches[w::N]); the full index list is never shipped."""
    import traceback

    ring = RingBuffer(ring_name, create=False)
    try:
        # publish WorkerInfo so datasets can shard via get_worker_info()
        from .. import io as _io_mod

        _io_mod._worker_info = _io_mod.WorkerInfo(worker_id, num_workers, dataset)
        if worker_init_fn is not None:
            worker_init_fn(worker_id)
        for indices in my_batches:
            samples = [dataset[i] for i in indices]
            out = collate_fn(samples)
            ring.push(pickle.dumps(("ok", out), protocol=4), timeout=3600.0)
    except BrokenPipeError:
        pass
    except BaseException:
        try:
            ring.push(
                pickle.dumps(("error", traceback.format_exc()), protocol=4),
                timeout=60.0,
            )
        except Exception:
            pass
    finally:
        ring.detach()


class ProcessPrefetchIter:
    """Parent-side iterator over N per-worker rings (see _worker_main)."""

    def __init__(self, loader, batch_indices):
        import multiprocessing as mp

        self._loader = loader
        self._total = len(batch_indices)
        self._next = 0
        self._live = max(1, loader.num_workers)
        per_ring = max(4 << 20, (128 << 20) // self._live)
        self._rings = [RingBuffer(capacity=per_ring) for _ in range(self._live)]
        # spawn, not fork: the parent runs JAX's thread pool and fork
        # would deadlock; dataset/collate travel by pickle (the same
        # contract the reference's multiprocess loader imposes)
        ctx = mp.get_context("spawn")
        self._procs = [
            ctx.Process(
                target=_worker_main,
                args=(self._rings[w].name, loader.dataset,
                      batch_indices[w::self._live], w,
                      loader.collate_fn, loader.worker_init_fn,
                      self._live),
                daemon=True,
            )
            for w in range(self._live)
        ]
        # workers are host-side only: force the CPU backend in children
        # (see the PADDLE_TPU_FORCE_CPU hook in paddle_tpu/__init__).
        # Env mutation is process-global: serialize spawns across
        # threads so a concurrent iterator can't observe the window
        # where the flag is restored.
        with _spawn_lock:
            prev = os.environ.get("PADDLE_TPU_FORCE_CPU")
            os.environ["PADDLE_TPU_FORCE_CPU"] = "1"
            try:
                for p in self._procs:
                    p.start()
            finally:
                if prev is None:
                    os.environ.pop("PADDLE_TPU_FORCE_CPU", None)
                else:
                    os.environ["PADDLE_TPU_FORCE_CPU"] = prev

    def __iter__(self):
        return self

    def __next__(self):
        import time

        if self._next >= self._total:
            self.close()
            raise StopIteration
        # 0 means block (matching the thread path's `timeout or None`),
        # but poll in short slices so a dead worker (e.g. its dataset
        # failed to unpickle) surfaces instead of blocking forever
        timeout = self._loader.timeout or None
        deadline = None if timeout is None else time.monotonic() + timeout
        w = self._next % self._live
        try:
            while True:
                slice_s = 5.0
                if deadline is not None:
                    slice_s = max(0.01, min(5.0, deadline - time.monotonic()))
                try:
                    payload = self._rings[w].pop(timeout=slice_s)
                    break
                except TimeoutError:
                    if deadline is not None and time.monotonic() > deadline:
                        raise
                    if not self._procs[w].is_alive():
                        try:  # drain anything pushed just before death
                            payload = self._rings[w].pop(timeout=0.5)
                            break
                        except TimeoutError:
                            raise RuntimeError(
                                f"DataLoader worker {w} died (exitcode "
                                f"{self._procs[w].exitcode}) before batch "
                                f"{self._next}; check worker stderr — a "
                                "dataset defined in __main__ of a -c "
                                "script cannot be unpickled by spawn "
                                "workers"
                            ) from None
            if payload is None:
                raise RuntimeError(
                    f"DataLoader worker {w} exited before producing batch "
                    f"{self._next}"
                )
            tag, out = pickle.loads(payload)
            if tag == "error":
                raise RuntimeError(
                    f"DataLoader worker {w} failed:\n{out}"
                )
        except BaseException:
            self.close()
            raise
        self._next += 1
        return self._loader._to_output(out)

    def close(self):
        if getattr(self, "_closed", False):
            return
        self._closed = True
        for r in self._rings:
            r.close()
        for p in self._procs:
            if p.is_alive():
                p.terminate()
        for r in self._rings:
            r.detach()
            r.unlink()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
