"""paddle_tpu.io — datasets, samplers, DataLoader.

ref: python/paddle/io/ — Dataset/IterableDataset (dataset.py),
Sampler/RandomSampler/BatchSampler (batch_sampler.py, sampler.py),
DataLoader (reader.py:216, dataloader/dataloader_iter.py).

TPU-native redesign: the reference's multiprocess worker pool exists to
hide CPU decode latency behind GPU kernels launched from the same
process. On TPU the input pipeline instead needs (a) per-host sharding
(each host feeds its own chips — DistributedBatchSampler), (b) batches
landing as device arrays ready for jit donation, and (c) background
prefetch so host step N+1 overlaps device step N. Threads suffice for
(c) because the work is numpy/IO, which releases the GIL; a
C-extension ring buffer is unnecessary where there is no CUDA stream
to synchronize with.
"""
from __future__ import annotations

from .dataset import (  # noqa: F401
    ChainDataset,
    ComposeDataset,
    ConcatDataset,
    Dataset,
    IterableDataset,
    Subset,
    TensorDataset,
    random_split,
)
from .sampler import (  # noqa: F401
    BatchSampler,
    DistributedBatchSampler,
    RandomSampler,
    Sampler,
    SequenceSampler,
    SubsetRandomSampler,
    WeightedRandomSampler,
)
from .dataloader import DataLoader, default_collate_fn, default_convert_fn  # noqa: F401

__all__ = [
    "get_worker_info", "WorkerInfo",
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "ConcatDataset", "Subset", "random_split",
    "Sampler", "SequenceSampler", "RandomSampler", "SubsetRandomSampler",
    "WeightedRandomSampler", "BatchSampler", "DistributedBatchSampler",
    "DataLoader", "default_collate_fn", "default_convert_fn",
]


class WorkerInfo:
    """ref: io/dataloader/worker.py WorkerInfo."""

    def __init__(self, id, num_workers, dataset=None, seed=0):  # noqa: A002
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


_worker_info = None


def get_worker_info():
    """Inside a DataLoader worker returns its WorkerInfo, else None
    (ref: io/dataloader/worker.py get_worker_info). The shm-ring
    process workers set this before running the worker loop."""
    return _worker_info
