"""Dataset abstractions (ref: python/paddle/io/dataset.py).

Map-style datasets implement ``__getitem__``/``__len__``; iterable
datasets implement ``__iter__``. Composition helpers mirror the
reference set exactly.
"""
from __future__ import annotations

import bisect
from typing import List, Sequence

import numpy as np

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "ConcatDataset", "Subset", "random_split",
]


class Dataset:
    """Map-style dataset base (ref: io/dataset.py Dataset)."""

    def __getitem__(self, idx):
        raise NotImplementedError(
            f"{type(self).__name__} must implement __getitem__"
        )

    def __len__(self):
        raise NotImplementedError(f"{type(self).__name__} must implement __len__")


class IterableDataset(Dataset):
    """Stream-style dataset base (ref: io/dataset.py IterableDataset)."""

    def __iter__(self):
        raise NotImplementedError(f"{type(self).__name__} must implement __iter__")

    def __getitem__(self, idx):
        raise TypeError("IterableDataset does not support indexing")

    def __len__(self):
        # TypeError (not RuntimeError) so list()/length_hint probing
        # treats this as "no length" instead of propagating
        raise TypeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    """Wraps equal-length tensors; item i is the tuple of row i
    (ref: io/dataset.py TensorDataset)."""

    def __init__(self, tensors: Sequence):
        from ..base.tensor import Tensor

        if not tensors:
            raise ValueError("TensorDataset needs at least one tensor")
        self.tensors = list(tensors)
        self._arrays = [
            np.asarray(t.numpy() if isinstance(t, Tensor) else t) for t in tensors
        ]
        n = len(self._arrays[0])
        if any(len(a) != n for a in self._arrays):
            raise ValueError("all tensors must have the same first dimension")

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self._arrays)

    def __len__(self):
        return len(self._arrays[0])


class ComposeDataset(Dataset):
    """Zip datasets: item i concatenates each dataset's fields
    (ref: io/dataset.py ComposeDataset)."""

    def __init__(self, datasets: Sequence[Dataset]):
        self.datasets = list(datasets)
        if not self.datasets:
            raise ValueError("ComposeDataset needs at least one dataset")
        n = len(self.datasets[0])
        if any(len(d) != n for d in self.datasets):
            raise ValueError("all datasets must have the same length")

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    """Concatenate iterable datasets (ref: io/dataset.py ChainDataset)."""

    def __init__(self, datasets: Sequence[IterableDataset]):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    """Concatenate map-style datasets (ref: io/dataset.py ConcatDataset)."""

    def __init__(self, datasets: Sequence[Dataset]):
        self.datasets = list(datasets)
        if not self.datasets:
            raise ValueError("ConcatDataset needs at least one dataset")
        self.cumulative_sizes: List[int] = []
        s = 0
        for d in self.datasets:
            s += len(d)
            self.cumulative_sizes.append(s)

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        di = bisect.bisect_right(self.cumulative_sizes, idx)
        start = self.cumulative_sizes[di - 1] if di > 0 else 0
        return self.datasets[di][idx - start]


class Subset(Dataset):
    """View of a dataset at selected indices (ref: io/dataset.py Subset)."""

    def __init__(self, dataset: Dataset, indices: Sequence[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset: Dataset, lengths: Sequence, generator=None):
    """Split into non-overlapping subsets (ref: io/dataset.py
    random_split). Accepts absolute lengths or fractions summing to 1."""
    n = len(dataset)
    lengths = list(lengths)
    if all(0 < l < 1 for l in lengths) and abs(sum(lengths) - 1.0) < 1e-6:
        sizes = [int(np.floor(n * frac)) for frac in lengths]
        for i in range(n - sum(sizes)):
            sizes[i % len(sizes)] += 1
        lengths = sizes
    if sum(lengths) != n:
        raise ValueError(
            f"sum of lengths {sum(lengths)} != dataset length {n}"
        )
    from ..base import random as _random

    if generator is not None:
        perm = np.asarray(generator.permutation(n))
    else:
        import jax

        perm = np.asarray(jax.random.permutation(_random.next_key(), n))
    out, offset = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset : offset + l].tolist()))
        offset += l
    return out
