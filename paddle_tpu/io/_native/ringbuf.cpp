// Shared-memory ring buffer for DataLoader worker→parent sample
// transport.
//
// ref: the reference's multiprocess DataLoader moves samples through
// shared-memory LoDTensors (paddle/fluid/memory/allocation/
// mmap_allocator.h + fluid/imperative/data_loader.cc): workers
// serialize into POSIX shm and the parent maps them zero-copy. This is
// the TPU build's equivalent: one byte-ring per loader in POSIX shm,
// process-shared pthread mutex/cond for blocking push/pop, length-
// prefixed messages. The parent feeds jnp.asarray straight from the
// popped buffer — one copy host-side, none extra.
//
// Build: g++ -O2 -shared -fPIC -o _ringbuf.so ringbuf.cpp -lpthread
// (driven by paddle_tpu/io/shm_ring.py at first use, cached next to
// this file).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Header {
  uint64_t capacity;   // payload area size in bytes
  uint64_t head;       // read offset  (bytes consumed)
  uint64_t tail;       // write offset (bytes produced)
  uint32_t closed;     // writers done
  uint32_t magic;
  pthread_mutex_t mu;
  pthread_cond_t not_empty;
  pthread_cond_t not_full;
};

constexpr uint32_t kMagic = 0x52494e47;  // "RING"

struct Ring {
  Header* h;
  uint8_t* data;
  uint64_t map_size;
  int fd;
};

inline uint64_t used(const Header* h) { return h->tail - h->head; }

// lock handling EOWNERDEAD from a died holder; marks state consistent
// and closes the stream (the framing may be torn if the holder died
// mid-push, so consumers see end-of-stream instead of garbage)
inline void recover_dead_owner(Header* h) {
  pthread_mutex_consistent(&h->mu);
  h->closed = 1;  // conservatively end the stream; framing may be torn
  pthread_cond_broadcast(&h->not_empty);
  pthread_cond_broadcast(&h->not_full);
}

inline int lock_robust(Header* h) {
  int rc = pthread_mutex_lock(&h->mu);
  if (rc == EOWNERDEAD) {
    recover_dead_owner(h);
    return 0;
  }
  return rc;
}

// timedwait that recovers EOWNERDEAD (the wait reacquires the mutex and
// can observe a holder's death just like lock does)
inline int wait_robust(pthread_cond_t* cv, Header* h, const timespec* ts) {
  int rc = pthread_cond_timedwait(cv, &h->mu, ts);
  if (rc == EOWNERDEAD) {
    recover_dead_owner(h);
    return 0;
  }
  return rc;
}

void abs_deadline(double timeout_s, timespec* ts) {
  clock_gettime(CLOCK_MONOTONIC, ts);
  time_t sec = static_cast<time_t>(timeout_s);
  long nsec = static_cast<long>((timeout_s - sec) * 1e9);
  ts->tv_sec += sec;
  ts->tv_nsec += nsec;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

// copy in/out across the ring wrap point
void ring_write(Ring* r, uint64_t pos, const uint8_t* src, uint64_t len) {
  uint64_t off = pos % r->h->capacity;
  uint64_t first = len < r->h->capacity - off ? len : r->h->capacity - off;
  memcpy(r->data + off, src, first);
  if (len > first) memcpy(r->data, src + first, len - first);
}

void ring_read(Ring* r, uint64_t pos, uint8_t* dst, uint64_t len) {
  uint64_t off = pos % r->h->capacity;
  uint64_t first = len < r->h->capacity - off ? len : r->h->capacity - off;
  memcpy(dst, r->data + off, first);
  if (len > first) memcpy(dst + len - (len - first), r->data, len - first);
}

}  // namespace

extern "C" {

// returns opaque handle or nullptr
void* rb_create(const char* name, uint64_t capacity) {
  shm_unlink(name);  // stale segment from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t map_size = sizeof(Header) + capacity;
  if (ftruncate(fd, static_cast<off_t>(map_size)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, map_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  Header* h = static_cast<Header*>(mem);
  h->capacity = capacity;
  h->head = 0;
  h->tail = 0;
  h->closed = 0;
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  // robust: a worker SIGKILLed while holding the lock must not
  // deadlock the parent — EOWNERDEAD is recovered in lock_robust()
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_condattr_setclock(&ca, CLOCK_MONOTONIC);
  pthread_cond_init(&h->not_empty, &ca);
  pthread_cond_init(&h->not_full, &ca);
  h->magic = kMagic;
  Ring* r = new Ring{h, reinterpret_cast<uint8_t*>(mem) + sizeof(Header),
                     map_size, fd};
  return r;
}

void* rb_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, static_cast<size_t>(st.st_size),
                   PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Header* h = static_cast<Header*>(mem);
  if (h->magic != kMagic) {
    munmap(mem, static_cast<size_t>(st.st_size));
    close(fd);
    return nullptr;
  }
  Ring* r = new Ring{h, reinterpret_cast<uint8_t*>(mem) + sizeof(Header),
                     static_cast<uint64_t>(st.st_size), fd};
  return r;
}

// 0 ok, -1 timeout, -2 closed, -3 message larger than capacity
int rb_push(void* handle, const uint8_t* data, uint64_t len, double timeout_s) {
  Ring* r = static_cast<Ring*>(handle);
  Header* h = r->h;
  uint64_t need = len + sizeof(uint32_t);
  // the length prefix is 32-bit; reject anything it cannot represent
  if (need > h->capacity || len > 0xffffffffull) return -3;
  timespec ts;
  abs_deadline(timeout_s, &ts);
  lock_robust(h);
  while (h->capacity - used(h) < need) {
    if (h->closed) {
      pthread_mutex_unlock(&h->mu);
      return -2;
    }
    if (wait_robust(&h->not_full, h, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
  }
  uint32_t len32 = static_cast<uint32_t>(len);
  ring_write(r, h->tail, reinterpret_cast<uint8_t*>(&len32), sizeof(len32));
  ring_write(r, h->tail + sizeof(len32), data, len);
  h->tail += need;
  pthread_cond_signal(&h->not_empty);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// returns payload length (>=0), -1 timeout, -2 closed-and-drained,
// -4 out buffer too small (message left in place; query with rb_peek_len)
int64_t rb_pop(void* handle, uint8_t* out, uint64_t out_cap, double timeout_s) {
  Ring* r = static_cast<Ring*>(handle);
  Header* h = r->h;
  timespec ts;
  abs_deadline(timeout_s, &ts);
  lock_robust(h);
  while (used(h) == 0) {
    if (h->closed) {
      pthread_mutex_unlock(&h->mu);
      return -2;
    }
    if (wait_robust(&h->not_empty, h, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
  }
  uint32_t len32 = 0;
  ring_read(r, h->head, reinterpret_cast<uint8_t*>(&len32), sizeof(len32));
  if (len32 > out_cap) {
    pthread_mutex_unlock(&h->mu);
    return -4;
  }
  ring_read(r, h->head + sizeof(len32), out, len32);
  h->head += len32 + sizeof(len32);
  pthread_cond_signal(&h->not_full);
  pthread_mutex_unlock(&h->mu);
  return static_cast<int64_t>(len32);
}

int64_t rb_peek_len(void* handle) {
  Ring* r = static_cast<Ring*>(handle);
  Header* h = r->h;
  lock_robust(h);
  int64_t out = -1;
  if (used(h) > 0) {
    uint32_t len32 = 0;
    ring_read(r, h->head, reinterpret_cast<uint8_t*>(&len32), sizeof(len32));
    out = static_cast<int64_t>(len32);
  }
  pthread_mutex_unlock(&h->mu);
  return out;
}

void rb_close(void* handle) {  // writer side: no more pushes
  Ring* r = static_cast<Ring*>(handle);
  lock_robust(r->h);
  r->h->closed = 1;
  pthread_cond_broadcast(&r->h->not_empty);
  pthread_cond_broadcast(&r->h->not_full);
  pthread_mutex_unlock(&r->h->mu);
}

void rb_detach(void* handle) {
  Ring* r = static_cast<Ring*>(handle);
  munmap(r->h, r->map_size);
  close(r->fd);
  delete r;
}

void rb_unlink(const char* name) { shm_unlink(name); }

}  // extern "C"
