"""Samplers and batch samplers (ref: python/paddle/io/sampler.py,
batch_sampler.py).

DistributedBatchSampler is the TPU input-sharding primitive: each host
(or each data-parallel rank on a virtual mesh) reads only its slice, the
same role the reference gives it for multi-GPU (batch_sampler.py
DistributedBatchSampler).
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np

__all__ = [
    "Sampler", "SequenceSampler", "RandomSampler", "SubsetRandomSampler",
    "WeightedRandomSampler", "BatchSampler", "DistributedBatchSampler",
]


def _np_rng():
    """Host-side numpy RNG seeded from the framework generator, so
    paddle.seed() reproduces shuffles without consuming device RNG."""
    import jax

    from ..base import random as _random

    key_data = np.asarray(jax.random.key_data(_random.next_key()))
    return np.random.default_rng(key_data.astype(np.uint32))


class Sampler:
    """Index-sequence base (ref: sampler.py Sampler)."""

    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self) -> Iterator[int]:
        raise NotImplementedError

    def __len__(self) -> int:
        return len(self.data_source)


class SequenceSampler(Sampler):
    """0..n-1 in order (ref: sampler.py SequenceSampler)."""

    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    """Uniform permutation, optionally with replacement
    (ref: sampler.py RandomSampler)."""

    def __init__(self, data_source, replacement: bool = False,
                 num_samples: Optional[int] = None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator
        if not replacement and num_samples is not None and num_samples > len(data_source):
            raise ValueError("num_samples > dataset size requires replacement=True")

    @property
    def num_samples(self) -> int:
        return self._num_samples if self._num_samples is not None else len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.generator is not None:
            it = iter(self.generator)
            for _ in range(self.num_samples):
                try:
                    yield int(next(it))
                except StopIteration:
                    return
            return
        rng = _np_rng()
        if self.replacement:
            yield from rng.integers(0, n, self.num_samples).tolist()
        else:
            yield from rng.permutation(n)[: self.num_samples].tolist()

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    """Permutation of a fixed index set (ref: sampler.py)."""

    def __init__(self, indices: Sequence[int]):
        super().__init__(None)
        self.indices = list(indices)

    def __iter__(self):
        rng = _np_rng()
        for i in rng.permutation(len(self.indices)):
            yield self.indices[i]

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    """Draw by weight (ref: sampler.py WeightedRandomSampler)."""

    def __init__(self, weights: Sequence[float], num_samples: int, replacement: bool = True):
        super().__init__(None)
        self.weights = np.asarray(weights, np.float64)
        if self.weights.ndim != 1 or (self.weights < 0).any():
            raise ValueError("weights must be a 1-D non-negative sequence")
        self.num_samples = int(num_samples)
        self.replacement = bool(replacement)
        if not self.replacement and self.num_samples > len(self.weights):
            raise ValueError("num_samples > len(weights) requires replacement")

    def __iter__(self):
        rng = _np_rng()
        p = self.weights / self.weights.sum()
        idx = rng.choice(len(self.weights), self.num_samples,
                         replace=self.replacement, p=p)
        yield from idx.tolist()

    def __len__(self):
        return self.num_samples


def _group_batches(indices, batch_size: int, drop_last: bool) -> Iterator[List[int]]:
    batch: List[int] = []
    for idx in indices:
        batch.append(idx)
        if len(batch) == batch_size:
            yield batch
            batch = []
    if batch and not drop_last:
        yield batch


class BatchSampler(Sampler):
    """Group sampler indices into batches (ref: batch_sampler.py:23).

    Accepts either a dataset (with shuffle flag) or an explicit sampler,
    mirroring the reference's dual constructor.
    """

    def __init__(self, dataset=None, sampler=None, shuffle: bool = False,
                 batch_size: int = 1, drop_last: bool = False):
        if (dataset is None) == (sampler is None):
            raise ValueError("exactly one of dataset / sampler must be given")
        if sampler is not None:
            self.sampler = sampler
        else:
            self.sampler = RandomSampler(dataset) if shuffle else SequenceSampler(dataset)
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        self.batch_size = int(batch_size)
        self.drop_last = bool(drop_last)

    def __iter__(self) -> Iterator[List[int]]:
        yield from _group_batches(self.sampler, self.batch_size, self.drop_last)

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Per-rank slice of the (optionally shuffled) index space
    (ref: batch_sampler.py DistributedBatchSampler:179).

    ``set_epoch`` reseeds the shuffle so every epoch has a distinct but
    rank-consistent permutation — identical semantics to the reference.
    """

    def __init__(self, dataset, batch_size: int, num_replicas: Optional[int] = None,
                 rank: Optional[int] = None, shuffle: bool = False, drop_last: bool = False):
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        self.dataset = dataset
        self.sampler = None  # index stream is computed per-epoch in __iter__
        self.batch_size = int(batch_size)
        self.shuffle = bool(shuffle)
        self.drop_last = bool(drop_last)
        if num_replicas is None or rank is None:
            from ..distributed.parallel import ParallelEnv

            env = ParallelEnv()
            num_replicas = env.world_size if num_replicas is None else num_replicas
            rank = env.rank if rank is None else rank
        if not 0 <= rank < num_replicas:
            raise ValueError(f"rank {rank} out of range for {num_replicas} replicas")
        self.nranks = int(num_replicas)
        self.local_rank = int(rank)
        self.epoch = 0
        n = len(dataset)
        if self.drop_last:
            self.num_samples = n // self.nranks
        else:
            self.num_samples = (n + self.nranks - 1) // self.nranks
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch: int):
        self.epoch = int(epoch)

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.epoch)
            indices = rng.permutation(n)
        indices = indices.tolist()
        if not indices:
            return
        if not self.drop_last:
            # pad to total_size by wrapping (reference pads with head);
            # loop because tiny datasets may need multiple wraps
            while len(indices) < self.total_size:
                indices += indices[: self.total_size - len(indices)]
        else:
            indices = indices[: self.total_size]
        local = indices[self.local_rank : self.total_size : self.nranks]
        assert len(local) == self.num_samples
        yield from _group_batches(local, self.batch_size, self.drop_last)

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size
