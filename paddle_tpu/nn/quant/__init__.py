"""paddle.nn.quant — weight-only quantization for inference
(ref: python/paddle/nn/quant/__init__.py: Stub, weight_only_linear,
llm_int8_linear, weight_quantize, weight_dequantize).

TPU-native: int8/int4 weight-only quantization stores packed int
weights + per-channel scales; the matmul path dequantizes on the fly
(XLA fuses the dequant into the MXU feed — the role the cutlass
weight-only kernels play in the reference)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...base.tape import apply
from ...base.tensor import Tensor
from ..layer.layers import Layer

__all__ = ["Stub", "weight_only_linear", "llm_int8_linear",
           "weight_quantize", "weight_dequantize"]


class Stub(Layer):
    """ref: nn/quant/stub.py Stub — a placeholder the quantizer swaps
    for an observer/quanter; identity until configured."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, x):
        return self._observer(x) if self._observer is not None else x


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """Quantize a [in, out] weight to int8/int4 with per-out-channel
    absmax scales (ref: nn/quant/quantized_linear.py weight_quantize)."""
    if algo not in ("weight_only_int8", "weight_only_int4", "llm.int8"):
        raise ValueError(f"unsupported algo {algo!r}")
    bits = 4 if algo == "weight_only_int4" else 8
    qmax = (1 << (bits - 1)) - 1

    def _f(w):
        scale = jnp.max(jnp.abs(w), axis=0) / qmax
        q = jnp.clip(jnp.round(w / jnp.maximum(scale, 1e-9)), -qmax - 1, qmax)
        return q.astype(jnp.int8), scale.astype(jnp.float32)

    return apply(_f, x, op_name="weight_quantize")


def weight_dequantize(x, scale, algo="weight_only_int8", out_dtype="float16"):
    """ref: quantized_linear.py weight_dequantize."""
    from ...base.dtype import canonical_dtype

    dt = canonical_dtype(out_dtype)
    return apply(
        lambda q, s: (q.astype(jnp.float32) * s).astype(dt),
        x, scale, op_name="weight_dequantize",
    )


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """y = x @ dequant(weight) + bias (ref: quantized_linear.py
    weight_only_linear). The dequant fuses into the matmul under XLA."""

    def _f(a, q, s, *maybe_b):
        w = q.astype(a.dtype) * s.astype(a.dtype)
        out = a @ w
        if maybe_b:
            out = out + maybe_b[0]
        return out

    args = (x, weight, weight_scale) + ((bias,) if bias is not None else ())
    return apply(_f, *args, op_name="weight_only_linear")


def llm_int8_linear(x, weight, bias=None, weight_scale=None, threshold=6.0):
    """ref: quantized_linear.py llm_int8_linear. The reference splits
    outlier activation columns onto fp16 weights to avoid int8-arithmetic
    error; on TPU the weight is dequantized into the matmul anyway (the
    MXU computes in bf16/f32), so a single dequantized matmul IS the
    numerically-higher-precision path and the outlier split would only
    duplicate work — ``threshold`` is accepted for signature parity."""

    def _f(a, q, s, *maybe_b):
        w = q.astype(a.dtype) * s.astype(a.dtype)
        out = a @ w
        if maybe_b:
            out = out + maybe_b[0]
        return out

    args = (x, weight, weight_scale) + ((bias,) if bias is not None else ())
    return apply(_f, *args, op_name="llm_int8_linear")
