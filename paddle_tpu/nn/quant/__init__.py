"""paddle.nn.quant — weight-only quantization for inference
(ref: python/paddle/nn/quant/__init__.py: Stub, weight_only_linear,
llm_int8_linear, weight_quantize, weight_dequantize).

TPU-native: int8/int4 weight-only quantization stores packed int
weights + per-channel scales; the matmul path dequantizes on the fly
(XLA fuses the dequant into the MXU feed — the role the cutlass
weight-only kernels play in the reference)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...base.tape import apply
from ...base.tensor import Tensor
from ..layer.layers import Layer

__all__ = ["Stub", "weight_only_linear", "llm_int8_linear",
           "weight_quantize", "weight_dequantize", "int8_dynamic_matmul"]


class Stub(Layer):
    """ref: nn/quant/stub.py Stub — a placeholder the quantizer swaps
    for an observer/quanter; identity until configured."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, x):
        return self._observer(x) if self._observer is not None else x


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """Quantize a [in, out] weight to int8/int4 with per-out-channel
    absmax scales (ref: nn/quant/quantized_linear.py weight_quantize)."""
    if algo not in ("weight_only_int8", "weight_only_int4", "llm.int8"):
        raise ValueError(f"unsupported algo {algo!r}")
    bits = 4 if algo == "weight_only_int4" else 8
    qmax = (1 << (bits - 1)) - 1

    def _f(w):
        scale = jnp.max(jnp.abs(w), axis=0) / qmax
        q = jnp.clip(jnp.round(w / jnp.maximum(scale, 1e-9)), -qmax - 1, qmax)
        return q.astype(jnp.int8), scale.astype(jnp.float32)

    return apply(_f, x, op_name="weight_quantize")


def weight_dequantize(x, scale, algo="weight_only_int8", out_dtype="float16"):
    """ref: quantized_linear.py weight_dequantize."""
    from ...base.dtype import canonical_dtype

    dt = canonical_dtype(out_dtype)
    return apply(
        lambda q, s: (q.astype(jnp.float32) * s).astype(dt),
        x, scale, op_name="weight_dequantize",
    )


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """y = x @ dequant(weight) + bias (ref: quantized_linear.py
    weight_only_linear). The dequant fuses into the matmul under XLA."""

    def _f(a, q, s, *maybe_b):
        w = q.astype(a.dtype) * s.astype(a.dtype)
        out = a @ w
        if maybe_b:
            out = out + maybe_b[0]
        return out

    args = (x, weight, weight_scale) + ((bias,) if bias is not None else ())
    return apply(_f, *args, op_name="weight_only_linear")


def int8_dynamic_matmul(a, q, s, outlier_threshold=None, max_outliers=16):
    """Raw-jnp int8 execution core: dynamically quantize activations per
    row, run the int8 x int8 -> int32 dot on the MXU, rescale by
    act_scale * weight_scale. With ``outlier_threshold``, the llm.int8
    decomposition (arXiv:2208.07339): the top-``max_outliers`` activation
    feature columns whose magnitude exceeds the threshold are carried in
    a small float matmul instead (static K — TPU-friendly; the
    reference gathers a dynamic outlier set into cutlass fp16)."""
    in_f = a.shape[-1]
    extra = None
    if outlier_threshold is not None:
        k = min(max_outliers, in_f)
        flat = jnp.abs(a.reshape(-1, in_f))
        col_max = jnp.max(flat, axis=0)
        top_vals, idx = jax.lax.top_k(col_max, k)
        sel = top_vals > outlier_threshold  # [k]
        outlier_mask = jnp.zeros((in_f,), bool).at[idx].set(sel)
        a_main = jnp.where(outlier_mask, 0.0, a)
        a_out = jnp.take(a, idx, axis=-1) * sel.astype(a.dtype)  # [.., k]
        w_out = q[idx].astype(jnp.float32) * s.astype(jnp.float32)  # [k, out]
        extra = a_out.astype(jnp.float32) @ w_out
    else:
        a_main = a
    act_scale = jnp.maximum(
        jnp.max(jnp.abs(a_main), axis=-1, keepdims=True) / 127.0, 1e-9
    )
    qa = jnp.clip(jnp.round(a_main / act_scale), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        qa, q, (((qa.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    out = acc.astype(jnp.float32) * act_scale.astype(jnp.float32) * s.astype(jnp.float32)
    if extra is not None:
        out = out + extra
    return out.astype(a.dtype)


def llm_int8_linear(x, weight, bias=None, weight_scale=None, threshold=6.0):
    """llm.int8 linear with REAL int8 arithmetic (ref:
    quantized_linear.py llm_int8_linear; kernels
    paddle/phi/kernels/impl/llm_int8_matmul_kernel_impl.h): activations
    are dynamically quantized per row and the main product runs as an
    int8 x int8 -> int32 MXU dot; activation feature columns above
    ``threshold`` take the float path (static top-K decomposition;
    ``threshold=None`` disables the split).

    Gradients: the int8 round/clip has zero derivative, so when the
    input requires grad (e.g. LoRA over a frozen int8 base) the op runs
    a straight-through estimator — value from the int8 dot, gradient
    from the dequantized float matmul (one extra matmul, paid only in
    differentiating contexts). Pure inference stays int8-only."""
    from ...base import tape as _tape

    def _int8(a, q, s, *maybe_b):
        out = int8_dynamic_matmul(a, q, s, outlier_threshold=threshold)
        if maybe_b:
            out = out + maybe_b[0]
        return out

    def _ste(a, q, s, *maybe_b):
        out_i = int8_dynamic_matmul(a, q, s, outlier_threshold=threshold)
        w = q.astype(jnp.float32) * s.astype(jnp.float32)
        out_f = (a.astype(jnp.float32) @ w).astype(a.dtype)
        # value == int8 result exactly; gradient == float matmul's
        out = out_f + jax.lax.stop_gradient(out_i - out_f)
        if maybe_b:
            out = out + maybe_b[0]
        return out

    def _diff(t):
        return (
            isinstance(t, Tensor) and not t.stop_gradient
        )

    needs_grad = _tape.is_grad_enabled() and any(
        _diff(t) for t in (x, weight, weight_scale, bias)
    )
    args = (x, weight, weight_scale) + ((bias,) if bias is not None else ())
    return apply(_ste if needs_grad else _int8, *args, op_name="llm_int8_linear")
