"""paddle.nn.quant — weight-only quantization for inference
(ref: python/paddle/nn/quant/__init__.py: Stub, weight_only_linear,
llm_int8_linear, weight_quantize, weight_dequantize).

TPU-native: int8/int4 weight-only quantization stores packed int
weights + per-channel scales; the matmul path dequantizes on the fly
(XLA fuses the dequant into the MXU feed — the role the cutlass
weight-only kernels play in the reference)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...base.tape import apply
from ...base.tensor import Tensor
from ..layer.layers import Layer

__all__ = ["Stub", "weight_only_linear", "llm_int8_linear",
    "WeightOnlyLinear", "convert_to_weight_only",
           "weight_quantize", "weight_dequantize", "int8_dynamic_matmul"]


class Stub(Layer):
    """ref: nn/quant/stub.py Stub — a placeholder the quantizer swaps
    for an observer/quanter; identity until configured."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, x):
        return self._observer(x) if self._observer is not None else x


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """Quantize a [in, out] weight (ref: nn/quant/quantized_linear.py:39
    weight_quantize).

    - int8: per-out-channel absmax scales, stored unpacked.
    - int4: values in [-8, 7] PACKED two-per-byte along the in axis
      ([in/2, out] int8 — the serving win is the halved HBM weight
      stream), with per-out-channel scales (group_size=-1) or
      group-wise scales over the in axis (group_size 64/128, scale
      shape [in/group, out] — the GroupWiseWeightObserver layout).
    """
    if algo not in ("weight_only_int8", "weight_only_int4", "llm.int8"):
        raise ValueError(f"unsupported algo {algo!r}")
    if algo != "weight_only_int4":
        def _f8(w):
            scale = jnp.max(jnp.abs(w), axis=0) / 127.0
            q = jnp.clip(jnp.round(w / jnp.maximum(scale, 1e-9)), -128, 127)
            return q.astype(jnp.int8), scale.astype(jnp.float32)

        return apply(_f8, x, op_name="weight_quantize")

    cin = int(x.shape[0])
    if group_size not in (-1, 64, 128):
        raise ValueError("group_size supports -1, 64 or 128")
    if cin % 2:
        raise ValueError("int4 packing needs an even input dim")
    if group_size > 0 and cin % group_size:
        raise ValueError(f"group_size {group_size} must divide in={cin}")

    def _f4(w):
        if group_size > 0:
            g = w.reshape(cin // group_size, group_size, -1)
            scale = jnp.max(jnp.abs(g), axis=1) / 7.0  # [in/gs, out]
            sc = jnp.repeat(jnp.maximum(scale, 1e-9), group_size, axis=0)
        else:
            scale = jnp.max(jnp.abs(w), axis=0) / 7.0  # [out]
            sc = jnp.maximum(scale, 1e-9)
        q = jnp.clip(jnp.round(w / sc), -8, 7).astype(jnp.int32)
        # pack: byte = (q[2i] & 0xF) | (q[2i+1] << 4)
        lo = q[0::2] & 0xF
        hi = (q[1::2] & 0xF) << 4
        packed = (lo | hi).astype(jnp.uint8).view(jnp.int8)
        return packed, scale.astype(jnp.float32)

    return apply(_f4, x, op_name="weight_quantize_int4")


def _unpack_int4(packed):
    """[in/2, out] packed int8 -> [in, out] int8 values in [-8, 7]."""
    u = packed.view(jnp.uint8).astype(jnp.int32)
    lo = (u & 0xF)
    hi = (u >> 4) & 0xF
    # sign-extend 4-bit two's complement
    lo = jnp.where(lo >= 8, lo - 16, lo)
    hi = jnp.where(hi >= 8, hi - 16, hi)
    n2, out = packed.shape
    return jnp.stack([lo, hi], axis=1).reshape(2 * n2, out).astype(jnp.int8)


def _dequant_weight(q, s, weight_dtype, group_size, dtype):
    if weight_dtype == "int4":
        q = _unpack_int4(q)
    if s.ndim == 2:  # group-wise [in/gs, out]
        gs = q.shape[0] // s.shape[0]
        s = jnp.repeat(s, gs, axis=0)
    return q.astype(dtype) * s.astype(dtype)


def weight_dequantize(x, scale, algo="weight_only_int8", out_dtype="float16"):
    """ref: quantized_linear.py weight_dequantize."""
    from ...base.dtype import canonical_dtype

    dt = canonical_dtype(out_dtype)
    wd = "int4" if algo == "weight_only_int4" else "int8"
    return apply(
        lambda q, s: _dequant_weight(q, s, wd, -1, jnp.float32).astype(dt),
        x, scale, op_name="weight_dequantize",
    )


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """y = x @ dequant(weight) + bias (ref: quantized_linear.py:156
    weight_only_linear). int4 weights arrive PACKED ([in/2, out], see
    weight_quantize) with per-channel or group-wise scales; the unpack+
    dequant fuses into the matmul's operand load under XLA, so the HBM
    stream is the packed array — the bandwidth-bound decode win."""

    def _f(a, q, s, *maybe_b):
        w = _dequant_weight(q, s, weight_dtype, group_size, a.dtype)
        out = a @ w
        if maybe_b:
            out = out + maybe_b[0]
        return out

    args = (x, weight, weight_scale) + ((bias,) if bias is not None else ())
    return apply(_f, *args, op_name="weight_only_linear")


def int8_dynamic_matmul(a, q, s, outlier_threshold=None, max_outliers=16):
    """Raw-jnp int8 execution core: dynamically quantize activations per
    row, run the int8 x int8 -> int32 dot on the MXU, rescale by
    act_scale * weight_scale. With ``outlier_threshold``, the llm.int8
    decomposition (arXiv:2208.07339): the top-``max_outliers`` activation
    feature columns whose magnitude exceeds the threshold are carried in
    a small float matmul instead (static K — TPU-friendly; the
    reference gathers a dynamic outlier set into cutlass fp16)."""
    in_f = a.shape[-1]
    extra = None
    if outlier_threshold is not None:
        k = min(max_outliers, in_f)
        flat = jnp.abs(a.reshape(-1, in_f))
        col_max = jnp.max(flat, axis=0)
        top_vals, idx = jax.lax.top_k(col_max, k)
        sel = top_vals > outlier_threshold  # [k]
        outlier_mask = jnp.zeros((in_f,), bool).at[idx].set(sel)
        a_main = jnp.where(outlier_mask, 0.0, a)
        a_out = jnp.take(a, idx, axis=-1) * sel.astype(a.dtype)  # [.., k]
        w_out = q[idx].astype(jnp.float32) * s.astype(jnp.float32)  # [k, out]
        extra = a_out.astype(jnp.float32) @ w_out
    else:
        a_main = a
    act_scale = jnp.maximum(
        jnp.max(jnp.abs(a_main), axis=-1, keepdims=True) / 127.0, 1e-9
    )
    qa = jnp.clip(jnp.round(a_main / act_scale), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        qa, q, (((qa.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    out = acc.astype(jnp.float32) * act_scale.astype(jnp.float32) * s.astype(jnp.float32)
    if extra is not None:
        out = out + extra
    return out.astype(a.dtype)


def llm_int8_linear(x, weight, bias=None, weight_scale=None, threshold=6.0):
    """llm.int8 linear with REAL int8 arithmetic (ref:
    quantized_linear.py llm_int8_linear; kernels
    paddle/phi/kernels/impl/llm_int8_matmul_kernel_impl.h): activations
    are dynamically quantized per row and the main product runs as an
    int8 x int8 -> int32 MXU dot; activation feature columns above
    ``threshold`` take the float path (static top-K decomposition;
    ``threshold=None`` disables the split).

    Gradients: the int8 round/clip has zero derivative, so when the
    input requires grad (e.g. LoRA over a frozen int8 base) the op runs
    a straight-through estimator — value from the int8 dot, gradient
    from the dequantized float matmul (one extra matmul, paid only in
    differentiating contexts). Pure inference stays int8-only."""
    from ...base import tape as _tape

    def _int8(a, q, s, *maybe_b):
        out = int8_dynamic_matmul(a, q, s, outlier_threshold=threshold)
        if maybe_b:
            out = out + maybe_b[0]
        return out

    def _ste(a, q, s, *maybe_b):
        out_i = int8_dynamic_matmul(a, q, s, outlier_threshold=threshold)
        w = q.astype(jnp.float32) * s.astype(jnp.float32)
        out_f = (a.astype(jnp.float32) @ w).astype(a.dtype)
        # value == int8 result exactly; gradient == float matmul's
        out = out_f + jax.lax.stop_gradient(out_i - out_f)
        if maybe_b:
            out = out + maybe_b[0]
        return out

    def _diff(t):
        return (
            isinstance(t, Tensor) and not t.stop_gradient
        )

    needs_grad = _tape.is_grad_enabled() and any(
        _diff(t) for t in (x, weight, weight_scale, bias)
    )
    args = (x, weight, weight_scale) + ((bias,) if bias is not None else ())
    return apply(_ste if needs_grad else _int8, *args, op_name="llm_int8_linear")


class WeightOnlyLinear(Layer):
    """Inference Linear over frozen weight-only-quantized weights
    (ref: the deploy layer paddlenlp builds on quantized_linear.py:156;
    the functional contract is weight_only_linear above).

    - ``weight_dtype="int8"``: per-out-channel scales, unpacked int8.
    - ``weight_dtype="int4"``: weights PACKED two-per-byte ([in/2, out])
      with per-channel or group-wise scales — the weight HBM stream
      halves again vs int8, which is the whole game for small-batch
      decode. Dequant fuses into the matmul's operand load (XLA), so
      compute stays bf16 on the MXU.
    """

    def __init__(self, linear, weight_dtype: str = "int4",
                 group_size: int = -1):
        super().__init__()
        from ...base.tape import no_grad

        algo = ("weight_only_int4" if weight_dtype == "int4"
                else "weight_only_int8")
        with no_grad():
            q, s = weight_quantize(linear.weight, algo=algo,
                                   group_size=group_size)
        # deployment buffers: detached, non-differentiable (the float
        # weight must not stay alive through tape nodes)
        for t in (q, s):
            t._grad_node = None
            t.stop_gradient = True
        self.register_buffer("weight", q)  # packed for int4
        self.register_buffer("weight_scale", s)
        self.bias = linear.bias
        self.weight_dtype = weight_dtype
        self.group_size = group_size
        self._in_features = int(linear.weight.shape[0])
        self._out_features = int(linear.weight.shape[1])

    def forward(self, x):
        return weight_only_linear(
            x, self.weight, bias=self.bias, weight_scale=self.weight_scale,
            weight_dtype=self.weight_dtype, group_size=self.group_size,
        )

    def extra_repr(self):
        return (f"in={self._in_features}, out={self._out_features}, "
                f"weight_dtype={self.weight_dtype}, gs={self.group_size}")


def convert_to_weight_only(model, weight_dtype: str = "int4",
                           group_size: int = -1, exclude=lambda name: False):
    """Swap every nn.Linear in ``model`` for a WeightOnlyLinear holding
    quantized frozen weights (the weight-only deploy pass; int8's
    counterpart conversion lives in quantization.QAT.convert). Returns
    the number of layers converted."""
    from ..layer.common import Linear

    n = 0
    for name, sub in list(model.named_sublayers(include_self=False)):
        if not isinstance(sub, Linear) or exclude(name):
            continue
        if weight_dtype == "int4" and int(sub.weight.shape[0]) % 2:
            continue  # odd in-dim cannot pack
        parent = model
        parts = name.split(".")
        for p in parts[:-1]:
            parent = getattr(parent, p)
        setattr(parent, parts[-1],
                WeightOnlyLinear(sub, weight_dtype, group_size))
        n += 1
    return n
