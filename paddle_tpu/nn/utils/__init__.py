"""nn.utils (ref: python/paddle/nn/utils/__init__.py)."""
from ..clip import clip_grad_norm_, clip_grad_value_  # noqa: F401


def parameters_to_vector(parameters, name=None):
    from ...tensor import manipulation as M

    return M.concat([M.reshape(p, [-1]) for p in parameters], axis=0)


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        p.set_value(vec._data[offset : offset + n].reshape(p._data.shape))
        offset += n


def weight_norm(layer, name="weight", dim=0):
    raise NotImplementedError("weight_norm reparameterization: use SpectralNorm or explicit normalization")


def remove_weight_norm(layer, name="weight"):
    raise NotImplementedError
