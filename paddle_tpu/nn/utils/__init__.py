"""nn.utils (ref: python/paddle/nn/utils/__init__.py)."""
from ..clip import clip_grad_norm_, clip_grad_value_  # noqa: F401


def parameters_to_vector(parameters, name=None):
    from ...tensor import manipulation as M

    return M.concat([M.reshape(p, [-1]) for p in parameters], axis=0)


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        p.set_value(vec._data[offset : offset + n].reshape(p._data.shape))
        offset += n


def weight_norm(layer, name="weight", dim=0):
    raise NotImplementedError("weight_norm reparameterization: use SpectralNorm or explicit normalization")


def remove_weight_norm(layer, name="weight"):
    raise NotImplementedError


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12, dim=None):
    """Apply spectral normalization to a layer's weight via a forward
    pre-hook running power iteration (ref: nn/utils/spectral_norm_hook.py
    spectral_norm)."""
    import jax.numpy as jnp
    import numpy as np

    if dim is None:
        # reference: output-channel dim is 1 for Linear (weight is
        # [in, out]) and ConvTranspose ([in, out, ...]), else 0
        from ..layer.common import Linear as _Linear
        from ..layer.conv import (
            Conv1DTranspose as _C1T,
            Conv2DTranspose as _C2T,
            Conv3DTranspose as _C3T,
        )

        dim = 1 if isinstance(layer, (_Linear, _C1T, _C2T, _C3T)) else 0
    w0 = getattr(layer, name)
    mat0 = np.asarray(w0._data, np.float32)
    mat0 = np.moveaxis(mat0, dim, 0).reshape(mat0.shape[dim], -1)
    state = {"u": np.random.RandomState(0).randn(mat0.shape[0]).astype(np.float32)}

    def _pre_hook(l, inputs):
        w = getattr(l, name)
        mat = jnp.moveaxis(w._data, dim, 0)
        shape = mat.shape
        mat2 = mat.reshape(shape[0], -1)
        u = jnp.asarray(state["u"])
        # v always derives from the current u so 0 iterations is legal
        v = mat2.T @ u
        v = v / jnp.maximum(jnp.linalg.norm(v), eps)
        for _ in range(n_power_iterations):
            u = mat2 @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
            v = mat2.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
        state["u"] = np.asarray(u)
        sigma = u @ (mat2 @ v)
        wn = (mat2 / jnp.maximum(sigma, eps)).reshape(shape)
        w._data = jnp.moveaxis(wn, 0, dim).astype(w._data.dtype)
        return None

    layer.register_forward_pre_hook(_pre_hook)
    return layer
