"""Gradient clipping strategies.

ref: python/paddle/nn/clip.py (ClipGradByValue, ClipGradByNorm,
ClipGradByGlobalNorm). Each is a callable applied by the optimizer to
the (param, grad) list before the update; global-norm computes one
fp32 norm over all grads (single fused XLA reduction on TPU — and,
under hybrid parallel, the HybridParallelOptimizer wraps this with the
cross-mesh-axis allreduce, ref: hybrid_parallel_optimizer.py:255).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..base.tape import apply

__all__ = ["ClipGradBase", "ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm", "clip_grad_norm_", "clip_grad_value_"]


def _sq_sum(g):
    return apply(lambda a: jnp.sum(jnp.square(a.astype(jnp.float32))), g,
                 op_name="sq_sum")


class ClipGradBase:
    def __call__(self, params_grads):
        return self._clip(params_grads)

    def _clip(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):  # noqa: A002
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, apply(lambda a: jnp.clip(a, self.min, self.max), g, op_name="clip_by_value")))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue

            def _f(a):
                norm = jnp.sqrt(jnp.sum(jnp.square(a.astype(jnp.float32))))
                scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
                return (a.astype(jnp.float32) * scale).astype(a.dtype)

            out.append((p, apply(_f, g, op_name="clip_by_norm")))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm=1.0, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _total_sq(self, clippable):
        """Total fp32 squared grad norm — the aggregation seam
        expert-parallel variants override (moe.ClipGradForMOEByGlobalNorm
        allreduces the expert share over the ep group before summing)."""
        sq_sums = [_sq_sum(g) for _, g in clippable]
        total = sq_sums[0]
        for s in sq_sums[1:]:
            total = total + s
        return total

    def _clip(self, params_grads):
        clippable = [(p, g) for p, g in params_grads if g is not None and getattr(p, "need_clip", True)]
        if not clippable:
            return params_grads
        global_norm = apply(lambda t: jnp.sqrt(t), self._total_sq(clippable), op_name="global_norm")
        scale = apply(
            lambda n: jnp.minimum(self.clip_norm / jnp.maximum(n, 1e-12), 1.0),
            global_norm,
            op_name="clip_scale",
        )
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
            else:
                out.append((p, apply(lambda a, s: (a.astype(jnp.float32) * s).astype(a.dtype), g, scale, op_name="apply_clip")))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    """torch-style utility (ref: python/paddle/nn/utils/clip_grad_norm_.py)."""
    if not isinstance(parameters, (list, tuple)):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return None
    import numpy as np

    if norm_type == float("inf"):
        total = max(float(jnp.max(jnp.abs(g._data))) for g in grads)
    else:
        total = float(
            sum(jnp.sum(jnp.abs(g._data.astype(jnp.float32)) ** norm_type) for g in grads)
            ** (1.0 / norm_type)
        )
    scale = max_norm / (total + 1e-6)
    if scale < 1.0:
        for p in parameters:
            if p.grad is not None:
                p.grad._data = (p.grad._data.astype(jnp.float32) * scale).astype(p.grad._data.dtype)
    from ..base.tensor import Tensor

    return Tensor(total, _internal=True)


def clip_grad_value_(parameters, clip_value):
    if not isinstance(parameters, (list, tuple)):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -clip_value, clip_value)
