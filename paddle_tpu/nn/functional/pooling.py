"""Pooling functionals over lax.reduce_window.

ref: python/paddle/nn/functional/pooling.py. XLA's reduce_window is the
single TPU primitive behind max/avg pooling (replaces the phi pool2d
kernel family); adaptive pools compute per-output windows statically.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...base.tape import apply
from ...base.tensor import Tensor

__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d",
    "max_pool1d", "max_pool2d", "max_pool3d",
    "adaptive_avg_pool1d", "adaptive_avg_pool2d", "adaptive_avg_pool3d",
    "adaptive_max_pool1d", "adaptive_max_pool2d", "adaptive_max_pool3d",
    "lp_pool1d", "lp_pool2d", "max_unpool1d", "max_unpool2d", "max_unpool3d",
    "fractional_max_pool2d", "fractional_max_pool3d",
]


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in (v if len(v) == n else list(v) * n)[:n])
    return (int(v),) * n


def _norm_pad(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if padding and isinstance(padding[0], (list, tuple)):
        return [tuple(int(x) for x in p) for p in padding][-n:]
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    raise ValueError(f"bad padding {padding!r}")


def _pool(x, kernel, stride, padding, n, reducer, init, data_format, ceil_mode, name,
          count_include_pad=True, average=False):
    ks = _tuple(kernel, n)
    st = _tuple(stride if stride is not None else kernel, n)
    pad = _norm_pad(padding, n)
    channels_first = data_format.startswith("NC")

    def _f(a):
        if channels_first:
            window = (1, 1) + ks
            strides = (1, 1) + st
            pads = ([(0, 0), (0, 0)] + list(pad)) if not isinstance(pad, str) else pad
        else:
            window = (1,) + ks + (1,)
            strides = (1,) + st + (1,)
            pads = ([(0, 0)] + list(pad) + [(0, 0)]) if not isinstance(pad, str) else pad
        if ceil_mode and not isinstance(pads, str):
            # extend high padding so the last partial window is included
            # (single source of truth with the return_mask index helpers)
            spatial_axes = list(range(2, 2 + n) if channels_first else range(1, 1 + n))
            sp_pads = _pool_pads(
                [a.shape[ax] for ax in spatial_axes],
                ks, st, [pads[ax] for ax in spatial_axes], True,
            )
            pads = list(pads)
            for ax, p2 in zip(spatial_axes, sp_pads):
                pads[ax] = p2
        if average:
            summed = jax.lax.reduce_window(a, 0.0 if jnp.issubdtype(a.dtype, jnp.floating) else 0, jax.lax.add, window, strides, pads)
            if count_include_pad and not isinstance(pads, str):
                denom = np.prod(ks)
                return summed / jnp.asarray(denom, a.dtype)
            ones = jnp.ones_like(a)
            counts = jax.lax.reduce_window(ones, 0.0 if jnp.issubdtype(a.dtype, jnp.floating) else 0, jax.lax.add, window, strides, pads)
            return summed / counts
        return jax.lax.reduce_window(a, init(a.dtype), reducer, window, strides, pads)

    return apply(_f, x, op_name=name)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 2, jax.lax.max,
                lambda dt: -jnp.inf if jnp.issubdtype(dt, jnp.floating) else int(np.iinfo(dt).min),
                data_format, ceil_mode, "max_pool2d")
    if return_mask:
        idx = _max_pool_indices(x, kernel_size, stride, padding,
                                data_format, ceil_mode)
        return out, idx
    return out


def _pool_pads(shape_sp, ks, st, pad, ceil_mode):
    """Per-spatial-dim (lo, hi) pads incl. the ceil-mode high extension
    — EXACTLY _pool's geometry, so (out, indices) shapes always agree."""
    pads = [tuple(p) for p in pad]
    if ceil_mode:
        for i in range(len(pads)):
            size = shape_sp[i] + pads[i][0] + pads[i][1]
            rem = (size - ks[i]) % st[i]
            if rem:
                pads[i] = (pads[i][0], pads[i][1] + st[i] - rem)
    return pads


def _neg_fill(dt):
    # finite lowest value, NOT -inf: the patch extraction is a one-hot
    # CONVOLUTION, and -inf * 0 = NaN would poison every window that
    # touches padding. Halved so low-precision rounding (bf16) cannot
    # tip it over to -inf.
    if jnp.issubdtype(dt, jnp.floating):
        try:
            lo = np.finfo(np.dtype(dt)).min
        except ValueError:  # ml_dtypes (bfloat16, ...)
            import ml_dtypes

            lo = ml_dtypes.finfo(dt).min
        return float(lo) * 0.5
    return int(np.iinfo(dt).min)


def _max_pool_indices(x, kernel_size, stride, padding, data_format,
                      ceil_mode=False):
    """Flat spatial argmax index per window (for max_unpool).

    The input is padded EXPLICITLY with -inf (the same fill the pooled
    reduce_window uses) before patch extraction — argmax can then never
    select a pad slot, so indices are always valid positions in the
    UNPADDED input (the zero-padded-patches variant returned negative /
    out-of-range indices on negative inputs)."""
    ks = _tuple(kernel_size, 2)
    st = _tuple(stride if stride is not None else kernel_size, 2)
    pad = _norm_pad(padding, 2)
    if isinstance(pad, str):
        raise ValueError("return_mask does not support string padding")

    def _f(a):
        N, C, H, W = a.shape
        pads = _pool_pads((H, W), ks, st, pad, ceil_mode)
        ap = jnp.pad(a, ((0, 0), (0, 0)) + tuple(pads),
                     constant_values=_neg_fill(a.dtype))
        patches = jax.lax.conv_general_dilated_patches(
            ap, ks, st, padding=[(0, 0), (0, 0)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )  # [N, C*kh*kw, oh, ow]
        oh, ow = patches.shape[2], patches.shape[3]
        patches = patches.reshape(N, C, ks[0] * ks[1], oh, ow)
        arg = jnp.argmax(patches, axis=2)  # [N, C, oh, ow] index inside window
        ky, kx = arg // ks[1], arg % ks[1]
        oy = jnp.arange(oh).reshape(1, 1, -1, 1)
        ox = jnp.arange(ow).reshape(1, 1, 1, -1)
        iy = oy * st[0] + ky - pads[0][0]
        ix = ox * st[1] + kx - pads[1][0]
        return (iy * W + ix).astype(jnp.int32)

    return apply(_f, x, op_name="max_pool2d_indices")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, None, None, data_format, ceil_mode,
                 "avg_pool2d", count_include_pad=not exclusive, average=True)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, name=None):
    out = _pool(x, kernel_size, stride, padding, 1, jax.lax.max,
                lambda dt: -jnp.inf if jnp.issubdtype(dt, jnp.floating) else int(np.iinfo(dt).min),
                "NCH", ceil_mode, "max_pool1d")
    if return_mask:
        # reuse the 2D argmax machinery over a singleton H dim; the flat
        # index of a [1, L] window IS the L index
        from ...tensor.manipulation import reshape as _rs

        x4 = _rs(x, [x.shape[0], x.shape[1], 1, x.shape[2]])
        idx = _max_pool_indices(
            x4, (1, _tuple(kernel_size, 1)[0]),
            (1, _tuple(stride if stride is not None else kernel_size, 1)[0]),
            (0, _tuple(padding, 1)[0]), "NCHW", ceil_mode,
        )
        idx = _rs(idx, [idx.shape[0], idx.shape[1], idx.shape[3]])
        return out, idx
    return out


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, None, None, "NCH", ceil_mode,
                 "avg_pool1d", count_include_pad=not exclusive, average=True)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 3, jax.lax.max,
                lambda dt: -jnp.inf if jnp.issubdtype(dt, jnp.floating) else int(np.iinfo(dt).min),
                data_format, ceil_mode, "max_pool3d")
    if return_mask:
        if data_format != "NCDHW":
            raise ValueError(
                "max_pool3d(return_mask=True) supports NCDHW only"
            )
        return out, _max_pool3d_indices(x, kernel_size, stride, padding,
                                        ceil_mode)
    return out


def _max_pool3d_indices(x, kernel_size, stride, padding, ceil_mode=False):
    """Flat spatial argmax index (d*H*W + h*W + w) per window — the 3D
    analogue of _max_pool_indices (same -inf padding + ceil geometry)."""
    ks = _tuple(kernel_size, 3)
    st = _tuple(stride if stride is not None else kernel_size, 3)
    pad = _norm_pad(padding, 3)
    if isinstance(pad, str):
        raise ValueError("return_mask does not support string padding")

    def _f(a):
        N, C, D, H, W = a.shape
        pads = _pool_pads((D, H, W), ks, st, pad, ceil_mode)
        ap = jnp.pad(a, ((0, 0), (0, 0)) + tuple(pads),
                     constant_values=_neg_fill(a.dtype))
        patches = jax.lax.conv_general_dilated_patches(
            ap, ks, st, padding=[(0, 0)] * 3,
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        )  # [N, C*kd*kh*kw, od, oh, ow]
        od, oh, ow = patches.shape[2:]
        patches = patches.reshape(N, C, ks[0] * ks[1] * ks[2], od, oh, ow)
        arg = jnp.argmax(patches, axis=2)  # index inside the window
        kd = arg // (ks[1] * ks[2])
        kh = (arg // ks[2]) % ks[1]
        kw = arg % ks[2]
        odx = jnp.arange(od).reshape(1, 1, -1, 1, 1)
        ohx = jnp.arange(oh).reshape(1, 1, 1, -1, 1)
        owx = jnp.arange(ow).reshape(1, 1, 1, 1, -1)
        iz = odx * st[0] + kd - pads[0][0]
        iy = ohx * st[1] + kh - pads[1][0]
        ix = owx * st[2] + kw - pads[2][0]
        return ((iz * H + iy) * W + ix).astype(jnp.int32)

    return apply(_f, x, op_name="max_pool3d_indices")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, None, None, data_format, ceil_mode,
                 "avg_pool3d", count_include_pad=not exclusive, average=True)


def _adaptive_pool(x, output_size, n, mode, data_format, name):
    def _norm_out(os):
        if isinstance(os, int):
            return (os,) * n
        return tuple(a if a is not None else None for a in os)

    out_sizes = _norm_out(output_size)

    def _f(a):
        channels_first = data_format.startswith("NC")
        spatial_axes = list(range(2, 2 + n)) if channels_first else list(range(1, 1 + n))
        out = a
        for i, ax in enumerate(spatial_axes):
            osz = out_sizes[i]
            if osz is None:
                continue
            isz = out.shape[ax]
            # split into osz windows: start/end per adaptive formula
            starts = [(j * isz) // osz for j in range(osz)]
            ends = [-(-((j + 1) * isz) // osz) for j in range(osz)]
            pieces = []
            for s, e in zip(starts, ends):
                sl = [slice(None)] * out.ndim
                sl[ax] = slice(s, e)
                win = out[tuple(sl)]
                red = jnp.max(win, axis=ax, keepdims=True) if mode == "max" else jnp.mean(win, axis=ax, keepdims=True)
                pieces.append(red)
            out = jnp.concatenate(pieces, axis=ax)
        return out

    return apply(_f, x, op_name=name)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "avg", "NCH", "adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, "avg", data_format, "adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, "avg", data_format, "adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, "max", "NCH", "adaptive_max_pool1d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, "max", "NCHW", "adaptive_max_pool2d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, "max", "NCDHW", "adaptive_max_pool3d")


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NCL", name=None):
    p = float(norm_type)
    powed = apply(lambda a: jnp.abs(a) ** p, x, op_name="lp_pow")
    pooled = avg_pool1d(powed, kernel_size, stride, padding, exclusive=False, ceil_mode=ceil_mode)
    ks = _tuple(kernel_size, 1)[0]
    return apply(lambda a: (a * ks) ** (1.0 / p), pooled, op_name="lp_root")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NCHW", name=None):
    p = float(norm_type)
    powed = apply(lambda a: jnp.abs(a) ** p, x, op_name="lp_pow")
    pooled = avg_pool2d(powed, kernel_size, stride, padding, ceil_mode=ceil_mode, exclusive=False)
    ks = _tuple(kernel_size, 2)
    scale = ks[0] * ks[1]
    return apply(lambda a: (a * scale) ** (1.0 / p), pooled, op_name="lp_root")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0, data_format="NCHW", output_size=None, name=None):
    ks = _tuple(kernel_size, 2)
    st = _tuple(stride if stride is not None else kernel_size, 2)

    def _f(a, idx):
        N, C, oh, ow = a.shape
        if output_size is not None:
            H, W = output_size[-2], output_size[-1]
        else:
            H = (oh - 1) * st[0] + ks[0] - 2 * (padding if isinstance(padding, int) else 0)
            W = (ow - 1) * st[1] + ks[1] - 2 * (padding if isinstance(padding, int) else 0)
        out = jnp.zeros((N, C, H * W), a.dtype)
        flat_idx = idx.reshape(N, C, -1)
        flat_val = a.reshape(N, C, -1)
        out = jax.vmap(jax.vmap(lambda o, i, v: o.at[i].set(v)))(out, flat_idx, flat_val)
        return out.reshape(N, C, H, W)

    return apply(_f, x, indices, op_name="max_unpool2d")


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0, data_format="NCL", output_size=None, name=None):
    """Inverse of max_pool1d(return_mask=True) — scatter values back to
    their argmax positions (ref: nn/functional/pooling.py max_unpool1d)."""
    from ...tensor.manipulation import reshape as _rs

    k = _tuple(kernel_size, 1)[0]
    s = _tuple(stride if stride is not None else kernel_size, 1)[0]
    p = _tuple(padding, 1)[0]
    if output_size is not None:
        L = output_size[-1]
    else:
        L = (x.shape[-1] - 1) * s + k - 2 * p
    x4 = _rs(x, [x.shape[0], x.shape[1], 1, x.shape[2]])
    i4 = _rs(indices, [indices.shape[0], indices.shape[1], 1, indices.shape[2]])
    # output_size carries the padding-corrected length; unpool2d must
    # not subtract the scalar padding from the singleton H dim
    out = max_unpool2d(x4, i4, (1, k), stride=(1, s), padding=0,
                       output_size=[1, L])
    return _rs(out, [out.shape[0], out.shape[1], out.shape[3]])


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0, data_format="NCDHW", output_size=None, name=None):
    """Inverse of max_pool3d(return_mask=True): values scatter to their
    flat (d*H*W + h*W + w) argmax positions."""
    if data_format != "NCDHW":
        raise ValueError("max_unpool3d supports NCDHW only")
    ks = _tuple(kernel_size, 3)
    st = _tuple(stride if stride is not None else kernel_size, 3)
    p = _tuple(padding, 3)

    def _f(a, idx):
        N, C, od, oh, ow = a.shape
        if output_size is not None:
            D, H, W = output_size[-3], output_size[-2], output_size[-1]
        else:
            D = (od - 1) * st[0] + ks[0] - 2 * p[0]
            H = (oh - 1) * st[1] + ks[1] - 2 * p[1]
            W = (ow - 1) * st[2] + ks[2] - 2 * p[2]
        out = jnp.zeros((N, C, D * H * W), a.dtype)
        flat_idx = idx.reshape(N, C, -1)
        flat_val = a.reshape(N, C, -1)
        out = jax.vmap(jax.vmap(lambda o, i, v: o.at[i].set(v)))(out, flat_idx, flat_val)
        return out.reshape(N, C, D, H, W)

    return apply(_f, x, indices, op_name="max_unpool3d")


def _fractional_bounds(in_size, out_size, u):
    """Fractional pooling boundaries (Graham 2014, the reference's
    fractional_max_pool formulation): row i spans
    [ceil(a*(i+u))-1, ceil(a*(i+1+u))-1) with a = in/out."""
    a = in_size / out_size
    idx = np.arange(out_size + 1)
    b = np.ceil(a * (idx + u)).astype(np.int64) - 1
    b[0] = 0
    b[-1] = in_size
    return b


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """ref: nn/functional/pooling.py fractional_max_pool2d."""
    import random as _pyrandom

    u = random_u if random_u is not None else _pyrandom.random()
    oh, ow = (output_size, output_size) if isinstance(output_size, int) else tuple(output_size)

    def _f(a):
        n, c, h, w = a.shape
        rb = _fractional_bounds(h, oh, u)
        cb = _fractional_bounds(w, ow, u)
        rows = []
        for i in range(oh):
            cols = []
            for j in range(ow):
                patch = a[:, :, rb[i]:rb[i + 1], cb[j]:cb[j + 1]]
                cols.append(patch.max(axis=(2, 3)))
            rows.append(jnp.stack(cols, -1))
        return jnp.stack(rows, -2)

    out = apply(_f, x, op_name="fractional_max_pool2d")
    if return_mask:
        # mask = flat input index of each max (recomputed on request)
        def _m(a):
            n, c, h, w = a.shape
            rb = _fractional_bounds(h, oh, u)
            cb = _fractional_bounds(w, ow, u)
            rows = []
            for i in range(oh):
                cols = []
                for j in range(ow):
                    patch = a[:, :, rb[i]:rb[i + 1], cb[j]:cb[j + 1]]
                    ph = patch.shape[2]
                    pw = patch.shape[3]
                    flat = patch.reshape(n, c, ph * pw)
                    k = flat.argmax(-1)
                    cols.append((rb[i] + k // pw) * w + (cb[j] + k % pw))
                rows.append(jnp.stack(cols, -1))
            return jnp.stack(rows, -2)

        return out, apply(_m, x, op_name="fractional_max_pool2d_mask")
    return out


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """ref: pooling.py fractional_max_pool3d."""
    import random as _pyrandom

    u = random_u if random_u is not None else _pyrandom.random()
    if isinstance(output_size, int):
        od = oh = ow = output_size
    else:
        od, oh, ow = tuple(output_size)

    def _f(a):
        n, c, d, h, w = a.shape
        db = _fractional_bounds(d, od, u)
        rb = _fractional_bounds(h, oh, u)
        cb = _fractional_bounds(w, ow, u)
        planes = []
        for z in range(od):
            rows = []
            for i in range(oh):
                cols = []
                for j in range(ow):
                    patch = a[:, :, db[z]:db[z + 1], rb[i]:rb[i + 1], cb[j]:cb[j + 1]]
                    cols.append(patch.max(axis=(2, 3, 4)))
                rows.append(jnp.stack(cols, -1))
            planes.append(jnp.stack(rows, -2))
        return jnp.stack(planes, -3)

    out = apply(_f, x, op_name="fractional_max_pool3d")
    if return_mask:
        def _m(a):
            n, c, d, h, w = a.shape
            db = _fractional_bounds(d, od, u)
            rb = _fractional_bounds(h, oh, u)
            cb = _fractional_bounds(w, ow, u)
            planes = []
            for z in range(od):
                rows = []
                for i in range(oh):
                    cols = []
                    for j in range(ow):
                        patch = a[:, :, db[z]:db[z + 1], rb[i]:rb[i + 1], cb[j]:cb[j + 1]]
                        pd, ph, pw = patch.shape[2], patch.shape[3], patch.shape[4]
                        k = patch.reshape(n, c, pd * ph * pw).argmax(-1)
                        zz = db[z] + k // (ph * pw)
                        yy = rb[i] + (k // pw) % ph
                        xx = cb[j] + k % pw
                        cols.append((zz * h + yy) * w + xx)
                    rows.append(jnp.stack(cols, -1))
                planes.append(jnp.stack(rows, -2))
            return jnp.stack(planes, -3)

        return out, apply(_m, x, op_name="fractional_max_pool3d_mask")
    return out
