"""Attention functionals.

ref: python/paddle/nn/functional/flash_attention.py:198
(flash_attention / scaled_dot_product_attention wrapping the external
FlashAttention-2 CUDA library via phi flash_attn kernels).

TPU-native design: one public entry, ``scaled_dot_product_attention``,
that dispatches to
- a **Pallas flash-attention kernel** (paddle_tpu.ops.flash_attention)
  when running on TPU with supported shapes/dtypes, and
- a reference jnp implementation otherwise (CPU tests, odd shapes).
Layout follows the reference: [batch, seq, num_heads, head_dim].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...base.tape import apply
from ...base.tensor import Tensor

__all__ = ["scaled_dot_product_attention", "flash_attention", "sdp_kernel", "flash_attn_qkvpacked", "flash_attention_with_sparse_mask", "flash_attn_varlen_qkvpacked"]


def _naive_attention(q, k, v, mask, dropout_p, causal, scale, key):
    """Reference jnp path; q/k/v: [B, S, H, D] (paddle flash-attn layout)."""
    qh = jnp.swapaxes(q, 1, 2)  # [B, H, S, D]
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / np.sqrt(d)
    # GQA: broadcast kv heads over query-head groups
    if kh.shape[1] != qh.shape[1]:
        rep = qh.shape[1] // kh.shape[1]
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * s
    logits = logits.astype(jnp.float32)
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cm, logits, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -jnp.inf)
        else:
            logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_p > 0 and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), jnp.zeros((), probs.dtype))
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)  # back to [B, S, H, D]


def _use_pallas(q_shape, dtype, mask, dropout_p) -> bool:
    if mask is not None or dropout_p > 0:
        return False
    try:
        d = jax.devices()[0]
        if d.platform not in ("tpu",):
            return False
    except Exception:
        return False
    head_dim = q_shape[-1]
    return head_dim in (64, 128, 256) and q_shape[1] % 128 == 0


def scaled_dot_product_attention(
    query,
    key,
    value,
    attn_mask=None,
    dropout_p=0.0,
    is_causal=False,
    training=True,
    name=None,
):
    """ref: python/paddle/nn/functional/flash_attention.py
    scaled_dot_product_attention. Input layout [B, S, H, D]."""
    from ...base import random as _random

    if not training:
        dropout_p = 0.0
    rng_key = _random.next_key() if dropout_p > 0 else None

    if _use_pallas(tuple(query.shape), query.dtype, attn_mask, dropout_p):
        try:
            from ...ops.flash_attention import flash_attention_fwd

            def _pallas(qq, kk, vv):
                return flash_attention_fwd(qq, kk, vv, causal=is_causal)

            return apply(_pallas, query, key, value, op_name="flash_attention")
        except Exception:
            pass  # fall through to the jnp path

    def _f(qq, kk, vv, *maybe_mask):
        m = maybe_mask[0] if maybe_mask else None
        return _naive_attention(qq, kk, vv, m, dropout_p, is_causal, None, rng_key)

    args = (query, key, value) + ((attn_mask,) if attn_mask is not None else ())
    return apply(_f, *args, op_name="scaled_dot_product_attention")


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False, fixed_seed_offset=None, rng_name="", training=True, name=None):
    """ref: flash_attention.py:198 — same output tuple (out, softmax)."""
    out = scaled_dot_product_attention(
        query, key, value, None, dropout, causal, training
    )
    return out, None


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False, return_softmax=False, fixed_seed_offset=None, rng_name="", training=True, name=None):
    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    return flash_attention(q, k, v, dropout, causal, return_softmax, fixed_seed_offset, rng_name, training, name)


class sdp_kernel:
    """Context selecting the attention backend (parity shim; TPU picks
    automatically between Pallas and jnp)."""

    def __init__(self, enable_flash=True, enable_math=True, enable_mem_efficient=True):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def flash_attention_with_sparse_mask(query, key, value, attn_mask_start_row_indices=None,
                                     attn_mask_start_row=0, dropout_p=0.0,
                                     is_causal=True, training=True, name=None):
    """ref: flash_attention.py flash_attention_with_sparse_mask — causal
    attention where row i additionally masks keys before
    start_row_indices[i]. Lowered to SDPA with the composed mask."""
    if attn_mask_start_row_indices is None:
        return scaled_dot_product_attention(query, key, value, None, dropout_p, is_causal, training)

    def _f(q, k, v, start_rows):
        b, s, h, d = q.shape
        r = jnp.arange(s)
        causal = r[None, :] <= r[:, None]
        # start_rows: [B, H, S] or [B, S]; key j masked for rows >= start_rows[j]
        sr = start_rows if start_rows.ndim == 3 else start_rows[:, None, :]
        # row i attends key j iff j <= i AND i < start_rows[..., j]
        mask = causal[None, None] & (r[None, None, :, None] < sr[:, :, None, :])
        qh = jnp.swapaxes(q, 1, 2)
        kh = jnp.swapaxes(k, 1, 2)
        vh = jnp.swapaxes(v, 1, 2)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(d)
        logits = jnp.where(mask, logits.astype(jnp.float32), -1e30)
        probs = jax.nn.softmax(logits, -1).astype(q.dtype)
        return jnp.swapaxes(jnp.einsum("bhqk,bhkd->bhqd", probs, vh), 1, 2)

    return apply(_f, query, key, value, attn_mask_start_row_indices, op_name="flash_attention_with_sparse_mask")


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k, max_seqlen_q, max_seqlen_k,
                                scale=None, dropout=0.0, causal=False, return_softmax=False,
                                fixed_seed_offset=None, rng_name="", varlen_padded=True,
                                training=True, name=None):
    """ref: flash_attention.py flash_attn_varlen_qkvpacked — packed
    variable-length batches. Segment ids from cu_seqlens mask
    cross-sequence attention; one SDPA over the packed [total, ...]."""

    def _f(packed, cu_q):
        # packed: [total, 3, H, D] (varlen_padded packs all seqs)
        total = packed.shape[0]
        q = packed[:, 0]
        k = packed[:, 1]
        v = packed[:, 2]
        pos = jnp.arange(total)
        seg = jnp.searchsorted(cu_q, pos, side="right")  # segment id per token
        same = seg[:, None] == seg[None, :]
        if causal:
            same = same & (pos[None, :] <= pos[:, None])
        d = q.shape[-1]
        s = scale if scale is not None else 1.0 / np.sqrt(d)
        logits = jnp.einsum("qhd,khd->hqk", q, k) * s
        logits = jnp.where(same[None], logits.astype(jnp.float32), -1e30)
        probs = jax.nn.softmax(logits, -1).astype(q.dtype)
        return jnp.einsum("hqk,khd->qhd", probs, v)

    out = apply(_f, qkv, cu_seqlens_q, op_name="flash_attn_varlen_qkvpacked")
    return (out, None) if return_softmax else out
