"""Activation functionals (ref: python/paddle/nn/functional/activation.py).

All lower to jax.nn / jnp primitives through the tape dispatch point so
XLA fuses them into adjacent matmuls (SURVEY §7.1: phi activation kernels
collapse to jnp lowering on TPU).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...base.tape import apply
from ...base.tensor import Tensor

__all__ = [
    "celu", "elu", "gelu", "glu", "gumbel_softmax", "hardshrink", "hardsigmoid",
    "hardswish", "hardtanh", "leaky_relu", "log_sigmoid", "log_softmax",
    "maxout", "mish", "prelu", "relu", "relu6", "relu_", "rrelu", "selu",
    "sigmoid", "silu", "softmax", "softmax_", "softplus", "softshrink",
    "softsign", "swish", "tanh", "tanh_", "tanhshrink", "thresholded_relu",
    "elu_", "hardtanh_", "leaky_relu_", "thresholded_relu_",
]


def _unary(fn, name):
    # NB: the user-facing ``name=None`` kwarg must not shadow the op name
    # (amp list lookup keys on op_name at the dispatch point)
    def wrapper(x, name=None, _op=name):
        return apply(fn, x, op_name=_op)

    wrapper.__name__ = name
    return wrapper


relu = _unary(jax.nn.relu, "relu")
sigmoid = _unary(jax.nn.sigmoid, "sigmoid")
silu = _unary(jax.nn.silu, "silu")
tanh = _unary(jnp.tanh, "tanh")
softsign = _unary(jax.nn.soft_sign, "softsign")
log_sigmoid = _unary(jax.nn.log_sigmoid, "log_sigmoid")
mish = _unary(lambda x: x * jnp.tanh(jax.nn.softplus(x)), "mish")
tanhshrink = _unary(lambda x: x - jnp.tanh(x), "tanhshrink")


def relu_(x, name=None):
    return x._inplace_from(relu(x))


def tanh_(x, name=None):
    return x._inplace_from(tanh(x))


def relu6(x, name=None):
    return apply(lambda a: jnp.clip(a, 0, 6), x, op_name="relu6")


def elu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.elu(a, alpha), x, op_name="elu")


def celu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.celu(a, alpha), x, op_name="celu")


def selu(
    x,
    scale=1.0507009873554804934193349852946,
    alpha=1.6732632423543772848170429916717,
    name=None,
):
    return apply(
        lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x, op_name="selu"
    )


def gelu(x, approximate=False, name=None):
    return apply(lambda a: jax.nn.gelu(a, approximate=approximate), x, op_name="gelu")


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(lambda a: jax.nn.leaky_relu(a, negative_slope), x, op_name="leaky_relu")


def prelu(x, weight, data_format="NCHW", name=None):
    def _f(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        # per-channel: broadcast along channel axis
        ch_axis = 1 if data_format[1] == "C" else a.ndim - 1
        shape = [1] * a.ndim
        shape[ch_axis] = w.size
        return jnp.where(a > 0, a, w.reshape(shape) * a)

    return apply(_f, x, weight, op_name="prelu")


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False, name=None):
    if training:
        from ...base import random as _random

        def _f(a):
            r = jax.random.uniform(_random.next_key(), a.shape, jnp.float32, lower, upper)
            return jnp.where(a >= 0, a, a * r.astype(a.dtype))

        return apply(_f, x, op_name="rrelu")
    mid = (lower + upper) / 2.0
    return leaky_relu(x, mid)


def hardshrink(x, threshold=0.5, name=None):
    return apply(
        lambda a: jnp.where(jnp.abs(a) > threshold, a, jnp.zeros((), a.dtype)),
        x,
        op_name="hardshrink",
    )


def softshrink(x, threshold=0.5, name=None):
    return apply(
        lambda a: jnp.where(a > threshold, a - threshold, jnp.where(a < -threshold, a + threshold, jnp.zeros((), a.dtype))),
        x,
        op_name="softshrink",
    )


def hardtanh(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    return apply(lambda a: jnp.clip(a, min, max), x, op_name="hardtanh")


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply(lambda a: jnp.clip(a * slope + offset, 0.0, 1.0), x, op_name="hardsigmoid")


def hardswish(x, name=None):
    return apply(lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, x, op_name="hardswish")


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(
        lambda a: jnp.where(a * beta > threshold, a, jax.nn.softplus(a * beta) / beta),
        x,
        op_name="softplus",
    )


def swish(x, name=None):
    return silu(x)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply(
        lambda a: jnp.where(a > threshold, a, jnp.asarray(value, a.dtype)),
        x,
        op_name="thresholded_relu",
    )


def softmax(x, axis=-1, dtype=None, name=None):
    def _f(a):
        if dtype is not None:
            from ...base import dtype as _dt

            a = a.astype(_dt.canonical_dtype(dtype))
        return jax.nn.softmax(a, axis=axis)

    return apply(_f, x, op_name="softmax")


def softmax_(x, axis=-1, dtype=None, name=None):
    return x._inplace_from(softmax(x, axis=axis, dtype=dtype))


def log_softmax(x, axis=-1, dtype=None, name=None):
    def _f(a):
        if dtype is not None:
            from ...base import dtype as _dt

            a = a.astype(_dt.canonical_dtype(dtype))
        return jax.nn.log_softmax(a, axis=axis)

    return apply(_f, x, op_name="log_softmax")


def glu(x, axis=-1, name=None):
    def _f(a):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * jax.nn.sigmoid(a2)

    return apply(_f, x, op_name="glu")


def maxout(x, groups, axis=1, name=None):
    def _f(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        shape = list(a.shape)
        shape[ax : ax + 1] = [c // groups, groups]
        return jnp.max(a.reshape(shape), axis=ax + 1)

    return apply(_f, x, op_name="maxout")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...base import random as _random

    def _f(a):
        u = jax.random.uniform(
            _random.next_key(), a.shape, jnp.float32, 1e-10, 1.0 - 1e-10
        ).astype(a.dtype)
        g = -jnp.log(-jnp.log(u))
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y).at[...].set(0)
            y_hard = jnp.where(
                jnp.arange(y.shape[axis]).reshape([-1 if i == (axis % y.ndim) else 1 for i in range(y.ndim)]) == idx,
                jnp.ones((), y.dtype),
                jnp.zeros((), y.dtype),
            )
            y = y_hard - jax.lax.stop_gradient(y) + y
        return y

    return apply(_f, x, op_name="gumbel_softmax")


# in-place activation variants (functional rebinding, ref: the
# `@inplace_apis_in_dygraph_only` activations in nn/functional/activation.py)
def elu_(x, alpha=1.0, name=None):
    return x._inplace_from(elu(x, alpha))


def hardtanh_(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    return x._inplace_from(hardtanh(x, min, max))


def leaky_relu_(x, negative_slope=0.01, name=None):
    return x._inplace_from(leaky_relu(x, negative_slope))


def thresholded_relu_(x, threshold=1.0, value=0.0, name=None):
    return x._inplace_from(thresholded_relu(x, threshold, value))
