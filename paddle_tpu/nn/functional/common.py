"""Common functionals: linear, dropout, embedding, pad, interpolate, …

ref: python/paddle/nn/functional/common.py + input.py. TPU notes:
- ``linear`` is a single jnp.matmul so XLA maps it onto the MXU and fuses
  the bias add (no fused-op kernel needed, SURVEY §7.1).
- ``dropout`` draws from the framework Generator (splittable key), so it
  is reproducible and decorrelated across TP ranks via RNGStatesTracker.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...base import random as _random
from ...base.tape import apply
from ...base.tensor import Tensor

__all__ = [
    "linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout", "pad",
    "embedding", "one_hot", "interpolate", "upsample", "cosine_similarity",
    "normalize", "unfold", "fold", "pixel_shuffle", "pixel_unshuffle",
    "channel_shuffle", "label_smooth", "bilinear", "class_center_sample",
    "pairwise_distance", "sequence_mask", "zeropad2d", "feature_alpha_dropout",
    "temporal_shift", "affine_grid", "grid_sample", "gather_tree",
    "sparse_attention",
]


def linear(x, weight, bias=None, name=None):
    """y = x @ W (+ b); W is [in_features, out_features] (paddle layout)."""
    if bias is None:
        return apply(lambda a, w: jnp.matmul(a, w), x, weight, op_name="linear")
    return apply(lambda a, w, b: jnp.matmul(a, w) + b, x, weight, bias, op_name="linear")


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or (isinstance(p, (int, float)) and p == 0):
        if mode == "downscale_in_infer" and not training:
            return apply(lambda a: a * (1.0 - p), x, op_name="dropout_infer")
        return x

    if isinstance(p, Tensor):
        p = float(p.item())
    if not 0 <= p < 1:
        if p == 1:
            return apply(lambda a: jnp.zeros_like(a), x, op_name="dropout")
        raise ValueError(f"dropout p must be in [0,1], got {p}")

    key = _random.next_key()

    def _f(a):
        if axis is None:
            mask_shape = a.shape
        else:
            axes = [axis] if isinstance(axis, int) else list(axis)
            mask_shape = tuple(
                a.shape[i] if i in axes else 1 for i in range(a.ndim)
            )
        keep = jax.random.bernoulli(key, 1.0 - p, mask_shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), jnp.zeros((), a.dtype))
        return jnp.where(keep, a, jnp.zeros((), a.dtype))

    return apply(_f, x, op_name="dropout")


def _dropout_nd(x, p, training, data_format, ndim_spatial, name):
    if not training or p == 0:
        return x
    key = _random.next_key()

    def _f(a):
        if data_format.startswith("NC"):
            mask_shape = a.shape[:2] + (1,) * ndim_spatial
        else:
            mask_shape = (a.shape[0],) + (1,) * ndim_spatial + (a.shape[-1],)
        keep = jax.random.bernoulli(key, 1.0 - p, mask_shape)
        return jnp.where(keep, a / (1.0 - p), jnp.zeros((), a.dtype))

    return apply(_f, x, op_name="dropout_nd")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    return _dropout_nd(x, p, training, data_format, 2, name)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    return _dropout_nd(x, p, training, data_format, 3, name)


def alpha_dropout(x, p=0.5, training=True, name=None):
    """SELU-preserving dropout (ref: common.py alpha_dropout)."""
    if not training or p == 0:
        return x
    alpha = 1.6732632423543772848170429916717
    scale = 1.0507009873554804934193349852946
    alpha_p = -alpha * scale
    a_coef = ((1 - p) * (1 + p * alpha_p**2)) ** -0.5
    b_coef = -a_coef * p * alpha_p
    key = _random.next_key()

    def _f(t):
        keep = jax.random.bernoulli(key, 1.0 - p, t.shape)
        return a_coef * jnp.where(keep, t, jnp.asarray(alpha_p, t.dtype)) + b_coef

    return apply(_f, x, op_name="alpha_dropout")


_PAD_MODES = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", pad_from_left_axis=True, name=None):  # noqa: A002
    """ref: python/paddle/nn/functional/common.py pad.

    ``pad`` may cover all axes (len == 2*ndim, paired low/high from the
    first axis) or only the spatial axes in data_format order (reversed,
    last-axis-first, like the reference/torch convention).
    """
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = list(int(p) for p in pad)
    jmode = _PAD_MODES.get(mode)
    if jmode is None:
        raise ValueError(f"unsupported pad mode {mode!r}")

    def _f(a):
        nd = a.ndim
        if len(pad) == 2 * nd:
            if pad_from_left_axis:
                widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
            else:
                widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)][::-1]
        else:
            n_spatial = len(pad) // 2
            widths = [(0, 0)] * nd
            if data_format.startswith("NC"):
                spatial_axes = list(range(2, 2 + (nd - 2)))
            else:
                spatial_axes = list(range(1, 1 + (nd - 2)))
            # reference pads last spatial axis first
            for i in range(n_spatial):
                ax = spatial_axes[-(i + 1)]
                widths[ax] = (pad[2 * i], pad[2 * i + 1])
        if jmode == "constant":
            return jnp.pad(a, widths, mode="constant", constant_values=jnp.asarray(value, a.dtype))
        return jnp.pad(a, widths, mode=jmode)

    return apply(_f, x, op_name="pad")


def embedding(x, weight, padding_idx=None, sparse=False, max_norm=None, norm_type=2.0, name=None):
    def _f(w, ids):
        out = jnp.take(w, ids.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            pidx = padding_idx if padding_idx >= 0 else w.shape[0] + padding_idx
            mask = (ids == pidx)[..., None]
            out = jnp.where(mask, jnp.zeros((), out.dtype), out)
        return out

    return apply(_f, weight, x, op_name="embedding")


def one_hot(x, num_classes, name=None):
    return apply(
        lambda ids: jax.nn.one_hot(ids.astype(jnp.int32), num_classes, dtype=jnp.float32),
        x,
        op_name="one_hot",
    )


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def _f(lbl, *maybe_prior):
        k = lbl.shape[-1]
        if maybe_prior:
            return (1 - epsilon) * lbl + epsilon * maybe_prior[0]
        return (1 - epsilon) * lbl + epsilon / k

    if prior_dist is not None:
        return apply(_f, label, prior_dist, op_name="label_smooth")
    return apply(_f, label, op_name="label_smooth")


def interpolate(
    x,
    size=None,
    scale_factor=None,
    mode="nearest",
    align_corners=False,
    align_mode=0,
    data_format="NCHW",
    name=None,
):
    """ref: common.py interpolate — nearest/bilinear/bicubic/trilinear/area
    via jax.image.resize (area ≈ 'linear' antialiased reduction)."""
    if isinstance(size, Tensor):
        size = size.tolist()

    def _f(a):
        channels_last = not data_format.startswith("NC")
        nd_spatial = a.ndim - 2
        if channels_last:
            spatial = a.shape[1:-1]
        else:
            spatial = a.shape[2:]
        if size is not None:
            out_spatial = tuple(int(s) for s in (size if isinstance(size, (list, tuple)) else [size]))
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * nd_spatial
            out_spatial = tuple(int(np.floor(s * f)) for s, f in zip(spatial, sf))
        if channels_last:
            out_shape = (a.shape[0],) + out_spatial + (a.shape[-1],)
        else:
            out_shape = a.shape[:2] + out_spatial
        method = {
            "nearest": "nearest",
            "bilinear": "bilinear",
            "bicubic": "bicubic",
            "trilinear": "trilinear",
            "linear": "linear",
            "area": "linear",
        }[mode]
        if method == "trilinear":
            method = "linear"
        if mode != "nearest" and align_corners:
            # jax.image.resize has no align_corners; emulate with explicit
            # coordinate gather for the bilinear 2-D case
            if nd_spatial == 2 and method in ("bilinear", "linear"):
                return _bilinear_align_corners(a, out_spatial, channels_last)
        return jax.image.resize(a, out_shape, method=method)

    return apply(_f, x, op_name="interpolate")


def _bilinear_align_corners(a, out_spatial, channels_last):
    if channels_last:
        a = jnp.moveaxis(a, -1, 1)
    N, C, H, W = a.shape
    oh, ow = out_spatial
    ys = jnp.linspace(0, H - 1, oh)
    xs = jnp.linspace(0, W - 1, ow)
    y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, H - 1)
    x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, W - 1)
    y1 = jnp.clip(y0 + 1, 0, H - 1)
    x1 = jnp.clip(x0 + 1, 0, W - 1)
    wy = (ys - y0).reshape(1, 1, -1, 1).astype(a.dtype)
    wx = (xs - x0).reshape(1, 1, 1, -1).astype(a.dtype)
    v00 = a[:, :, y0][:, :, :, x0]
    v01 = a[:, :, y0][:, :, :, x1]
    v10 = a[:, :, y1][:, :, :, x0]
    v11 = a[:, :, y1][:, :, :, x1]
    out = (
        v00 * (1 - wy) * (1 - wx)
        + v01 * (1 - wy) * wx
        + v10 * wy * (1 - wx)
        + v11 * wy * wx
    )
    if channels_last:
        out = jnp.moveaxis(out, 1, -1)
    return out


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format, name)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def _f(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)

    return apply(_f, x1, x2, op_name="cosine_similarity")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def _f(a):
        norm = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(norm, epsilon)

    return apply(_f, x, op_name="normalize")


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (ref: common.py unfold): NCHW → [N, C*kh*kw, L]."""

    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    dh, dw = _pair(dilations)
    pads = _pair(paddings)
    if len(pads) == 2:
        pt, pb, pl, pr = pads[0], pads[0], pads[1], pads[1]
    else:
        pt, pb, pl, pr = pads

    def _f(a):
        N, C, H, W = a.shape
        a = jnp.pad(a, [(0, 0), (0, 0), (pt, pb), (pl, pr)])
        Hp, Wp = a.shape[2], a.shape[3]
        oh = (Hp - (dh * (kh - 1) + 1)) // sh + 1
        ow = (Wp - (dw * (kw - 1) + 1)) // sw + 1
        patches = jax.lax.conv_general_dilated_patches(
            a, (kh, kw), (sh, sw), padding="VALID", rhs_dilation=(dh, dw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )  # [N, C*kh*kw, oh, ow]
        return patches.reshape(N, C * kh * kw, oh * ow)

    return apply(_f, x, op_name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """col2im: inverse of unfold (sum of overlapping patches)."""

    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    oh, ow = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    dh, dw = _pair(dilations)
    pads = _pair(paddings)
    if len(pads) == 2:
        pt, pb, pl, pr = pads[0], pads[0], pads[1], pads[1]
    else:
        pt, pb, pl, pr = pads

    def _f(cols):
        N = cols.shape[0]
        C = cols.shape[1] // (kh * kw)
        Hp, Wp = oh + pt + pb, ow + pl + pr
        nh = (Hp - (dh * (kh - 1) + 1)) // sh + 1
        nw = (Wp - (dw * (kw - 1) + 1)) // sw + 1
        cols_r = cols.reshape(N, C, kh, kw, nh, nw)
        out = jnp.zeros((N, C, Hp, Wp), cols.dtype)
        for i in range(kh):
            for j in range(kw):
                out = out.at[:, :, i * dh : i * dh + nh * sh : sh, j * dw : j * dw + nw * sw : sw].add(
                    cols_r[:, :, i, j]
                )
        return out[:, :, pt : pt + oh, pl : pl + ow]

    return apply(_f, x, op_name="fold")


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def _f(a):
        if data_format == "NCHW":
            N, C, H, W = a.shape
            a = a.reshape(N, C // (r * r), r, r, H, W)
            a = a.transpose(0, 1, 4, 2, 5, 3)
            return a.reshape(N, C // (r * r), H * r, W * r)
        N, H, W, C = a.shape
        a = a.reshape(N, H, W, C // (r * r), r, r)
        a = a.transpose(0, 1, 4, 2, 5, 3)
        return a.reshape(N, H * r, W * r, C // (r * r))

    return apply(_f, x, op_name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def _f(a):
        if data_format == "NCHW":
            N, C, H, W = a.shape
            a = a.reshape(N, C, H // r, r, W // r, r)
            a = a.transpose(0, 1, 3, 5, 2, 4)
            return a.reshape(N, C * r * r, H // r, W // r)
        N, H, W, C = a.shape
        a = a.reshape(N, H // r, r, W // r, r, C)
        a = a.transpose(0, 1, 3, 5, 2, 4)
        return a.reshape(N, H // r, W // r, C * r * r)

    return apply(_f, x, op_name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def _f(a):
        if data_format == "NCHW":
            N, C, H, W = a.shape
            return a.reshape(N, groups, C // groups, H, W).transpose(0, 2, 1, 3, 4).reshape(N, C, H, W)
        N, H, W, C = a.shape
        return a.reshape(N, H, W, groups, C // groups).transpose(0, 1, 2, 4, 3).reshape(N, H, W, C)

    return apply(_f, x, op_name="channel_shuffle")


def bilinear(x1, x2, weight, bias=None, name=None):
    """out[n, o] = x1[n] @ W[o] @ x2[n] (+ b) (ref: common.py bilinear)."""

    def _f(a, b, w, *maybe_bias):
        out = jnp.einsum("ni,oij,nj->no", a, w, b)
        if maybe_bias:
            out = out + maybe_bias[0]
        return out

    if bias is not None:
        return apply(_f, x1, x2, weight, bias, op_name="bilinear")
    return apply(_f, x1, x2, weight, op_name="bilinear")


def class_center_sample(label, num_classes, num_samples, group=None):
    raise NotImplementedError(
        "class_center_sample is a PartialFC training op; use full-class "
        "margin softmax on TPU (MXU-friendly) instead."
    )


# -- parity sweep (ref: nn/functional/ common/extension/vision entries) ------


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """ref: nn/functional/distance.py pairwise_distance."""

    def _f(a, b):
        d = a - b + epsilon
        return jnp.sum(jnp.abs(d) ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)

    return apply(_f, x, y, op_name="pairwise_distance")


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """ref: nn/functional/extension.py sequence_mask — mask[i, j] =
    j < x[i]."""
    from ...base.dtype import canonical_dtype

    if maxlen is None:
        import jax as _jax

        maxlen = int(np.asarray(_jax.device_get(x._data if isinstance(x, Tensor) else x)).max())

    def _f(lens):
        r = jnp.arange(maxlen, dtype=jnp.int32)
        return (r < lens[..., None].astype(jnp.int32)).astype(canonical_dtype(dtype))

    return apply(_f, x, op_name="sequence_mask")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """ref: common.py zeropad2d."""
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """Channel-wise alpha dropout (ref: common.py feature_alpha_dropout):
    whole channels are dropped to the SELU negative saturation value."""
    if not training or p == 0:
        return x if isinstance(x, Tensor) else Tensor(x, _internal=True)
    from ...base import random as _random

    key = _random.next_key()
    alpha_p = -1.7580993408473766

    def _f(a):
        shape = (a.shape[0], a.shape[1]) + (1,) * (a.ndim - 2)
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        ap = jnp.asarray(alpha_p, jnp.float32)
        kept = jnp.where(keep, a.astype(jnp.float32), ap)
        # affine correction keeps zero mean / unit variance (the SELU
        # self-normalizing contract): out = coef_a * masked + coef_b
        coef_a = ((1.0 - p) * (1.0 + p * alpha_p ** 2)) ** -0.5
        coef_b = -coef_a * alpha_p * p
        return (kept * coef_a + coef_b).astype(a.dtype)

    return apply(_f, x, op_name="feature_alpha_dropout")


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    """ref: nn/functional/extension.py temporal_shift (TSM): shift a
    slice of channels one step along time within each segment."""

    def _f(a):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        nt, c, h, w = a.shape
        n = nt // seg_num
        v = a.reshape(n, seg_num, c, h, w)
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        back = jnp.concatenate([v[:, 1:, :c1], jnp.zeros_like(v[:, :1, :c1])], 1)
        fwd = jnp.concatenate([jnp.zeros_like(v[:, :1, c1:c2]), v[:, :-1, c1:c2]], 1)
        out = jnp.concatenate([back, fwd, v[:, :, c2:]], 2).reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return apply(_f, x, op_name="temporal_shift")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """ref: nn/functional/vision.py affine_grid — 2D only ([N,2,3])."""
    n, c, h, w = [int(s) for s in out_shape]

    def _lin(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size)
        step = 2.0 / size
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

    def _f(th):
        ys = _lin(h)
        xs = _lin(w)
        gx, gy = jnp.meshgrid(xs, ys)  # [h, w]
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], -1).reshape(-1, 3)  # [h*w, 3]
        out = jnp.einsum("nij,pj->npi", th, base)  # [n, h*w, 2]
        return out.reshape(n, h, w, 2)

    return apply(_f, theta, op_name="affine_grid")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """ref: nn/functional/vision.py grid_sample — NCHW, 2D bilinear /
    nearest with zeros/border/reflection padding."""

    def _unnorm(coord, size):
        if align_corners:
            return (coord + 1.0) * (size - 1) / 2.0
        return ((coord + 1.0) * size - 1.0) / 2.0

    def _f(a, g):
        n, c, h, w = a.shape
        gx = _unnorm(g[..., 0], w)  # [n, gh, gw]
        gy = _unnorm(g[..., 1], h)

        def sample(ix, iy):
            # gather with padding handling; ix/iy int32 [n, gh, gw]
            inb = (ix >= 0) & (ix < w) & (iy >= 0) & (iy < h)
            cx = jnp.clip(ix, 0, w - 1)
            cy = jnp.clip(iy, 0, h - 1)
            bidx = jnp.arange(n)[:, None, None]
            vals = a[bidx, :, cy, cx]  # [n, gh, gw, c]
            if padding_mode == "zeros":
                vals = jnp.where(inb[..., None], vals, 0.0)
            return vals

        def reflect(coord, size):
            if align_corners:
                span = 2 * (size - 1)
                m = jnp.mod(jnp.abs(coord), span)
                return jnp.where(m > size - 1, span - m, m)
            span = 2 * size
            m = jnp.mod(jnp.abs(coord + 0.5), span)
            return jnp.clip(jnp.where(m > size - 0.5, span - m, m) - 0.5, 0, size - 1)

        if padding_mode == "reflection":
            gx = reflect(gx, w)
            gy = reflect(gy, h)
        elif padding_mode == "border":
            gx = jnp.clip(gx, 0, w - 1)
            gy = jnp.clip(gy, 0, h - 1)

        if mode == "nearest":
            out = sample(jnp.round(gx).astype(jnp.int32), jnp.round(gy).astype(jnp.int32))
        else:
            x0 = jnp.floor(gx).astype(jnp.int32)
            y0 = jnp.floor(gy).astype(jnp.int32)
            x1, y1 = x0 + 1, y0 + 1
            wx = gx - x0
            wy = gy - y0
            v00 = sample(x0, y0)
            v01 = sample(x1, y0)
            v10 = sample(x0, y1)
            v11 = sample(x1, y1)
            out = (
                v00 * ((1 - wx) * (1 - wy))[..., None]
                + v01 * (wx * (1 - wy))[..., None]
                + v10 * ((1 - wx) * wy)[..., None]
                + v11 * (wx * wy)[..., None]
            )
        return jnp.transpose(out, (0, 3, 1, 2))  # -> NCHW

    return apply(_f, x, grid, op_name="grid_sample")


def gather_tree(ids, parents, name=None):
    """Beam-search backtrace (ref: nn/functional/extension.py
    gather_tree): walk parent pointers from the last step back,
    re-gathering each step's ids. ids/parents: [T, B, beam]."""

    def _f(seq, par):
        T = seq.shape[0]

        def step(beams, t):
            # beams: current beam index per [B, beam]
            idx = jnp.take_along_axis(seq[t], beams, axis=-1)
            nxt = jnp.take_along_axis(par[t], beams, axis=-1)
            return nxt, idx

        init = jnp.broadcast_to(
            jnp.arange(seq.shape[2], dtype=seq.dtype), seq.shape[1:]
        )
        _, out_rev = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return jnp.flip(out_rev, 0)

    return apply(_f, ids, parents, op_name="gather_tree")


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention (ref: nn/functional/sparse_attention.py,
    CUDA-only there). TPU path: materialize the CSR sparsity as a mask
    over an SDPA call — XLA's fused attention handles the rest; for
    genuinely long sequences use ops.ring_attention or flash attention
    with block masking instead."""
    import jax as _jax

    offs = np.asarray(_jax.device_get(sparse_csr_offset._data if isinstance(sparse_csr_offset, Tensor) else sparse_csr_offset))
    cols = np.asarray(_jax.device_get(sparse_csr_columns._data if isinstance(sparse_csr_columns, Tensor) else sparse_csr_columns))

    def _f(q, k, v):
        b, h, s, d = q.shape
        # offsets/columns are per (batch, head): [B, H, S+1] / [B, H, nnz]
        o = np.broadcast_to(offs, (b, h) + offs.shape[-1:]) if offs.ndim < 3 else offs
        cc = np.broadcast_to(cols, (b, h) + cols.shape[-1:]) if cols.ndim < 3 else cols
        mask = np.zeros((b, h, s, s), bool)
        for bi in range(b):
            for hi in range(h):
                ro = o[bi, hi]
                cl = cc[bi, hi]
                for r in range(s):
                    mask[bi, hi, r, cl[ro[r]:ro[r + 1]]] = True
        m = jnp.asarray(mask)
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
        logits = jnp.where(m, logits, -1e30)
        probs = jax.nn.softmax(logits.astype(jnp.float32), -1).astype(q.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", probs, v)

    return apply(_f, query, key, value, op_name="sparse_attention")
