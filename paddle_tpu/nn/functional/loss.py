"""Loss functionals.

ref: python/paddle/nn/functional/loss.py. cross_entropy keeps the
reference's combined softmax+CE surface (use_softmax, soft_label,
ignore_index, weight, label_smoothing) but lowers to one fused
log_softmax+gather — a single XLA fusion on TPU instead of the
softmax_with_cross_entropy CUDA kernel.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...base.tape import apply
from ...base.tensor import Tensor

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "mse_loss", "l1_loss", "nll_loss",
    "smooth_l1_loss", "kl_div", "margin_ranking_loss", "cosine_embedding_loss",
    "hinge_embedding_loss", "triplet_margin_loss", "log_loss", "square_error_cost",
    "sigmoid_focal_loss", "softmax_with_cross_entropy", "poisson_nll_loss",
    "multi_label_soft_margin_loss", "soft_margin_loss",
]


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(
    input,  # noqa: A002
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
    name=None,
):
    def _f(logits, lbl, *maybe_w):
        ax = axis % logits.ndim
        num_classes = logits.shape[ax]
        logp = jax.nn.log_softmax(logits, axis=ax) if use_softmax else jnp.log(
            jnp.clip(logits, 1e-15, 1.0)
        )
        if soft_label or (lbl.ndim == logits.ndim and lbl.shape[ax] == num_classes and np.dtype(lbl.dtype).kind == "f"):
            soft = lbl
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / num_classes
            loss = -jnp.sum(soft * logp, axis=ax)
            valid = None
        else:
            ids = lbl
            if ids.ndim == logits.ndim:  # trailing singleton label dim
                ids = jnp.squeeze(ids, axis=ax)
            ids = ids.astype(jnp.int32)
            valid = ids != ignore_index
            safe_ids = jnp.where(valid, ids, 0)
            picked = jnp.take_along_axis(
                logp, jnp.expand_dims(safe_ids, ax), axis=ax
            ).squeeze(ax)
            if label_smoothing > 0:
                smooth_term = jnp.mean(logp, axis=ax)
                loss = -(1 - label_smoothing) * picked - label_smoothing * smooth_term
            else:
                loss = -picked
            loss = jnp.where(valid, loss, 0.0)
            if maybe_w:
                w = maybe_w[0][safe_ids]
                w = jnp.where(valid, w, 0.0)
                loss = loss * w
                if reduction == "mean":
                    return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
        if reduction == "mean":
            if valid is not None:
                return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
            return jnp.mean(loss)
        return _reduce(loss, reduction)

    args = (input, label) + ((weight,) if weight is not None else ())
    return apply(_f, *args, op_name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index, reduction="none", axis=axis)
    # reference keeps a trailing singleton dim on the hard-label path
    loss = apply(lambda a: jnp.expand_dims(a, axis), loss, op_name="unsqueeze_loss") if not soft_label else loss
    if return_softmax:
        from .activation import softmax as _softmax

        return loss, _softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):  # noqa: A002
    def _f(logp, lbl, *maybe_w):
        ids = lbl.astype(jnp.int32)
        valid = ids != ignore_index
        safe = jnp.where(valid, ids, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1).squeeze(1)
        loss = -jnp.where(valid, picked, 0.0)
        if maybe_w:
            w = maybe_w[0][safe] * valid.astype(logp.dtype)
            loss = loss * w
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(logp.dtype)), 1.0)
        return _reduce(loss, reduction)

    args = (input, label) + ((weight,) if weight is not None else ())
    return apply(_f, *args, op_name="nll_loss")


def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return apply(lambda a, b: _reduce((a - b) ** 2, reduction), input, label, op_name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return apply(lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label, op_name="l1_loss")


def square_error_cost(input, label):  # noqa: A002
    return apply(lambda a, b: (a - b) ** 2, input, label, op_name="square_error_cost")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    def _f(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)

    return apply(_f, input, label, op_name="smooth_l1_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):  # noqa: A002
    def _f(p, y, *maybe_w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if maybe_w:
            loss = loss * maybe_w[0]
        return _reduce(loss, reduction)

    args = (input, label) + ((weight,) if weight is not None else ())
    return apply(_f, *args, op_name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    def _f(z, y, *rest):
        # numerically-stable BCE-with-logits
        log_sig = jax.nn.log_sigmoid(z)
        log_one_minus = jax.nn.log_sigmoid(-z)
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = rest[i]; i += 1
        if pos_weight is not None:
            pw = rest[i]; i += 1
        pos_term = -y * log_sig
        if pw is not None:
            pos_term = pos_term * pw
        loss = pos_term - (1 - y) * log_one_minus
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    args = (logit, label) + tuple(t for t in (weight, pos_weight) if t is not None)
    return apply(_f, *args, op_name="bce_with_logits")


def kl_div(input, label, reduction="mean", log_target=False, name=None):  # noqa: A002
    def _f(logp, q):
        if log_target:
            loss = jnp.exp(q) * (q - logp)
        else:
            safe_q = jnp.clip(q, 1e-12, None)
            loss = q * (jnp.log(safe_q) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)

    return apply(_f, input, label, op_name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):  # noqa: A002
    def _f(a, b, y):
        loss = jnp.maximum(0.0, -y * (a - b) + margin)
        return _reduce(loss, reduction)

    return apply(_f, input, other, label, op_name="margin_ranking_loss")


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def _f(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12
        )
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return apply(_f, input1, input2, label, op_name="cosine_embedding_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):  # noqa: A002
    def _f(a, y):
        loss = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)

    return apply(_f, input, label, op_name="hinge_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):  # noqa: A002
    def _f(a, pos, neg):
        def dist(u, v):
            return jnp.power(jnp.sum(jnp.abs(u - v) ** p, axis=-1) + epsilon, 1.0 / p)

        d_pos = dist(a, pos)
        d_neg = dist(a, neg)
        if swap:
            d_neg = jnp.minimum(d_neg, dist(pos, neg))
        loss = jnp.maximum(0.0, d_pos - d_neg + margin)
        return _reduce(loss, reduction)

    return apply(_f, input, positive, negative, op_name="triplet_margin_loss")


def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    def _f(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)

    return apply(_f, input, label, op_name="log_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    def _f(z, y, *maybe_norm):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if maybe_norm:
            loss = loss / maybe_norm[0]
        return _reduce(loss, reduction)

    args = (logit, label) + ((normalizer,) if normalizer is not None else ())
    return apply(_f, *args, op_name="sigmoid_focal_loss")


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8, reduction="mean", name=None):  # noqa: A002
    def _f(x, y):
        if log_input:
            loss = jnp.exp(x) - y * x
        else:
            loss = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(2 * np.pi * (y + epsilon))
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)

    return apply(_f, input, label, op_name="poisson_nll_loss")


def soft_margin_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return apply(
        lambda a, y: _reduce(jnp.log1p(jnp.exp(-y * a)), reduction),
        input, label, op_name="soft_margin_loss",
    )


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean", name=None):  # noqa: A002
    def _f(z, y, *maybe_w):
        loss = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        if maybe_w:
            loss = loss * maybe_w[0]
        loss = jnp.mean(loss, axis=-1)
        return _reduce(loss, reduction)

    args = (input, label) + ((weight,) if weight is not None else ())
    return apply(_f, *args, op_name="multi_label_soft_margin_loss")
