"""Loss functionals.

ref: python/paddle/nn/functional/loss.py. cross_entropy keeps the
reference's combined softmax+CE surface (use_softmax, soft_label,
ignore_index, weight, label_smoothing) but lowers to one fused
log_softmax+gather — a single XLA fusion on TPU instead of the
softmax_with_cross_entropy CUDA kernel.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...base.tape import apply
from ...base.tensor import Tensor

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "mse_loss", "l1_loss", "nll_loss",
    "smooth_l1_loss", "kl_div", "margin_ranking_loss", "cosine_embedding_loss",
    "hinge_embedding_loss", "triplet_margin_loss", "log_loss", "square_error_cost",
    "sigmoid_focal_loss", "softmax_with_cross_entropy", "poisson_nll_loss",
    "multi_label_soft_margin_loss", "soft_margin_loss",
    "ctc_loss", "rnnt_loss", "dice_loss", "npair_loss", "multi_margin_loss",
    "gaussian_nll_loss", "triplet_margin_with_distance_loss", "hsigmoid_loss",
    "margin_cross_entropy", "adaptive_log_softmax_with_loss",
]


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(
    input,  # noqa: A002
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
    name=None,
):
    def _f(logits, lbl, *maybe_w):
        ax = axis % logits.ndim
        num_classes = logits.shape[ax]
        hard = not (soft_label or (lbl.ndim == logits.ndim and lbl.shape[ax] == num_classes and np.dtype(lbl.dtype).kind == "f"))
        if not hard or not use_softmax:
            logp = jax.nn.log_softmax(logits, axis=ax) if use_softmax else jnp.log(
                jnp.clip(logits, 1e-15, 1.0)
            )
        if not hard:
            soft = lbl
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / num_classes
            loss = -jnp.sum(soft * logp, axis=ax)
            valid = None
        else:
            ids = lbl
            if ids.ndim == logits.ndim:  # trailing singleton label dim
                ids = jnp.squeeze(ids, axis=ax)
            ids = ids.astype(jnp.int32)
            valid = ids != ignore_index
            safe_ids = jnp.where(valid, ids, 0)
            if use_softmax:
                # logsumexp-gather form: -logp[y] = lse - logits[y].
                # Avoids materializing the [*, num_classes] log-softmax
                # array (for an LM head that array is tokens x vocab in
                # f32 — the dominant HBM traffic of the loss)
                lse = jax.scipy.special.logsumexp(
                    logits.astype(jnp.float32), axis=ax
                )
                picked = jnp.take_along_axis(
                    logits, jnp.expand_dims(safe_ids, ax), axis=ax
                ).squeeze(ax).astype(jnp.float32)
                if label_smoothing > 0:
                    mean_logit = jnp.mean(logits.astype(jnp.float32), axis=ax)
                    loss = ((1 - label_smoothing) * (lse - picked)
                            + label_smoothing * (lse - mean_logit))
                else:
                    loss = lse - picked
            else:
                picked = jnp.take_along_axis(
                    logp, jnp.expand_dims(safe_ids, ax), axis=ax
                ).squeeze(ax)
                if label_smoothing > 0:
                    smooth_term = jnp.mean(logp, axis=ax)
                    loss = -(1 - label_smoothing) * picked - label_smoothing * smooth_term
                else:
                    loss = -picked
            loss = jnp.where(valid, loss, 0.0)
            if maybe_w:
                w = maybe_w[0][safe_ids]
                w = jnp.where(valid, w, 0.0)
                loss = loss * w
                if reduction == "mean":
                    return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
        if reduction == "mean":
            if valid is not None:
                return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
            return jnp.mean(loss)
        return _reduce(loss, reduction)

    args = (input, label) + ((weight,) if weight is not None else ())
    return apply(_f, *args, op_name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index, reduction="none", axis=axis)
    # reference keeps a trailing singleton dim on the hard-label path
    loss = apply(lambda a: jnp.expand_dims(a, axis), loss, op_name="unsqueeze_loss") if not soft_label else loss
    if return_softmax:
        from .activation import softmax as _softmax

        return loss, _softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):  # noqa: A002
    def _f(logp, lbl, *maybe_w):
        ids = lbl.astype(jnp.int32)
        valid = ids != ignore_index
        safe = jnp.where(valid, ids, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1).squeeze(1)
        loss = -jnp.where(valid, picked, 0.0)
        if maybe_w:
            w = maybe_w[0][safe] * valid.astype(logp.dtype)
            loss = loss * w
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(logp.dtype)), 1.0)
        return _reduce(loss, reduction)

    args = (input, label) + ((weight,) if weight is not None else ())
    return apply(_f, *args, op_name="nll_loss")


def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return apply(lambda a, b: _reduce((a - b) ** 2, reduction), input, label, op_name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return apply(lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label, op_name="l1_loss")


def square_error_cost(input, label):  # noqa: A002
    return apply(lambda a, b: (a - b) ** 2, input, label, op_name="square_error_cost")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    def _f(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)

    return apply(_f, input, label, op_name="smooth_l1_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):  # noqa: A002
    def _f(p, y, *maybe_w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if maybe_w:
            loss = loss * maybe_w[0]
        return _reduce(loss, reduction)

    args = (input, label) + ((weight,) if weight is not None else ())
    return apply(_f, *args, op_name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    def _f(z, y, *rest):
        # numerically-stable BCE-with-logits
        log_sig = jax.nn.log_sigmoid(z)
        log_one_minus = jax.nn.log_sigmoid(-z)
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = rest[i]; i += 1
        if pos_weight is not None:
            pw = rest[i]; i += 1
        pos_term = -y * log_sig
        if pw is not None:
            pos_term = pos_term * pw
        loss = pos_term - (1 - y) * log_one_minus
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    args = (logit, label) + tuple(t for t in (weight, pos_weight) if t is not None)
    return apply(_f, *args, op_name="bce_with_logits")


def kl_div(input, label, reduction="mean", log_target=False, name=None):  # noqa: A002
    def _f(logp, q):
        if log_target:
            loss = jnp.exp(q) * (q - logp)
        else:
            safe_q = jnp.clip(q, 1e-12, None)
            loss = q * (jnp.log(safe_q) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)

    return apply(_f, input, label, op_name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):  # noqa: A002
    def _f(a, b, y):
        loss = jnp.maximum(0.0, -y * (a - b) + margin)
        return _reduce(loss, reduction)

    return apply(_f, input, other, label, op_name="margin_ranking_loss")


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def _f(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12
        )
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return apply(_f, input1, input2, label, op_name="cosine_embedding_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):  # noqa: A002
    def _f(a, y):
        loss = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)

    return apply(_f, input, label, op_name="hinge_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):  # noqa: A002
    def _f(a, pos, neg):
        def dist(u, v):
            return jnp.power(jnp.sum(jnp.abs(u - v) ** p, axis=-1) + epsilon, 1.0 / p)

        d_pos = dist(a, pos)
        d_neg = dist(a, neg)
        if swap:
            d_neg = jnp.minimum(d_neg, dist(pos, neg))
        loss = jnp.maximum(0.0, d_pos - d_neg + margin)
        return _reduce(loss, reduction)

    return apply(_f, input, positive, negative, op_name="triplet_margin_loss")


def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    def _f(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)

    return apply(_f, input, label, op_name="log_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    def _f(z, y, *maybe_norm):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if maybe_norm:
            loss = loss / maybe_norm[0]
        return _reduce(loss, reduction)

    args = (logit, label) + ((normalizer,) if normalizer is not None else ())
    return apply(_f, *args, op_name="sigmoid_focal_loss")


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8, reduction="mean", name=None):  # noqa: A002
    def _f(x, y):
        if log_input:
            loss = jnp.exp(x) - y * x
        else:
            loss = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(2 * np.pi * (y + epsilon))
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)

    return apply(_f, input, label, op_name="poisson_nll_loss")


def soft_margin_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return apply(
        lambda a, y: _reduce(jnp.log1p(jnp.exp(-y * a)), reduction),
        input, label, op_name="soft_margin_loss",
    )


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean", name=None):  # noqa: A002
    def _f(z, y, *maybe_w):
        loss = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        if maybe_w:
            loss = loss * maybe_w[0]
        loss = jnp.mean(loss, axis=-1)
        return _reduce(loss, reduction)

    args = (input, label) + ((weight,) if weight is not None else ())
    return apply(_f, *args, op_name="multi_label_soft_margin_loss")


# -- parity sweep (ref: nn/functional/loss.py remaining entries) ------------


def _reduce_t(v, reduction):
    if reduction == "mean":
        return v.mean()
    if reduction == "sum":
        return v.sum()
    return v


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False, name=None):
    """CTC loss (ref: loss.py ctc_loss binding warpctc).

    TPU-native: the forward-alpha recursion runs as one lax.scan over
    time on the padded [B, 2*L+1] extended-label lattice — no host loop,
    batch-vectorized, works under jit. log_probs: [T, B, C] log-softmaxed
    (the reference applies log_softmax inside; we do too for parity)."""

    def _f(lp, lab, in_len, lab_len):
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        T, B, C = lp.shape
        L = lab.shape[1]
        S = 2 * L + 1
        NEG = -1e30
        # extended labels: blank, l1, blank, l2, ..., blank
        ext = jnp.full((B, S), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        s_len = 2 * lab_len.astype(jnp.int32) + 1
        # can skip from s-2 to s when ext[s] != blank and ext[s] != ext[s-2]
        ext_m2 = jnp.concatenate([jnp.full((B, 2), -1, jnp.int32), ext[:, :-2]], 1)
        can_skip = (ext != blank) & (ext != ext_m2)

        def emit(t):
            return jnp.take_along_axis(lp[t], ext, axis=-1)  # [B, S]

        alpha0 = jnp.full((B, S), NEG)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        first_lab = jnp.where(lab_len > 0, lab[:, 0].astype(jnp.int32), blank)
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(lab_len > 0, lp[0, jnp.arange(B), first_lab], NEG)
        )

        def step(alpha, t):
            stay = alpha
            prev1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], 1)
            prev2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], 1)
            prev2 = jnp.where(can_skip, prev2, NEG)
            merged = jnp.logaddexp(jnp.logaddexp(stay, prev1), prev2)
            new = merged + emit(t)
            # sequences already past their length keep old alpha
            alive = (t < in_len)[:, None]
            return jnp.where(alive, new, alpha), None

        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        idx_last = jnp.clip(s_len - 1, 0, S - 1)
        idx_prev = jnp.clip(s_len - 2, 0, S - 1)
        ar = jnp.arange(B)
        # for empty labels (s_len == 1) there is no second terminal state;
        # idx_prev would clip onto idx_last and double-count the all-blank path
        prev = jnp.where(s_len >= 2, alpha[ar, idx_prev], NEG)
        ll = jnp.logaddexp(alpha[ar, idx_last], prev)
        loss = -ll
        if norm_by_times:
            loss = loss / jnp.maximum(in_len.astype(loss.dtype), 1)
        return loss

    out = apply(_f, log_probs, labels, input_lengths, label_lengths, op_name="ctc_loss")
    return _reduce_t(out, reduction)


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,  # noqa: A002
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-T transducer loss (ref: loss.py rnnt_loss binding warprnnt).

    Forward variable over the [T, U+1] grid, computed as one lax.scan
    over T with a cumulative inner recursion over U (vectorized with an
    associative scan via logaddexp cumulation). input: [B, T, U+1, C]
    raw logits (log_softmax applied here, as the reference does)."""

    def _f(acts, lab, t_len, u_len):
        lp = jax.nn.log_softmax(acts.astype(jnp.float32), axis=-1)
        B, T, U1, C = lp.shape
        U = U1 - 1
        NEG = -1e30
        ar = jnp.arange(B)
        # emit[b,t,u] = lp[b,t,u,label[b,u]] (emit label u+1), null = blank
        lab_i = lab.astype(jnp.int32)
        emit = jnp.take_along_axis(
            lp[:, :, :U, :], lab_i[:, None, :, None], axis=-1
        )[..., 0]  # [B, T, U]
        if fastemit_lambda:
            # FastEmit (arXiv:2010.11148): scale the gradient through the
            # label-emission log-probs by (1 + lambda) while leaving the
            # loss value unchanged — the value-preserving gradient-scaling
            # identity (1+l)*e - l*stop_grad(e) == e implements exactly the
            # emission-gradient boost warprnnt applies in its backward.
            lam = jnp.asarray(fastemit_lambda, emit.dtype)
            emit = (1.0 + lam) * emit - lam * jax.lax.stop_gradient(emit)
        null = lp[..., blank]  # [B, T, U+1]

        def time_step(alpha_prev, t):
            # alpha_prev: [B, U+1] = alpha[t-1, :]
            # horizontal (time) move: alpha[t, u] += alpha[t-1, u] + null[t-1, u]
            from_top = alpha_prev + null[:, t - 1, :]
            # then vertical (label) moves within row t:
            # alpha[t, u] = logaddexp(from_top[u], alpha[t, u-1] + emit[t, u-1])
            def vert(carry, u):
                cur = jnp.logaddexp(from_top[:, u], carry + emit[:, t, u - 1])
                return cur, cur

            first = from_top[:, 0]
            _, rest = jax.lax.scan(vert, first, jnp.arange(1, U1))
            row = jnp.concatenate([first[:, None], rest.T], axis=1)
            return row, row

        # row 0: only vertical moves from alpha[0,0]=0
        def vert0(carry, u):
            cur = carry + emit[:, 0, u - 1]
            return cur, cur

        first0 = jnp.zeros((B,))
        _, rest0 = jax.lax.scan(vert0, first0, jnp.arange(1, U1))
        alpha0 = jnp.concatenate([first0[:, None], rest0.T], axis=1)

        def scan_t(alpha, t):
            row = time_step(alpha, t)[0]
            alive = (t < t_len)[:, None]
            row = jnp.where(alive, row, alpha)
            return row, row

        alpha_last, rows = jax.lax.scan(scan_t, alpha0, jnp.arange(1, T))
        all_rows = jnp.concatenate([alpha0[None], rows], 0)  # [T, B, U+1]
        # ll = alpha[t_len-1, u_len] + null[t_len-1, u_len]
        tt = jnp.clip(t_len.astype(jnp.int32) - 1, 0, T - 1)
        uu = jnp.clip(u_len.astype(jnp.int32), 0, U)
        ll = all_rows[tt, ar, uu] + null[ar, tt, uu]
        return -ll

    out = apply(_f, input, label, input_lengths, label_lengths, op_name="rnnt_loss")
    return _reduce_t(out, reduction)


def dice_loss(input, label, epsilon=1e-5, name=None):  # noqa: A002
    """ref: loss.py dice_loss — 1 - 2|X∩Y| / (|X|+|Y|)."""

    def _f(x, y):
        y1 = jax.nn.one_hot(y.reshape(y.shape[:-1]), x.shape[-1], dtype=x.dtype)
        red = tuple(range(1, x.ndim))
        inter = jnp.sum(x * y1, axis=red)
        union = jnp.sum(x, axis=red) + jnp.sum(y1, axis=red)
        return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))

    return apply(_f, input, label, op_name="dice_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """ref: loss.py npair_loss — softmax CE over anchor·positiveᵀ plus
    L2 on embeddings."""

    def _f(a, p, y):
        reg = l2_reg * (jnp.sum(a * a) + jnp.sum(p * p)) / a.shape[0]
        sim = a @ p.T
        same = (y[:, None] == y[None, :]).astype(sim.dtype)
        tgt = same / jnp.maximum(same.sum(-1, keepdims=True), 1)
        ce = jnp.mean(jnp.sum(-tgt * jax.nn.log_softmax(sim, -1), -1))
        return ce + reg

    return apply(_f, anchor, positive, labels, op_name="npair_loss")


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,  # noqa: A002
                      reduction="mean", name=None):
    """ref: loss.py multi_margin_loss."""

    def _f(x, y, *maybe_w):
        n, c = x.shape
        correct = x[jnp.arange(n), y]
        m = jnp.maximum(margin - correct[:, None] + x, 0.0) ** p
        if maybe_w:
            m = m * maybe_w[0][y][:, None]
        m = m.at[jnp.arange(n), y].set(0.0)
        return m.sum(-1) / c

    args = (input, label) + ((weight,) if weight is not None else ())
    return _reduce_t(apply(_f, *args, op_name="multi_margin_loss"), reduction)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,  # noqa: A002
                      reduction="mean", name=None):
    """ref: loss.py gaussian_nll_loss."""

    def _f(mu, y, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + (y - mu) ** 2 / var)
        if full:
            loss = loss + 0.5 * np.log(2 * np.pi)
        return loss

    return _reduce_t(apply(_f, input, label, variance, op_name="gaussian_nll_loss"), reduction)


def triplet_margin_with_distance_loss(input, positive, negative,  # noqa: A002
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean", name=None):
    """ref: loss.py triplet_margin_with_distance_loss."""
    from ...tensor import linalg as _linalg

    def _dist(a, b):
        return jnp.sqrt(jnp.sum((a - b) ** 2, axis=-1) + 1e-12)

    if distance_function is not None:
        # user fn operates on Tensors; run eagerly through the tape
        d_pos = distance_function(input, positive)
        d_neg = distance_function(input, negative)
        if swap:
            d_pn = distance_function(positive, negative)
            d_neg = _minimum_t(d_neg, d_pn)
        loss = _relu_t(d_pos - d_neg + margin)
        if reduction == "mean":
            return loss.mean()
        if reduction == "sum":
            return loss.sum()
        return loss

    def _f(a, p, n):
        d_pos = _dist(a, p)
        d_neg = _dist(a, n)
        if swap:
            d_neg = jnp.minimum(d_neg, _dist(p, n))
        return jnp.maximum(d_pos - d_neg + margin, 0.0)

    return _reduce_t(apply(_f, input, positive, negative, op_name="triplet_margin_with_distance_loss"), reduction)


def _minimum_t(a, b):
    from ...tensor import math as _m

    return _m.minimum(a, b)


def _relu_t(x):
    return apply(lambda a: jnp.maximum(a, 0.0), x, op_name="relu")


def hsigmoid_loss(input, label, num_classes, weight, bias=None,  # noqa: A002
                  path_table=None, path_code=None, is_sparse=False, name=None):
    """Hierarchical sigmoid loss (ref: loss.py hsigmoid_loss). Default
    complete-binary-tree coding (no custom path): class c's path is the
    bit decomposition of c + num_classes in the implicit Huffman-style
    tree the reference builds; depth = ceil(log2(num_classes))."""
    depth = max(1, int(np.ceil(np.log2(max(num_classes, 2)))))

    def _f(x, y, w, *maybe_b):
        # node index walk: node = y + num_classes (leaf), parents = node//2
        leaf = y.astype(jnp.int32) + num_classes
        nodes = []
        codes = []
        cur = leaf
        for _ in range(depth):
            codes.append(cur % 2)
            cur = cur // 2
            nodes.append(cur)
        nodes = jnp.stack(nodes, -1)  # [N, depth] internal nodes (1-rooted)
        codes = jnp.stack(codes, -1).astype(x.dtype)
        # internal node k (1-rooted) owns weight row k-1 (table is
        # [num_classes-1, D] in the reference)
        rows = jnp.clip(nodes - 1, 0, w.shape[0] - 1)
        w_nodes = w[rows]  # [N, depth, D]
        logits = jnp.einsum("nd,nkd->nk", x, w_nodes)
        if maybe_b:
            logits = logits + maybe_b[0][jnp.clip(nodes - 1, 0, maybe_b[0].shape[0] - 1)]
        # code 1 -> sigmoid(logit), code 0 -> 1 - sigmoid
        logp = -jax.nn.softplus(-logits) * codes + -jax.nn.softplus(logits) * (1 - codes)
        # shallow leaves (num_classes not a power of two) reach the root
        # before `depth` steps; iterations past the root have node < 1 and
        # must not contribute (they'd re-count row 0)
        valid = (nodes >= 1).astype(x.dtype)
        return -((logp * valid).sum(-1))

    args = (input, label, weight) + ((bias,) if bias is not None else ())
    out = apply(_f, *args, op_name="hsigmoid_loss")
    return out.mean()


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean", name=None):
    """ArcFace/CosFace-style margin softmax (ref: loss.py
    margin_cross_entropy; single-group — the model-parallel sharded
    variant composes with mp via the TP layers)."""

    def _f(z, y):
        n = z.shape[0]
        ar = jnp.arange(n)
        target = z[ar, y]
        theta = jnp.arccos(jnp.clip(target, -1.0, 1.0))
        target_m = jnp.cos(margin1 * theta + margin2) - margin3
        z2 = z.at[ar, y].set(target_m) * scale
        logp = jax.nn.log_softmax(z2, -1)
        loss = -logp[ar, y]
        return (loss, jax.nn.softmax(z2, -1)) if return_softmax else loss

    out = apply(_f, logits, label, op_name="margin_cross_entropy")
    if return_softmax:
        loss, sm = out
        return _reduce_t(loss, reduction), sm
    return _reduce_t(out, reduction)


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,  # noqa: A002
                                   cutoffs, head_bias=None, name=None):
    """Adaptive softmax (ref: loss.py adaptive_log_softmax_with_loss):
    head covers [0, cutoff0) + one logit per tail cluster; each tail
    cluster has a two-matrix projection."""

    def _f(x, y, hw, *rest):
        n_clusters = len(cutoffs)
        hb = rest[-1] if head_bias is not None else None
        tails = rest[: 2 * n_clusters]
        head_logits = x @ hw
        if hb is not None:
            head_logits = head_logits + hb
        head_logp = jax.nn.log_softmax(head_logits, -1)
        shortlist = cutoffs[0]
        out = jnp.zeros(x.shape[0], x.dtype)
        in_short = y < shortlist
        safe_y = jnp.where(in_short, y, 0)
        out = jnp.where(in_short, head_logp[jnp.arange(x.shape[0]), safe_y], out)
        low = shortlist
        for i in range(n_clusters):
            high = cutoffs[i + 1] if i + 1 < len(cutoffs) else None
            hi = high if high is not None else 10 ** 9
            mask = (y >= low) & (y < hi)
            proj, cls_w = tails[2 * i], tails[2 * i + 1]
            tail_logp = jax.nn.log_softmax((x @ proj) @ cls_w, -1)
            cluster_logp = head_logp[:, shortlist + i]
            rel = jnp.clip(y - low, 0, cls_w.shape[1] - 1)
            val = cluster_logp + tail_logp[jnp.arange(x.shape[0]), rel]
            out = jnp.where(mask, val, out)
            low = hi
        return out, -out.mean()

    flat_tails = []
    for tw in tail_weights:
        if isinstance(tw, (list, tuple)):
            flat_tails.extend(tw)  # [projection, cluster_weight] pairs
        else:
            flat_tails.append(tw)
    args = [input, label, head_weight] + flat_tails
    if head_bias is not None:
        args.append(head_bias)
    out, loss = apply(_f, *args, op_name="adaptive_log_softmax_with_loss")
    return out, loss
