"""Convolution functionals over lax.conv_general_dilated.

ref: python/paddle/nn/functional/conv.py (conv2d etc. → phi conv kernels /
cudnn). On TPU the single XLA convolution primitive covers all of
cudnn's algo zoo — XLA tiles it onto the MXU; weight layout is paddle's
[out_c, in_c/groups, *kernel] mapped via dimension_numbers.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...base.tape import apply

__all__ = [
    "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose",
]


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(x) for x in v)
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(n))
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _norm_padding(padding, n):
    """paddle padding: int | list[n] | list[2n] | [[lo,hi],...] | 'SAME'/'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if padding and isinstance(padding[0], (list, tuple)):
        # may include batch/channel dims pairs; keep the last n pairs
        pairs = [tuple(int(x) for x in p) for p in padding]
        return pairs[-n:]
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    raise ValueError(f"bad padding {padding!r}")


def _conv_precision(a, w):
    """Match tensor/linalg.py matmul: f32 inputs get HIGHEST precision
    (the TPU default truncates conv operands to bf16); low-precision
    inputs stay MXU-native."""
    if np.dtype(a.dtype) == np.float32 and np.dtype(w.dtype) == np.float32:
        return jax.lax.Precision.HIGHEST
    return None


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, data_format, n, name):
    strides = _tuple(stride, n)
    dilations = _tuple(dilation, n)
    pad = _norm_padding(padding, n)
    spatial = "DHW"[-n:]
    if data_format.startswith("NC"):
        lhs_spec = "NC" + spatial
    else:
        lhs_spec = "N" + spatial + "C"
    dn = (lhs_spec, "OI" + spatial, lhs_spec)

    def _f(a, w, *maybe_b):
        out = jax.lax.conv_general_dilated(
            a, w,
            window_strides=strides,
            padding=pad,
            rhs_dilation=dilations,
            dimension_numbers=dn,
            feature_group_count=groups,
            precision=_conv_precision(a, w),
        )
        if maybe_b:
            b = maybe_b[0]
            shape = [1] * out.ndim
            shape[1 if data_format.startswith("NC") else -1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply(_f, *args, op_name=name)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCL", name=None):
    fmt = "NCH" if data_format in ("NCL", "NCH") else "NHC"
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, fmt, 1, "conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, data_format, 2, "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCDHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, data_format, 3, "conv3d")


def _conv_transpose_nd(
    x, weight, bias, stride, padding, output_padding, dilation, groups, data_format, output_size, n, name
):
    strides = _tuple(stride, n)
    dilations = _tuple(dilation, n)
    pad = _norm_padding(padding, n)
    out_pad = _tuple(output_padding, n) if output_padding is not None else (0,) * n
    spatial = "DHW"[-n:]
    lhs_spec = ("NC" + spatial) if data_format.startswith("NC") else ("N" + spatial + "C")
    # paddle transpose-conv weight layout: [in_c, out_c/groups, *kernel] → "IO"
    dn = (lhs_spec, "IO" + spatial, lhs_spec)

    def _f(a, w, *maybe_b):
        if isinstance(pad, str):
            padding_cfg = pad
        else:
            # conv_transpose pad semantics: effective output crop
            padding_cfg = [
                (
                    dilations[i] * (w.shape[2 + i] - 1) - pad[i][0],
                    dilations[i] * (w.shape[2 + i] - 1) - pad[i][1] + out_pad[i],
                )
                for i in range(n)
            ]
        # transpose-conv kernel: spatial flip; the I/O channel swap is
        # already expressed by the "IO" rhs spec in dn (newer jax removed
        # conv_general_dilated's transpose_kernel kwarg)
        w = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        if groups > 1:
            # grouped transpose: split I axis; lax transpose has no
            # feature_group_count for IO layout, do per-group and concat
            a_groups = jnp.split(a, groups, axis=1 if lhs_spec.startswith("NC") else -1)
            w_groups = jnp.split(w, groups, axis=0)
            outs = [
                jax.lax.conv_general_dilated(
                    ag, wg, window_strides=(1,) * n, padding=padding_cfg,
                    lhs_dilation=strides, rhs_dilation=dilations,
                    dimension_numbers=dn, precision=_conv_precision(ag, wg),
                )
                for ag, wg in zip(a_groups, w_groups)
            ]
            out = jnp.concatenate(outs, axis=1 if lhs_spec.startswith("NC") else -1)
        else:
            out = jax.lax.conv_general_dilated(
                a, w, window_strides=(1,) * n, padding=padding_cfg,
                lhs_dilation=strides, rhs_dilation=dilations,
                dimension_numbers=dn, precision=_conv_precision(a, w),
            )
        if maybe_b:
            b = maybe_b[0]
            shape = [1] * out.ndim
            shape[1 if lhs_spec.startswith("NC") else -1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply(_f, *args, op_name=name)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    fmt = "NCH" if data_format in ("NCL", "NCH") else "NHC"
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding, dilation, groups, fmt, output_size, 1, "conv1d_transpose")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding, dilation, groups, data_format, output_size, 2, "conv2d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding, dilation, groups, data_format, output_size, 3, "conv3d_transpose")
