"""Normalization functionals.

ref: python/paddle/nn/functional/norm.py (batch_norm/layer_norm →
phi kernels like gpu/layer_norm_kernel.cu). On TPU these are jnp
reductions + elementwise math that XLA fuses into single HBM passes;
rms_norm matches the reference's fused_rms_norm surface
(ref: paddle/phi/kernels/fusion/gpu/fused_rms_norm*).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...base.tape import apply
from ...base.tensor import Tensor

__all__ = ["batch_norm", "layer_norm", "instance_norm", "group_norm", "local_response_norm", "rms_norm"]


def batch_norm(
    x,
    running_mean,
    running_var,
    weight=None,
    bias=None,
    training=False,
    momentum=0.9,
    epsilon=1e-5,
    data_format="NCHW",
    use_global_stats=None,
    name=None,
):
    """Functional BN. In training mode also updates the running stats
    in-place (reference semantics: new = momentum*old + (1-momentum)*batch).
    """
    if use_global_stats is None:
        use_global_stats = not training
    channels_first = data_format.startswith("NC") and data_format != "NC"

    def _stats_axes(ndim):
        if ndim <= 2:
            return (0,), 1 if ndim == 2 else 0
        ch_axis = 1 if channels_first else ndim - 1
        axes = tuple(i for i in range(ndim) if i != ch_axis)
        return axes, ch_axis

    has_w, has_b = weight is not None, bias is not None

    def _affine(out, wb, shape):
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        return out

    if use_global_stats:
        def _f(a, m, v, *wb):
            axes, ch_axis = _stats_axes(a.ndim)
            shape = [1] * a.ndim
            shape[ch_axis] = a.shape[ch_axis]
            out = (a - m.reshape(shape)) / jnp.sqrt(v.reshape(shape) + epsilon)
            return _affine(out, wb, shape)

        args = (x, running_mean, running_var) + tuple(t for t in (weight, bias) if t is not None)
        return apply(_f, *args, op_name="batch_norm")

    # training: compute batch stats; update running stats eagerly
    def _f(a, *wb):
        axes, ch_axis = _stats_axes(a.ndim)
        mean = jnp.mean(a, axis=axes)
        var = jnp.var(a, axis=axes)
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        out = (a - mean.reshape(shape)) / jnp.sqrt(var.reshape(shape) + epsilon)
        return _affine(out, wb, shape), mean, var

    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    out, batch_mean, batch_var = apply(_f, *args, op_name="batch_norm")
    if running_mean is not None:
        running_mean.set_value(momentum * running_mean._data + (1 - momentum) * batch_mean._data)
    if running_var is not None:
        running_var.set_value(momentum * running_var._data + (1 - momentum) * batch_var._data)
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    n_axes = len(tuple(normalized_shape))
    has_w, has_b = weight is not None, bias is not None

    def _f(a, *wb):
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) / jnp.sqrt(var + epsilon)
        i = 0
        if has_w:
            out = out * wb[i]
            i += 1
        if has_b:
            out = out + wb[i]
        return out

    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return apply(_f, *args, op_name="layer_norm")


def rms_norm(x, weight=None, epsilon=1e-6, axis=-1, name=None):
    """RMSNorm (ref: fused_rms_norm surface; used by Llama-family models)."""

    def _f(a, *w):
        # stats in fp32 even for bf16 inputs (matches the fused kernel)
        ms = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=axis, keepdims=True)
        out = (a.astype(jnp.float32) * jax.lax.rsqrt(ms + epsilon)).astype(a.dtype)
        if w:
            out = out * w[0]
        return out

    args = (x,) + ((weight,) if weight is not None else ())
    return apply(_f, *args, op_name="rms_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW", name=None):
    channels_first = data_format.startswith("NC")
    has_w, has_b = weight is not None, bias is not None

    def _f(a, *wb):
        ch_axis = 1 if channels_first else a.ndim - 1
        axes = tuple(i for i in range(a.ndim) if i not in (0, ch_axis))
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) / jnp.sqrt(var + eps)
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        return out

    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return apply(_f, *args, op_name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None, data_format="NCHW", name=None):
    channels_first = data_format.startswith("NC")
    has_w, has_b = weight is not None, bias is not None

    def _f(a, *wb):
        if not channels_first:
            a = jnp.moveaxis(a, -1, 1)
        N, C = a.shape[0], a.shape[1]
        spatial = a.shape[2:]
        g = a.reshape(N, num_groups, C // num_groups, *spatial)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) / jnp.sqrt(var + epsilon)).reshape(N, C, *spatial)
        shape = [1, C] + [1] * len(spatial)
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        if not channels_first:
            out = jnp.moveaxis(out, 1, -1)
        return out

    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return apply(_f, *args, op_name="group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    def _f(a):
        channels_first = data_format.startswith("NC")
        if not channels_first:
            a = jnp.moveaxis(a, -1, 1)
        sq = jnp.square(a)
        C = a.shape[1]
        half = size // 2
        pad_width = [(0, 0)] * a.ndim
        pad_width[1] = (half, size - half - 1)
        padded = jnp.pad(sq, pad_width)
        acc = sum(padded[:, i : i + C] for i in range(size))
        out = a / jnp.power(k + alpha * acc / size, beta)
        if not channels_first:
            out = jnp.moveaxis(out, 1, -1)
        return out

    return apply(_f, x, op_name="local_response_norm")
