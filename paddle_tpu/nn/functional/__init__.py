"""paddle_tpu.nn.functional — functional op surface.

ref: python/paddle/nn/functional/__init__.py. All functions lower to
jnp/lax through the autograd tape (paddle_tpu.base.tape.apply).
"""
from .activation import *  # noqa: F401,F403
from .attention import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403

from . import activation, attention, common, conv, loss, norm, pooling  # noqa: F401
