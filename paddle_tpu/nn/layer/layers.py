"""nn.Layer — the module base class.

TPU-native counterpart of the reference Layer
(ref: python/paddle/nn/layer/layers.py:351). Holds named parameters,
buffers and sublayers; supports forward pre/post hooks, state_dict
round-trips with structured names, train/eval modes, dtype casting via
``to``/``astype``, and ``apply``.

Parameters are ``Parameter`` (a Tensor with ``stop_gradient=False``);
their arrays are jax.Arrays, so a Layer's state flows through
``paddle_tpu.jit`` functionalization as a flat list of arrays gathered by
``named_parameters``/``named_buffers`` — no pybind/VarBase machinery.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, Optional, Tuple

import numpy as np

from ...base import dtype as _dtypes
from ...base.param_attr import ParamAttr
from ...base.tensor import Tensor
from .. import initializer as I

__all__ = ["Layer", "Parameter"]

# LazyGuard (paddle.LazyGuard) state: "enabled" defers initializers in
# create_parameter; "pending" counts deferred params process-wide so
# Layer.__call__ only pays the materialization scan while some exist
_lazy_init_state = {"enabled": False, "pending": 0}


class Parameter(Tensor):
    """Trainable tensor (ref: EagerParamBase, python/paddle/base/framework.py)."""

    __slots__ = (
        "optimize_attr", "regularizer", "do_model_average", "need_clip",
        "is_distributed", "tp_axis", "ep_axis", "no_weight_decay",
        "_lazy_init",
    )

    def __init__(self, data, trainable=True, name=None, **kw):
        super().__init__(data, stop_gradient=not trainable, name=name, persistable=True, _internal=True)
        self.optimize_attr = kw.get("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kw.get("regularizer")
        self.do_model_average = kw.get("do_model_average", True)
        self.need_clip = kw.get("need_clip", True)
        self.is_distributed = False
        self.tp_axis = None  # TP sharding hint consumed by distributed wrappers
        self.ep_axis = None  # expert-parallel sharding hint (MoE stacks)
        self.no_weight_decay = False

    @property
    def trainable(self) -> bool:
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v: bool):
        self.stop_gradient = not v

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


class HookRemoveHelper:
    next_id = 0

    def __init__(self, hooks: dict):
        self._hooks = hooks
        self._id = HookRemoveHelper.next_id
        HookRemoveHelper.next_id += 1

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        self.training = True
        self._dtype = _dtypes.canonical_dtype(dtype) if dtype is not None else None
        self._parameters: Dict[str, Parameter] = collections.OrderedDict()
        self._buffers: Dict[str, Optional[Tensor]] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._sub_layers: Dict[str, "Layer"] = collections.OrderedDict()
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._full_name = name_scope or type(self).__name__.lower()
        self._casted_by_pure_fp16 = False

    # ------------------------------------------------------------------
    # parameter / buffer / sublayer registration
    # ------------------------------------------------------------------
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias: bool = False,
        default_initializer=None,
    ) -> Optional[Parameter]:
        """ref: python/paddle/nn/layer/layers.py create_parameter — bias
        defaults to zeros, weight to the global default (Xavier-uniform)."""
        attr = ParamAttr._to_attr(attr)
        if attr is None:
            return None
        dtype = _dtypes.canonical_dtype(dtype) if dtype is not None else (self._dtype or _dtypes.get_default_dtype())
        init = attr.initializer or default_initializer
        if init is None:
            init = I._default_bias_init() if is_bias else I._default_weight_init()
        if _lazy_init_state["enabled"]:
            # LazyGuard: record the initializer, materialize on first call
            import jax.numpy as _jnp

            data = _jnp.zeros((), dtype)
            p = Parameter(
                data,
                trainable=attr.trainable,
                name=attr.name,
                optimize_attr={"learning_rate": attr.learning_rate},
                regularizer=attr.regularizer,
                do_model_average=attr.do_model_average,
                need_clip=attr.need_clip,
            )
            p._lazy_init = (init, list(shape), dtype)
            _lazy_init_state["pending"] += 1
            return p
        data = init(shape, dtype)
        p = Parameter(
            data,
            trainable=attr.trainable,
            name=attr.name,
            optimize_attr={"learning_rate": attr.learning_rate},
            regularizer=attr.regularizer,
            do_model_average=attr.do_model_average,
            need_clip=attr.need_clip,
        )
        return p

    def _materialize_lazy(self):
        for p in self.parameters():
            lazy = getattr(p, "_lazy_init", None)
            if lazy is not None:
                init, shape, dtype = lazy
                p._data = init(shape, dtype)
                p._lazy_init = None
                _lazy_init_state["pending"] -= 1

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError(f"add_parameter expects Parameter, got {type(parameter)}")
        self._parameters[name] = parameter
        self.__dict__.pop(name, None)  # a prior plain value would shadow
        return parameter

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable: bool = True):
        """Non-parameter state (e.g. BN running stats); persistable buffers
        are included in state_dict (ref: layers.py register_buffer)."""
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor, _internal=True)
        self._buffers[name] = tensor
        self.__dict__.pop(name, None)  # a prior plain value would shadow
        if persistable:
            self._non_persistable_buffer_names.discard(name)
        else:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def add_sublayer(self, name: str, sublayer: "Layer"):
        if sublayer is not None and not isinstance(sublayer, Layer):
            raise TypeError(f"add_sublayer expects Layer, got {type(sublayer)}")
        self._sub_layers[name] = sublayer
        self.__dict__.pop(name, None)
        return sublayer

    # ------------------------------------------------------------------
    # attribute magic
    # ------------------------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() before assigning parameters")
            params[name] = value
            self.__dict__.pop(name, None)  # a prior plain value would shadow
            layers is not None and layers.pop(name, None)
            buffers is not None and buffers.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() before assigning sublayers")
            layers[name] = value
            self.__dict__.pop(name, None)
            params is not None and params.pop(name, None)
            buffers is not None and buffers.pop(name, None)
        elif buffers is not None and name in buffers:
            if value is not None and not isinstance(value, Tensor):
                value = Tensor(value, _internal=True)
            buffers[name] = value
        else:
            if params is not None and name in params:
                del params[name]
            if layers is not None and name in layers:
                del layers[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                self._non_persistable_buffer_names.discard(name)
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + list(self._buffers) + list(self._sub_layers)

    # ------------------------------------------------------------------
    # traversal
    # ------------------------------------------------------------------
    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self):
        seen = set()
        for name, l in self._sub_layers.items():
            if l is not None and id(l) not in seen:
                seen.add(id(l))
                yield name, l

    def sublayers(self, include_self: bool = False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix: str = "", include_self: bool = False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, l in self.named_children():
            if l is None:
                continue
            sub_prefix = prefix + ("." if prefix else "") + name
            yield from l.named_sublayers(prefix=sub_prefix, include_self=True, layers_set=layers_set)

    def parameters(self, include_sublayers: bool = True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix: str = "", include_sublayers: bool = True):
        seen = set()
        gen = (
            self.named_sublayers(prefix=prefix, include_self=True)
            if include_sublayers
            else [(prefix, self)]
        )
        for layer_prefix, layer in gen:
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield layer_prefix + ("." if layer_prefix else "") + name, p

    def buffers(self, include_sublayers: bool = True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True):
        seen = set()
        gen = (
            self.named_sublayers(prefix=prefix, include_self=True)
            if include_sublayers
            else [(prefix, self)]
        )
        for layer_prefix, layer in gen:
            for name, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield layer_prefix + ("." if layer_prefix else "") + name, b

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    def full_name(self) -> str:
        return self._full_name

    # ------------------------------------------------------------------
    # modes
    # ------------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # ------------------------------------------------------------------
    # hooks
    # ------------------------------------------------------------------
    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        helper = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[helper._id] = hook
        return helper

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        helper = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[helper._id] = hook
        return helper

    # ------------------------------------------------------------------
    # call
    # ------------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        if _lazy_init_state["pending"] and not _lazy_init_state["enabled"]:
            self._materialize_lazy()
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    # ------------------------------------------------------------------
    # state dict
    # ------------------------------------------------------------------
    def state_dict(
        self,
        destination=None,
        include_sublayers: bool = True,
        structured_name_prefix: str = "",
        use_hook: bool = True,
    ):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip("."), include_sublayers=include_sublayers):
            dest[name] = p
        gen = (
            self.named_sublayers(prefix=structured_name_prefix.rstrip("."), include_self=True)
            if include_sublayers
            else [(structured_name_prefix.rstrip("."), self)]
        )
        for layer_prefix, layer in gen:
            for name, b in layer._buffers.items():
                if b is None or name in layer._non_persistable_buffer_names:
                    continue
                dest[layer_prefix + ("." if layer_prefix else "") + name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name: bool = True):
        """Load; returns (missing_keys, unexpected_keys) like the reference."""
        own = self.state_dict()
        own_names = {t.name for t in own.values() if getattr(t, "name", None)}
        missing, matched = [], set()
        for name, target in own.items():
            if name not in state_dict:
                missing.append(name)
                continue
            value = state_dict[name]
            if isinstance(value, Tensor):
                # adopt the persistent name so optimizer state (keyed by
                # param name, ref optimizer.py _accumulators) re-attaches
                # after load — the reference gets this for free from its
                # deterministic per-class name generator. Never adopt a
                # name another live param of this layer already holds:
                # that would merge their accumulator slots.
                if (
                    value.name
                    and value is not target
                    and value.name != target.name
                    and value.name not in own_names
                ):
                    own_names.discard(target.name)
                    target.name = value.name
                    own_names.add(value.name)
                value = value._data
            value = np.asarray(value)
            if tuple(value.shape) != tuple(target.shape):
                raise ValueError(
                    f"shape mismatch for {name}: loaded {value.shape} vs layer {tuple(target.shape)}"
                )
            target.set_value(value)
            matched.add(name)
        unexpected = [k for k in state_dict if k not in own]
        return missing, unexpected

    load_dict = set_state_dict

    # ------------------------------------------------------------------
    # dtype / device movement
    # ------------------------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._cast_params(dtype)
        return self

    def astype(self, dtype):
        self._cast_params(dtype)
        return self

    def float(self):
        return self.astype("float32")

    def half(self):
        return self.astype("float16")

    def bfloat16(self):
        return self.astype("bfloat16")

    def _cast_params(self, dtype, floating_only: bool = True):
        dt = _dtypes.canonical_dtype(dtype)
        for t in list(self.parameters()) + list(self.buffers()):
            if floating_only and t.dtype.kind not in "fc" and not _dtypes.is_floating_point(t.dtype):
                continue
            t._data = t._data.astype(dt)
        self._dtype = dt
        for l in self.sublayers():
            l._dtype = dt

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    # ------------------------------------------------------------------
    # repr
    # ------------------------------------------------------------------
    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, child in self.named_children():
            child_repr = repr(child).split("\n")
            child_repr = [child_repr[0]] + ["  " + ln for ln in child_repr[1:]]
            lines.append(f"({name}): " + "\n".join(child_repr))
        main = type(self).__name__ + "("
        if extra and not lines:
            return main + extra + ")"
        body = ([extra] if extra else []) + lines
        if not body:
            return main + ")"
        return main + "\n  " + "\n  ".join(b for b in body) + "\n)"
