"""Convolution layers (ref: python/paddle/nn/layer/conv.py _ConvNd).

Weight layout: [out_channels, in_channels // groups, *kernel] (paddle);
default weight init Normal(0, sqrt(2/fan_in)) matching the reference's
_get_default_param_initializer (conv.py:170-175).
"""
from __future__ import annotations

import numpy as np

from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose", "Conv3DTranspose"]


def _tuple(v, n):
    return tuple(v) if isinstance(v, (list, tuple)) else (v,) * n


class _ConvNd(Layer):
    ndim_spatial = 2
    transposed = False

    def __init__(
        self,
        in_channels,
        out_channels,
        kernel_size,
        stride=1,
        padding=0,
        dilation=1,
        groups=1,
        padding_mode="zeros",
        weight_attr=None,
        bias_attr=None,
        data_format=None,
        output_padding=0,
    ):
        super().__init__()
        n = self.ndim_spatial
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _tuple(kernel_size, n)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._padding_mode = padding_mode
        self._data_format = data_format or ("NCL", "NCHW", "NCDHW")[n - 1]
        self._output_padding = output_padding

        if self.transposed:
            filter_shape = [in_channels, out_channels // groups] + list(self._kernel_size)
            default_init = None
        else:
            filter_shape = [out_channels, in_channels // groups] + list(self._kernel_size)
            fan = int(np.prod(self._kernel_size)) * in_channels
            default_init = I.Normal(0.0, (2.0 / fan) ** 0.5)
        self.weight = self.create_parameter(shape=filter_shape, attr=weight_attr, default_initializer=default_init)
        self.bias = self.create_parameter(shape=[out_channels], attr=bias_attr, is_bias=True)

    def extra_repr(self):
        return (
            f"{self._in_channels}, {self._out_channels}, kernel_size={self._kernel_size}, "
            f"stride={self._stride}, padding={self._padding}"
        )


class Conv1D(_ConvNd):
    ndim_spatial = 1

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride, self._padding, self._dilation, self._groups, self._data_format)


class Conv2D(_ConvNd):
    ndim_spatial = 2

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride, self._padding, self._dilation, self._groups, self._data_format)


class Conv3D(_ConvNd):
    ndim_spatial = 3

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride, self._padding, self._dilation, self._groups, self._data_format)


class Conv1DTranspose(_ConvNd):
    ndim_spatial = 1
    transposed = True

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride, self._padding, self._output_padding, self._groups, self._dilation, output_size, self._data_format)


class Conv2DTranspose(_ConvNd):
    ndim_spatial = 2
    transposed = True

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride, self._padding, self._output_padding, self._groups, self._dilation, output_size, self._data_format)


class Conv3DTranspose(_ConvNd):
    ndim_spatial = 3
    transposed = True

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride, self._padding, self._output_padding, self._groups, self._dilation, output_size, self._data_format)
