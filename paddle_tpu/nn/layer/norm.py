"""Normalization layers.

ref: python/paddle/nn/layer/norm.py (_BatchNormBase, LayerNorm, GroupNorm,
InstanceNorm*, SyncBatchNorm). BN running stats are registered buffers;
on the TPU DP path SyncBatchNorm's cross-replica stats are what GSPMD
computes automatically when the batch axis is sharded, so SyncBatchNorm
aliases BatchNorm (documented divergence: identical numerics under
sharded jit, no extra collective needed eagerly).
"""
from __future__ import annotations

import jax.numpy as jnp

from ...base.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = [
    "BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D", "SyncBatchNorm",
    "LayerNorm", "GroupNorm", "InstanceNorm1D", "InstanceNorm2D", "InstanceNorm3D",
    "LocalResponseNorm", "RMSNorm", "SpectralNorm",
]


class _BatchNormBase(Layer):
    def __init__(
        self,
        num_features,
        momentum=0.9,
        epsilon=1e-5,
        weight_attr=None,
        bias_attr=None,
        data_format="NCHW",
        use_global_stats=None,
        name=None,
    ):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            shape=[num_features], attr=weight_attr, default_initializer=I.Constant(1.0)
        )
        self.bias = self.create_parameter(shape=[num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features]), _internal=True))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features]), _internal=True))

    def forward(self, x):
        return F.batch_norm(
            x,
            self._mean,
            self._variance,
            self.weight,
            self.bias,
            training=self.training,
            momentum=self._momentum,
            epsilon=self._epsilon,
            data_format=self._data_format,
            use_global_stats=self._use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}, epsilon={self._epsilon}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN: under sharded jit (DP over a mesh) XLA computes
    global batch stats automatically; eager single-process equals BN."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(
                layer._num_features, layer._momentum, layer._epsilon,
                data_format=layer._data_format,
            )
            new.weight.set_value(layer.weight)
            new.bias.set_value(layer.bias)
            new._mean.set_value(layer._mean)
            new._variance.set_value(layer._variance)
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                shape=self._normalized_shape, attr=weight_attr, default_initializer=I.Constant(1.0)
            )
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(shape=self._normalized_shape, attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """ref: python/paddle/incubate/nn/functional/fused_rms_norm — exposed
    as a first-class layer (Llama-family building block)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            shape=[hidden_size], attr=weight_attr, default_initializer=I.Constant(1.0)
        )

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[num_channels], attr=weight_attr, default_initializer=I.Constant(1.0)
        )
        self.bias = self.create_parameter(shape=[num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight, self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter(
                shape=[num_features], attr=weight_attr, default_initializer=I.Constant(1.0)
            )
            self.bias = self.create_parameter(shape=[num_features], attr=bias_attr, is_bias=True)
        else:
            self.weight = self.bias = None

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias, eps=self._epsilon, data_format=self._data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        s, a, b, k, df = self.args
        return F.local_response_norm(x, s, a, b, k, df)


class SpectralNorm(Layer):
    """Power-iteration spectral normalization of a weight tensor
    (ref: nn/layer/norm.py SpectralNorm)."""

    def __init__(self, weight_shape, axis=0, power_iters=1, epsilon=1e-12, dtype="float32"):
        super().__init__()
        self._axis = axis
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[axis]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != axis:
                w *= s
        self.weight_u = self.create_parameter(shape=[h], default_initializer=I.Normal(0, 1))
        self.weight_v = self.create_parameter(shape=[w], default_initializer=I.Normal(0, 1))
        self.weight_u.stop_gradient = True
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...base.tape import apply

        axis, eps, iters = self._axis, self._epsilon, self._power_iters

        def _f(w, u, v):
            wm = jnp.moveaxis(w, axis, 0).reshape(w.shape[axis], -1)
            for _ in range(iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return w / sigma

        return apply(_f, weight, self.weight_u, self.weight_v, op_name="spectral_norm")
