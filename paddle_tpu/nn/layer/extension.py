"""Parity-sweep layers wrapping the extended functionals.

ref: python/paddle/nn/layer/{common,loss,pooling,distance}.py entries
and python/paddle/nn/decode.py (BeamSearchDecoder / dynamic_decode).
"""
from __future__ import annotations

import numpy as np

from ...base.tensor import Tensor
from .. import functional as F
from .layers import Layer

__all__ = [
    "Unflatten", "Softmax2D", "ZeroPad1D", "ZeroPad3D", "FeatureAlphaDropout",
    "CTCLoss", "RNNTLoss", "HSigmoidLoss", "MultiMarginLoss",
    "TripletMarginWithDistanceLoss", "GaussianNLLLoss",
    "AdaptiveLogSoftmaxWithLoss", "MaxUnPool1D", "MaxUnPool3D",
    "FractionalMaxPool2D", "FractionalMaxPool3D",
    "BeamSearchDecoder", "dynamic_decode",
]


class Unflatten(Layer):
    """ref: nn/layer/common.py Unflatten."""

    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, shape

    def forward(self, x):
        from ... import tensor as T

        return T.unflatten(x, self.axis, self.shape)


class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW (ref: activation.py Softmax2D)."""

    def forward(self, x):
        if x.ndim not in (3, 4):
            raise ValueError("Softmax2D expects 3-D or 4-D input")
        return F.softmax(x, axis=-3)


class ZeroPad1D(Layer):
    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__()
        self.padding, self.data_format = padding, data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode="constant", value=0.0, data_format=self.data_format)


class ZeroPad3D(Layer):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__()
        self.padding, self.data_format = padding, data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode="constant", value=0.0, data_format=self.data_format)


class FeatureAlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.feature_alpha_dropout(x, self.p, training=self.training)


class CTCLoss(Layer):
    """ref: nn/layer/loss.py CTCLoss."""

    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths, norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class RNNTLoss(Layer):
    """ref: nn/layer/loss.py RNNTLoss."""

    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean", name=None):
        super().__init__()
        self.blank, self.fastemit_lambda, self.reduction = blank, fastemit_lambda, reduction

    def forward(self, input, label, input_lengths, label_lengths):  # noqa: A002
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           self.blank, self.fastemit_lambda, self.reduction)


class HSigmoidLoss(Layer):
    """ref: nn/layer/loss.py HSigmoidLoss — holds the [num_classes-1, D]
    internal-node table."""

    def __init__(self, feature_size, num_classes, weight_attr=None, bias_attr=None,
                 is_custom=False, is_sparse=False, name=None):
        super().__init__()
        self.num_classes = num_classes
        self.weight = self.create_parameter([num_classes - 1, feature_size], attr=weight_attr)
        self.bias = self.create_parameter([num_classes - 1], attr=bias_attr, is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):  # noqa: A002
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight, self.bias,
                               path_table, path_code)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean", name=None):
        super().__init__()
        self.p, self.margin, self.weight, self.reduction = p, margin, weight, reduction

    def forward(self, input, label):  # noqa: A002
        return F.multi_margin_loss(input, label, self.p, self.margin, self.weight, self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False, reduction="mean", name=None):
        super().__init__()
        self.distance_function, self.margin = distance_function, margin
        self.swap, self.reduction = swap, reduction

    def forward(self, input, positive, negative):  # noqa: A002
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, self.distance_function, self.margin,
            self.swap, self.reduction,
        )


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self.full, self.epsilon, self.reduction = full, epsilon, reduction

    def forward(self, input, label, variance):  # noqa: A002
        return F.gaussian_nll_loss(input, label, variance, self.full, self.epsilon, self.reduction)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """ref: nn/layer/loss.py AdaptiveLogSoftmaxWithLoss — head table +
    factorized tail projections per cluster."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        if any(c <= 0 or c >= n_classes for c in cutoffs) or sorted(set(cutoffs)) != cutoffs:
            raise ValueError("cutoffs must be increasing, in (0, n_classes)")
        self.cutoffs = cutoffs + [n_classes]
        self.n_clusters = len(cutoffs)
        head_size = cutoffs[0] + self.n_clusters
        self.head_weight = self.create_parameter([in_features, head_size])
        self.head_bias_p = self.create_parameter([head_size], is_bias=True) if head_bias else None
        self.tail_weights = []
        for i in range(self.n_clusters):
            hsz = max(1, int(in_features / (div_value ** (i + 1))))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            proj = self.create_parameter([in_features, hsz])
            cls_w = self.create_parameter([hsz, osz])
            self.add_parameter(f"tail_proj_{i}", proj)
            self.add_parameter(f"tail_cls_{i}", cls_w)
            self.tail_weights.append([proj, cls_w])

    def forward(self, input, label):  # noqa: A002
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights,
            self.cutoffs[:-1], self.head_bias_p,
        )

    def log_prob(self, input):  # noqa: A002
        import jax.numpy as jnp

        from ...base.tape import apply

        def _f(x, hw, *rest):
            hb = rest[-1] if self.head_bias_p is not None else None
            tails = rest[: 2 * self.n_clusters]
            head_logits = x @ hw
            if hb is not None:
                head_logits = head_logits + hb
            import jax

            head_logp = jax.nn.log_softmax(head_logits, -1)
            short = self.cutoffs[0]
            outs = [head_logp[:, :short]]
            for i in range(self.n_clusters):
                tail_logp = jax.nn.log_softmax((x @ tails[2 * i]) @ tails[2 * i + 1], -1)
                outs.append(head_logp[:, short + i:short + i + 1] + tail_logp)
            return jnp.concatenate(outs, -1)

        args = [input, self.head_weight] + [w for pair in self.tail_weights for w in pair]
        if self.head_bias_p is not None:
            args.append(self.head_bias_p)
        return apply(_f, *args, op_name="adaptive_log_softmax")

    def predict(self, input):  # noqa: A002
        return self.log_prob(input).argmax(-1)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCL",
                 output_size=None, name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format, self.output_size = data_format, output_size

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format, self.output_size)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCDHW",
                 output_size=None, name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format, self.output_size = data_format, output_size

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format, self.output_size)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size, self.kernel_size = output_size, kernel_size
        self.random_u, self.return_mask = random_u, return_mask

    def forward(self, x):
        return F.fractional_max_pool2d(x, self.output_size, self.kernel_size,
                                       self.random_u, self.return_mask)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size, self.kernel_size = output_size, kernel_size
        self.random_u, self.return_mask = random_u, return_mask

    def forward(self, x):
        return F.fractional_max_pool3d(x, self.output_size, self.kernel_size,
                                       self.random_u, self.return_mask)


# ---------------------------------------------------------------------------
# decoding (ref: python/paddle/nn/decode.py)
# ---------------------------------------------------------------------------


class BeamSearchDecoder:
    """Beam-search decoder over an RNN cell (ref: decode.py
    BeamSearchDecoder). Works with the greedy/eager dynamic_decode loop
    below — each step expands beam_size hypotheses with length-normalized
    log-prob scores."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token, self.end_token = start_token, end_token
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def initialize(self, initial_cell_states, batch_size):
        import numpy as _np

        from ...base.tensor import to_tensor

        ids = to_tensor(_np.full((batch_size, self.beam_size), self.start_token, _np.int64))
        scores = _np.full((batch_size, self.beam_size), -1e9, _np.float32)
        scores[:, 0] = 0.0
        return ids, to_tensor(scores), initial_cell_states

    def step(self, inputs, states):
        """One cell step + projection; returns log-probs over vocab."""
        cell_out, new_states = self.cell(inputs, states)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        logp = F.log_softmax(cell_out, axis=-1)
        return logp, new_states


def dynamic_decode(decoder, inits=None, max_step_num=100, batch_size=None,
                   is_test=False, return_length=False, **kwargs):
    """Run a BeamSearchDecoder to completion (ref: decode.py
    dynamic_decode). Host-driven loop (decode length is data-dependent);
    each step's compute is compiled. Returns (ids, scores) like the
    reference ([B, T, beam] ids)."""
    import jax.numpy as jnp
    import numpy as _np

    from ...base.tensor import to_tensor

    if batch_size is None:
        raise ValueError("dynamic_decode needs batch_size")
    B, K = batch_size, decoder.beam_size
    ids, scores, states = decoder.initialize(inits, B)
    # flatten beams into the batch dim for the cell
    collected = []
    fin = _np.zeros((B, K), bool)
    scores_np = _np.asarray(scores.numpy(), _np.float32)
    cur_tok = _np.asarray(ids.numpy())
    for step in range(max_step_num):
        if decoder.embedding_fn is not None:
            inp = decoder.embedding_fn(to_tensor(cur_tok.reshape(B * K)))
        else:
            inp = to_tensor(cur_tok.reshape(B * K).astype(_np.int64))
        logp, states = decoder.step(inp, states)
        lp = _np.asarray(logp.numpy(), _np.float32).reshape(B, K, -1)
        V = lp.shape[-1]
        # finished beams only extend with end_token at score 0
        lp_masked = lp.copy()
        lp_masked[fin] = -1e9
        lp_masked[fin, decoder.end_token] = 0.0
        total = scores_np[:, :, None] + lp_masked  # [B, K, V]
        flat = total.reshape(B, K * V)
        top = _np.argsort(-flat, axis=1)[:, :K]
        beam_idx = top // V
        tok = top % V
        scores_np = _np.take_along_axis(flat, top, 1)
        fin = _np.take_along_axis(fin, beam_idx, 1) | (tok == decoder.end_token)
        collected.append(tok)
        cur_tok = tok
        # reorder cell states along the beam dim
        states = _reorder_states(states, beam_idx, B, K)
        if fin.all():
            break
    out_ids = _np.stack(collected, 1)  # [B, T, K]
    return to_tensor(out_ids.astype(_np.int64)), to_tensor(scores_np)


def _reorder_states(states, beam_idx, B, K):
    import numpy as _np

    from ...base.tensor import to_tensor

    def reorder(t):
        arr = _np.asarray(t.numpy())
        arr = arr.reshape(B, K, -1)
        g = _np.take_along_axis(arr, beam_idx[:, :, None], 1)
        return to_tensor(g.reshape(B * K, -1).astype(arr.dtype))

    if isinstance(states, (tuple, list)):
        return type(states)(reorder(s) for s in states)
    return reorder(states)
