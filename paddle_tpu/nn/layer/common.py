"""Common layers: Linear, Dropout, Embedding, Flatten, padding, upsample.

ref: python/paddle/nn/layer/common.py. Linear stores W as
[in_features, out_features] (paddle layout; XLA MXU-friendly either way).
"""
from __future__ import annotations

import numpy as np

from .. import functional as F
from .layers import Layer

__all__ = [
    "Linear", "Dropout", "Dropout2D", "Dropout3D", "AlphaDropout", "Embedding",
    "Flatten", "Identity", "Pad1D", "Pad2D", "Pad3D", "ZeroPad2D", "Upsample",
    "UpsamplingBilinear2D", "UpsamplingNearest2D", "CosineSimilarity",
    "PairwiseDistance", "Bilinear", "Unfold", "Fold", "PixelShuffle",
    "PixelUnshuffle", "ChannelShuffle",
]


class Linear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(shape=[in_features, out_features], attr=weight_attr)
        self.bias = self.create_parameter(shape=[out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, out_features={self._out_features}"


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.axis, self.mode = p, axis, mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training, mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}, mode={self.mode}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training, data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training, data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None, sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        from .. import initializer as I

        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim],
            attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0) if weight_attr is None else None,
        )
        if padding_idx is not None:
            pidx = padding_idx if padding_idx >= 0 else num_embeddings + padding_idx
            import jax.numpy as jnp

            self.weight._data = self.weight._data.at[pidx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        from ...tensor import manipulation as M

        return M.flatten(x, self.start_axis, self.stop_axis)


class _PadNd(Layer):
    ndim_spatial = 2

    def __init__(self, padding, mode="constant", value=0.0, data_format=None, name=None):
        super().__init__()
        if isinstance(padding, int):
            padding = [padding] * (2 * self.ndim_spatial)
        self.padding, self.mode, self.value = padding, mode, value
        self.data_format = data_format or ("NCL", "NCHW", "NCDHW")[self.ndim_spatial - 1]

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad1D(_PadNd):
    ndim_spatial = 1


class Pad2D(_PadNd):
    ndim_spatial = 2


class Pad3D(_PadNd):
    ndim_spatial = 3


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format, name)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners, self.align_mode = mode, align_corners, align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode, self.align_corners, self.align_mode, self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format, name)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format, name)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        from ...base.tape import apply
        import jax.numpy as jnp

        return apply(
            lambda a, b: jnp.power(
                jnp.sum(jnp.abs(a - b) ** self.p, axis=-1, keepdims=self.keepdim) + self.epsilon,
                1.0 / self.p,
            ),
            x, y, op_name="pairwise_distance",
        )


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(shape=[out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = self.create_parameter(shape=[out_features], attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, self.output_sizes, *self.args)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor, self.data_format = upscale_factor, data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor, self.data_format = downscale_factor, data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups, self.data_format = groups, data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)
