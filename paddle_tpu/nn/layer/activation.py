"""Activation layers (ref: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = [
    "CELU", "ELU", "GELU", "GLU", "Hardshrink", "Hardsigmoid", "Hardswish",
    "Hardtanh", "LeakyReLU", "LogSigmoid", "LogSoftmax", "Maxout", "Mish",
    "PReLU", "ReLU", "ReLU6", "RReLU", "SELU", "Sigmoid", "Silu", "Softmax",
    "Softplus", "Softshrink", "Softsign", "Swish", "Tanh", "Tanhshrink",
    "ThresholdedReLU",
]


class _Act(Layer):
    _fn = None
    _kwargs: dict = {}

    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return type(self)._fn(x, **self._kwargs)


class ReLU(_Act):
    _fn = staticmethod(F.relu)


class Sigmoid(_Act):
    _fn = staticmethod(F.sigmoid)


class Silu(_Act):
    _fn = staticmethod(F.silu)


class Tanh(_Act):
    _fn = staticmethod(F.tanh)


class ReLU6(_Act):
    _fn = staticmethod(F.relu6)


class LogSigmoid(_Act):
    _fn = staticmethod(F.log_sigmoid)


class Mish(_Act):
    _fn = staticmethod(F.mish)


class Tanhshrink(_Act):
    _fn = staticmethod(F.tanhshrink)


class Softsign(_Act):
    _fn = staticmethod(F.softsign)


class Swish(_Act):
    _fn = staticmethod(F.swish)


class Hardswish(_Act):
    _fn = staticmethod(F.hardswish)


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return F.gelu(x, self.approximate)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.elu(x, self.alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.celu(x, self.alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554804934193349852946, alpha=1.6732632423543772848170429916717, name=None):
        super().__init__()
        self.scale, self.alpha = scale, alpha

    def forward(self, x):
        return F.selu(x, self.scale, self.alpha)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None, data_format="NCHW", name=None):
        super().__init__()
        from .. import initializer as I

        self.data_format = data_format
        self.weight = self.create_parameter(
            shape=[num_parameters], attr=weight_attr, default_initializer=I.Constant(init)
        )

    def forward(self, x):
        return F.prelu(x, self.weight, self.data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self.threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self.threshold)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):  # noqa: A002
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return F.hardtanh(x, self.min, self.max)


class Hardsigmoid(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.hardsigmoid(x)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self.beta, self.threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, self.beta, self.threshold)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, value=0.0, name=None):
        super().__init__()
        self.threshold, self.value = threshold, value

    def forward(self, x):
        return F.thresholded_relu(x, self.threshold, self.value)


class Softmax(Layer):
    def __init__(self, axis=-1, dtype=None, name=None):
        super().__init__()
        self.axis, self._softmax_dtype = axis, dtype

    def forward(self, x):
        return F.softmax(x, self.axis, self._softmax_dtype)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, self.axis)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.glu(x, self.axis)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)
