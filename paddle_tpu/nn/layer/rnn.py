"""Recurrent layers — SimpleRNN/LSTM/GRU cells and multi-layer wrappers.

ref: python/paddle/nn/layer/rnn.py (SimpleRNNCell:741, LSTMCell:918,
GRUCell:1144, RNN:1339, BiRNN:1421, RNNBase:1514). Formulas, weight
layouts ((gates*hidden, input) / (gates*hidden, hidden), gate order
i,f,g,o for LSTM and r,z,c for GRU), state shapes and the
(outputs, final_states) contract follow the reference exactly.

TPU-native design: the reference lowers to a fused rnn CUDNN kernel or
a python while-loop over time steps (_rnn_dynamic/_rnn_static). Here
the whole sequence runs as ONE ``lax.scan`` over time inside a single
tape.apply — XLA unrolls nothing, compiles one step body (two fused
gate matmuls on the MXU) and the backward is the transposed scan, so
eager per-step dispatch overhead (SURVEY §3.1) never appears. Variable
lengths (``sequence_length``) are handled with masked state carries
inside the scan instead of the reference's sequence reversal ops.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ...base import tape
from ...base.tensor import Tensor
from .. import initializer as I
from .layers import Layer
from .container import LayerList

__all__ = [
    "RNNCellBase",
    "SimpleRNNCell",
    "LSTMCell",
    "GRUCell",
    "RNN",
    "BiRNN",
    "SimpleRNN",
    "LSTM",
    "GRU",
]


class RNNCellBase(Layer):
    """Base for single-step recurrent cells (ref: rnn.py:590)."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None, init_value=0.0):
        batch = batch_ref.shape[0]
        n = getattr(self, "state_components", 1)
        shapes = [[batch, self.hidden_size]] * n if shape is None else shape
        outs = tuple(
            Tensor(jnp.full(tuple(s), init_value, dtype or jnp.float32), _internal=True)
            for s in shapes
        )
        return outs if n > 1 else outs[0]

    def _uniform_init(self):
        std = 1.0 / math.sqrt(self.hidden_size)
        return I.Uniform(-std, std)


class SimpleRNNCell(RNNCellBase):
    """h' = act(W_ih x + b_ih + W_hh h + b_hh) (ref: rnn.py:741)."""

    state_components = 1

    def __init__(
        self,
        input_size,
        hidden_size,
        activation="tanh",
        weight_ih_attr=None,
        weight_hh_attr=None,
        bias_ih_attr=None,
        bias_hh_attr=None,
        name=None,
    ):
        super().__init__()
        if activation not in ("tanh", "relu"):
            raise ValueError("activation must be tanh or relu")
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        init = self._uniform_init()
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], attr=weight_ih_attr, default_initializer=init
        )
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], attr=weight_hh_attr, default_initializer=init
        )
        self.bias_ih = self.create_parameter(
            [hidden_size], attr=bias_ih_attr, is_bias=True, default_initializer=init
        )
        self.bias_hh = self.create_parameter(
            [hidden_size], attr=bias_hh_attr, is_bias=True, default_initializer=init
        )

    def _params(self):
        return [p for p in (self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh) if p is not None]

    def _step(self, x, state, wih, whh, bih=None, bhh=None):
        """Pure jnp one-step body; state is a 1-tuple."""
        (h,) = state
        pre = x @ wih.T + h @ whh.T
        if bih is not None:
            pre = pre + bih
        if bhh is not None:
            pre = pre + bhh
        h = jnp.tanh(pre) if self.activation == "tanh" else jnp.maximum(pre, 0)
        return h, (h,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        out = tape.apply(
            lambda x, h, *ps: self._step(x, (h,), *ps),
            inputs, states, *self._params(), op_name="simple_rnn_cell",
        )
        y, (h,) = out
        return y, h


class LSTMCell(RNNCellBase):
    """Gate order i,f,g,o; c' = f*c + i*g; h' = o*tanh(c') (ref: rnn.py:918)."""

    state_components = 2

    def __init__(
        self,
        input_size,
        hidden_size,
        weight_ih_attr=None,
        weight_hh_attr=None,
        bias_ih_attr=None,
        bias_hh_attr=None,
        proj_size=None,
        name=None,
    ):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.proj_size = proj_size or 0
        if self.proj_size and self.proj_size >= hidden_size:
            raise ValueError("proj_size must be smaller than hidden_size")
        init = self._uniform_init()
        h_in = self.proj_size or hidden_size
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], attr=weight_ih_attr, default_initializer=init
        )
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, h_in], attr=weight_hh_attr, default_initializer=init
        )
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], attr=bias_ih_attr, is_bias=True, default_initializer=init
        )
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], attr=bias_hh_attr, is_bias=True, default_initializer=init
        )
        self.weight_ho = (
            self.create_parameter([hidden_size, self.proj_size], default_initializer=init)
            if self.proj_size
            else None
        )

    def _params(self):
        ps = [self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh]
        if self.weight_ho is not None:
            ps.append(self.weight_ho)
        return [p for p in ps if p is not None]

    def get_initial_states(self, batch_ref, shape=None, dtype=None, init_value=0.0):
        batch = batch_ref.shape[0]
        h_size = self.proj_size or self.hidden_size
        mk = lambda n: Tensor(jnp.full((batch, n), init_value, dtype or jnp.float32), _internal=True)
        return (mk(h_size), mk(self.hidden_size))

    def _step(self, x, state, wih, whh, bih=None, bhh=None, who=None):
        h, c = state
        gates = x @ wih.T + h @ whh.T
        if bih is not None:
            gates = gates + bih
        if bhh is not None:
            gates = gates + bhh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c = f * c + i * g
        h = o * jnp.tanh(c)
        if who is not None:
            h = h @ who
        return h, (h, c)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        out = tape.apply(
            lambda x, h, c, *ps: self._step(x, (h, c), *ps),
            inputs, states[0], states[1], *self._params(), op_name="lstm_cell",
        )
        y, (h, c) = out
        return y, (h, c)


class GRUCell(RNNCellBase):
    """Gate order r,z,c; h' = z*h + (1-z)*c~ (ref: rnn.py:1144)."""

    state_components = 1

    def __init__(
        self,
        input_size,
        hidden_size,
        weight_ih_attr=None,
        weight_hh_attr=None,
        bias_ih_attr=None,
        bias_hh_attr=None,
        name=None,
    ):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        init = self._uniform_init()
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], attr=weight_ih_attr, default_initializer=init
        )
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], attr=weight_hh_attr, default_initializer=init
        )
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], attr=bias_ih_attr, is_bias=True, default_initializer=init
        )
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], attr=bias_hh_attr, is_bias=True, default_initializer=init
        )

    def _params(self):
        return [p for p in (self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh) if p is not None]

    def _step(self, x, state, wih, whh, bih=None, bhh=None):
        (h,) = state
        xg = x @ wih.T
        hg = h @ whh.T
        if bih is not None:
            xg = xg + bih
        if bhh is not None:
            hg = hg + bhh
        xr, xz, xc = jnp.split(xg, 3, axis=-1)
        hr, hz, hc = jnp.split(hg, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        c = jnp.tanh(xc + r * hc)
        h = z * h + (1.0 - z) * c
        return h, (h,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        out = tape.apply(
            lambda x, h, *ps: self._step(x, (h,), *ps),
            inputs, states, *self._params(), op_name="gru_cell",
        )
        y, (h,) = out
        return y, h


def _scan_rnn(cell, inputs, init_state, params, is_reverse, seq_len):
    """Pure jnp: scan ``cell._step`` over time-major [T, B, ...] inputs.

    seq_len masking: steps at-or-beyond a sequence's length leave its
    state unchanged and emit zeros (the reference zero-pads outputs
    past the valid region)."""
    T = inputs.shape[0]

    def body(carry, xt):
        t, state = carry
        y, new_state = cell._step(xt, state, *params)
        if seq_len is not None:
            step = (T - 1 - t) if is_reverse else t
            alive = (step < seq_len)[:, None]
            new_state = tuple(
                jnp.where(alive, ns, s) for ns, s in zip(new_state, state)
            )
            y = jnp.where(alive, y, jnp.zeros_like(y))
        return (t + 1, new_state), y

    xs = jnp.flip(inputs, 0) if is_reverse else inputs
    (_, final), ys = lax.scan(body, (0, init_state), xs)
    if is_reverse:
        ys = jnp.flip(ys, 0)
    return ys, final


class RNN(Layer):
    """Wrap a cell into a full-sequence layer via one lax.scan
    (ref: rnn.py:1339)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if initial_states is None:
            b = inputs.shape[1] if self.time_major else inputs.shape[0]
            fake = Tensor(jnp.zeros((b, 1)), _internal=True)
            initial_states = self.cell.get_initial_states(fake)
        states = initial_states if isinstance(initial_states, (tuple, list)) else (initial_states,)
        params = self.cell._params()
        n_state = len(states)

        def f(x, *rest):
            sts = rest[:n_state]
            if sequence_length is not None:
                sl = rest[n_state]
                ps = rest[n_state + 1:]
            else:
                sl = None
                ps = rest[n_state:]
            xt = x if self.time_major else jnp.swapaxes(x, 0, 1)
            ys, final = _scan_rnn(self.cell, xt, tuple(sts), ps, self.is_reverse, sl)
            if not self.time_major:
                ys = jnp.swapaxes(ys, 0, 1)
            return ys, final

        args = (inputs,) + tuple(states)
        if sequence_length is not None:
            args = args + (sequence_length,)
        out = tape.apply(f, *args, *params, op_name="rnn_scan")
        ys, final = out
        if n_state == 1:
            return ys, final[0]
        return ys, tuple(final)


class BiRNN(Layer):
    """Forward + backward cells, outputs concatenated (ref: rnn.py:1421)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw, self.cell_bw = cell_fw, cell_bw
        self.time_major = time_major
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if initial_states is None:
            fw_states = bw_states = None
        else:
            fw_states, bw_states = initial_states
        y_fw, s_fw = self.rnn_fw(inputs, fw_states, sequence_length)
        y_bw, s_bw = self.rnn_bw(inputs, bw_states, sequence_length)
        from ... import tensor as T

        y = T.concat([y_fw, y_bw], axis=-1)
        return y, (s_fw, s_bw)


class RNNBase(LayerList):
    """Multi-layer, optionally bidirectional stack (ref: rnn.py:1514)."""

    def __init__(
        self,
        mode,
        input_size,
        hidden_size,
        num_layers=1,
        direction="forward",
        time_major=False,
        dropout=0.0,
        weight_ih_attr=None,
        weight_hh_attr=None,
        bias_ih_attr=None,
        bias_hh_attr=None,
        proj_size=0,
        activation="tanh",
    ):
        super().__init__()
        bidirectional = direction in ("bidirectional", "bidirect")
        if not bidirectional and direction != "forward":
            raise ValueError(f"direction should be forward or bidirect, got {direction}")
        self.mode = mode
        self.input_size, self.hidden_size = input_size, hidden_size
        self.num_layers = num_layers
        self.num_directions = 2 if bidirectional else 1
        self.time_major = time_major
        self.dropout = dropout
        self.state_components = 2 if mode == "LSTM" else 1
        self.proj_size = proj_size

        kwargs = dict(
            weight_ih_attr=weight_ih_attr,
            weight_hh_attr=weight_hh_attr,
            bias_ih_attr=bias_ih_attr,
            bias_hh_attr=bias_hh_attr,
        )
        if mode == "LSTM":
            mk = lambda i: LSTMCell(i, hidden_size, proj_size=proj_size or None, **kwargs)
        elif mode == "GRU":
            mk = lambda i: GRUCell(i, hidden_size, **kwargs)
        else:
            act = "relu" if mode == "RNN_RELU" else ("tanh" if mode == "RNN_TANH" else activation)
            mk = lambda i: SimpleRNNCell(i, hidden_size, activation=act, **kwargs)

        out_size = (proj_size or hidden_size) * self.num_directions
        for layer in range(num_layers):
            in_size = input_size if layer == 0 else out_size
            if bidirectional:
                self.append(BiRNN(mk(in_size), mk(in_size), time_major))
            else:
                self.append(RNN(mk(in_size), False, time_major))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        """Returns (outputs, final_states); final h/c are stacked to
        [num_layers * num_directions, B, size] like the reference."""
        from ... import tensor as T
        from .. import functional as F

        L, D = self.num_layers, self.num_directions
        per_layer_states = [None] * L
        if initial_states is not None:
            if self.state_components == 2:
                h0, c0 = initial_states
                for l in range(L):
                    if D == 2:
                        per_layer_states[l] = (
                            (h0[2 * l], c0[2 * l]),
                            (h0[2 * l + 1], c0[2 * l + 1]),
                        )
                    else:
                        per_layer_states[l] = (h0[l], c0[l])
            else:
                h0 = initial_states
                for l in range(L):
                    per_layer_states[l] = (
                        (h0[2 * l], h0[2 * l + 1]) if D == 2 else h0[l]
                    )

        x = inputs
        finals = []
        for l, rnn in enumerate(self):
            x, fin = rnn(x, per_layer_states[l], sequence_length)
            finals.append(fin)
            if self.dropout and l < L - 1:
                x = F.dropout(x, self.dropout, training=self.training)

        # stack finals: [L*D, B, size] per state component
        def collect(comp):
            outs = []
            for l in range(L):
                fin = finals[l]
                if D == 2:
                    fw, bw = fin
                    outs.append(fw[comp] if self.state_components == 2 else fw)
                    outs.append(bw[comp] if self.state_components == 2 else bw)
                else:
                    outs.append(fin[comp] if self.state_components == 2 else fin)
            return T.stack(outs, axis=0)

        if self.state_components == 2:
            state = (collect(0), collect(1))
        else:
            state = collect(0)
        return x, state


class SimpleRNN(RNNBase):
    """ref: rnn.py:1859."""

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kwargs):
        mode = "RNN_RELU" if activation == "relu" else "RNN_TANH"
        super().__init__(mode, input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation=activation, **kwargs)


class LSTM(RNNBase):
    """ref: rnn.py:1982."""

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, proj_size=0, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, proj_size=proj_size, **kwargs)


class GRU(RNNBase):
    """ref: rnn.py:2119."""

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)
