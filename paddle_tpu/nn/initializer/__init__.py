"""Parameter initializers.

TPU-native counterpart of python/paddle/nn/initializer/ (ref:
python/paddle/nn/initializer/__init__.py). Each initializer is a callable
``init(shape, dtype) -> jax.Array`` drawing from the framework's default
splittable Generator (paddle_tpu.base.random), so initialization is
reproducible under ``paddle_tpu.seed`` and trace-safe.

Fan computation follows the reference's ``_compute_fans``
(ref: python/paddle/nn/initializer/xavier.py): 2-D weights are [fan_in,
fan_out] (paddle Linear stores W as [in, out]); >2-D uses
shape[1]*receptive as fan_in, shape[0]*receptive as fan_out.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...base import random as _random
from ...base import dtype as _dtypes

__all__ = [
    "Initializer",
    "Constant",
    "Normal",
    "TruncatedNormal",
    "Uniform",
    "XavierNormal",
    "XavierUniform",
    "KaimingNormal",
    "KaimingUniform",
    "Assign",
    "Orthogonal",
    "Dirac",
    "calculate_gain",
    "set_global_initializer",
 "Bilinear",]


def _compute_fans(shape):
    if not shape:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


def calculate_gain(nonlinearity, param=None):
    """ref: python/paddle/nn/initializer/initializer.py calculate_gain."""
    recommended = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "conv1d_transpose": 1.0,
        "conv2d_transpose": 1.0,
        "conv3d_transpose": 1.0,
        "tanh": 5.0 / 3,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    if nonlinearity not in recommended:
        raise ValueError(f"unsupported nonlinearity: {nonlinearity}")
    return recommended[nonlinearity]


class Initializer:
    def __call__(self, shape, dtype=None):
        dtype = _dtypes.canonical_dtype(dtype) if dtype is not None else _dtypes.get_default_dtype()
        return self._generate(tuple(int(s) for s in shape), dtype)

    def _generate(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _generate(self, shape, dtype):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def _generate(self, shape, dtype):
        sample_dt = dtype if np.dtype(dtype).kind == "f" else jnp.float32
        out = self.mean + self.std * jax.random.normal(_random.next_key(), shape, sample_dt)
        return out.astype(dtype)


class TruncatedNormal(Initializer):
    """Normal truncated to [mean + a*std, mean + b*std] (ref:
    python/paddle/nn/initializer/normal.py TruncatedNormal)."""

    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0, name=None):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def _generate(self, shape, dtype):
        sample_dt = dtype if np.dtype(dtype).kind == "f" else jnp.float32
        out = jax.random.truncated_normal(_random.next_key(), self.a, self.b, shape, sample_dt)
        return (self.mean + self.std * out).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def _generate(self, shape, dtype):
        sample_dt = dtype if np.dtype(dtype).kind == "f" else jnp.float32
        out = jax.random.uniform(_random.next_key(), shape, sample_dt, self.low, self.high)
        return out.astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype):
        f_in, f_out = _compute_fans(shape)
        f_in = self.fan_in if self.fan_in is not None else f_in
        f_out = self.fan_out if self.fan_out is not None else f_out
        std = self.gain * math.sqrt(2.0 / (f_in + f_out))
        return Normal(0.0, std)._generate(shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype):
        f_in, f_out = _compute_fans(shape)
        f_in = self.fan_in if self.fan_in is not None else f_in
        f_out = self.fan_out if self.fan_out is not None else f_out
        limit = self.gain * math.sqrt(6.0 / (f_in + f_out))
        return Uniform(-limit, limit)._generate(shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def _generate(self, shape, dtype):
        f_in, _ = _compute_fans(shape)
        f_in = self.fan_in if self.fan_in is not None else f_in
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(f_in)
        return Normal(0.0, std)._generate(shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def _generate(self, shape, dtype):
        f_in, _ = _compute_fans(shape)
        f_in = self.fan_in if self.fan_in is not None else f_in
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / f_in)
        return Uniform(-limit, limit)._generate(shape, dtype)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def _generate(self, shape, dtype):
        v = self.value
        if hasattr(v, "_data"):
            v = v._data
        arr = jnp.asarray(np.asarray(v), dtype=dtype)
        if tuple(arr.shape) != shape:
            arr = arr.reshape(shape)
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def _generate(self, shape, dtype):
        if len(shape) < 2:
            raise ValueError("Orthogonal initializer needs >=2 dims")
        rows, cols = shape[0], int(np.prod(shape[1:]))
        flat = jax.random.normal(_random.next_key(), (max(rows, cols), min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        q = q.T if rows < cols else q
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


class Dirac(Initializer):
    """Identity-preserving conv kernel init (ref:
    python/paddle/nn/initializer/dirac.py)."""

    def __init__(self, groups=1, name=None):
        self.groups = groups

    def _generate(self, shape, dtype):
        if len(shape) < 3:
            raise ValueError("Dirac needs a conv kernel shape")
        out = np.zeros(shape, dtype=np.float32)
        out_per_group = shape[0] // self.groups
        mid = tuple(s // 2 for s in shape[2:])
        for g in range(self.groups):
            for i in range(min(out_per_group, shape[1])):
                out[(g * out_per_group + i, i) + mid] = 1.0
        return jnp.asarray(out, dtype=dtype)


_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    """ref: python/paddle/nn/initializer/__init__.py set_global_initializer."""
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def _default_weight_init():
    return _global_weight_init if _global_weight_init is not None else XavierUniform()


def _default_bias_init():
    return _global_bias_init if _global_bias_init is not None else Constant(0.0)


class Bilinear(Initializer):
    """Bilinear-interpolation kernel for transposed-conv upsampling
    (ref: python/paddle/nn/initializer/Bilinear). Weight shape
    [C_out, C_in, k, k]; each spatial slice gets the classic bilinear
    tent filter."""

    def __init__(self, name=None):
        pass

    def _generate(self, shape, dtype):
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D weight")
        k = shape[3]
        if shape[2] != k:
            raise ValueError("Bilinear initializer needs square kernels")
        f = int(np.ceil(k / 2.0))
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        grid = np.arange(k)
        tent = (1 - np.abs(grid / f - c))
        filt = np.outer(tent, tent).astype(np.float32)
        w = np.zeros(shape, np.float32)
        w[:, :, :, :] = filt
        return jnp.asarray(w, dtype)
