"""Per-layer FLOP counting via forward hooks.

ref: python/paddle/hapi/dynamic_flops.py — flops(net, input_size)
registers a count hook per leaf layer, runs one dummy forward, and sums
multiply-accumulate counts (their convention: 1 MAC = 1 FLOP, bias adds
counted, activations counted at one op/element).
"""
from __future__ import annotations

import numpy as np

import paddle_tpu.nn as nn


def _numel(t):
    n = 1
    for s in t.shape:
        n *= int(s)
    return n


def _count_conv(m, x, y):
    kernel = getattr(m, "_kernel_size", None) or getattr(m, "kernel_size", None)
    groups = getattr(m, "_groups", None) or getattr(m, "groups", 1) or 1
    w = m.weight
    # weight [out, in/groups, *k]
    kernel_ops = _numel(w) // int(w.shape[0])
    bias_ops = 1 if getattr(m, "bias", None) is not None else 0
    out = y[0] if isinstance(y, (tuple, list)) else y
    m._flops = _numel(out) * (kernel_ops + bias_ops)


def _count_linear(m, x, y):
    out = y[0] if isinstance(y, (tuple, list)) else y
    in_f = int(m.weight.shape[0])
    bias_ops = 1 if getattr(m, "bias", None) is not None else 0
    m._flops = _numel(out) * (in_f + bias_ops)


def _count_norm(m, x, y):
    out = y[0] if isinstance(y, (tuple, list)) else y
    m._flops = 2 * _numel(out)


def _count_act(m, x, y):
    out = y[0] if isinstance(y, (tuple, list)) else y
    m._flops = _numel(out)


def _count_pool(m, x, y):
    out = y[0] if isinstance(y, (tuple, list)) else y
    m._flops = _numel(out)


_HANDLERS = [
    ((nn.Conv1D, nn.Conv2D, nn.Conv3D, nn.Conv1DTranspose, nn.Conv2DTranspose, nn.Conv3DTranspose), _count_conv),
    ((nn.Linear,), _count_linear),
    ((nn.BatchNorm, nn.BatchNorm1D, nn.BatchNorm2D, nn.BatchNorm3D, nn.LayerNorm,
      nn.GroupNorm, nn.InstanceNorm1D, nn.InstanceNorm2D, nn.InstanceNorm3D, nn.RMSNorm), _count_norm),
    ((nn.ReLU, nn.ReLU6, nn.GELU, nn.Sigmoid, nn.Tanh, nn.LeakyReLU, nn.Silu,
      nn.Hardswish, nn.Hardsigmoid, nn.PReLU, nn.ELU, nn.Softmax), _count_act),
    ((nn.AvgPool1D, nn.AvgPool2D, nn.AvgPool3D, nn.MaxPool1D, nn.MaxPool2D,
      nn.MaxPool3D, nn.AdaptiveAvgPool1D, nn.AdaptiveAvgPool2D, nn.AdaptiveAvgPool3D), _count_pool),
]


def dynamic_flops(net, input_size, custom_ops=None, print_detail=False):
    """Count one forward's FLOPs for ``net`` on zeros of ``input_size``.

    custom_ops: {LayerType: fn(layer, inputs, output)} setting
    layer._flops, merged over the built-in table (ref dynamic_flops
    custom_ops)."""
    import paddle_tpu as paddle

    handles = []
    rows = []

    def _hook_for(layer):
        if custom_ops:
            for t, fn in custom_ops.items():
                if isinstance(layer, t):
                    return fn
        for types, fn in _HANDLERS:
            if isinstance(layer, types):
                return fn
        return None

    for name, layer in net.named_sublayers():
        if len(list(layer.children())) > 0:
            continue
        fn = _hook_for(layer)
        if fn is None:
            continue

        def make(f, lname):
            def hook(l, inp, out):
                f(l, inp, out)
                rows.append((lname, type(l).__name__, int(getattr(l, "_flops", 0))))

            return hook

        handles.append(layer.register_forward_post_hook(make(fn, name)))

    was_training = net.training
    net.eval()
    try:
        x = paddle.zeros(list(input_size))
        with paddle.no_grad():
            net(x)
    finally:
        if was_training:
            net.train()
        for h in handles:
            h.remove()

    total = sum(r[2] for r in rows)
    if print_detail:
        width = max((len(r[0]) for r in rows), default=10) + 2
        print(f"{'layer':<{width}}{'type':<20}{'FLOPs':>14}")
        for r in rows:
            print(f"{r[0]:<{width}}{r[1]:<20}{r[2]:>14,}")
        print(f"Total FLOPs: {total:,}")
    return total
