"""High-level Model wrapper (ref: python/paddle/hapi/model.py:874).

Train/eval/predict loops over io.DataLoader with callbacks + metrics.
TPU notes: the train and eval steps are (optionally) compiled whole —
forward+loss+backward+update as one XLA program — via
``prepare(..., jit_compile=True)`` (default), the role the reference's
static-graph Model engine plays, without a second engine.
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence, Union

import numpy as np

from ..base.tensor import Tensor
from ..metric import Metric

__all__ = ["Model"]


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    """ref: hapi/model.py Model — same public surface
    (prepare/fit/evaluate/predict/save/load/parameters/summary)."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._jit = True
        self._train_step = None
        self._eval_step = None
        self.stop_training = False

    # ------------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, jit_compile: bool = True):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric must be a paddle.metric.Metric, got {type(m)}")
        self._jit = jit_compile
        self._train_step = None
        self._eval_step = None
        # AMP (ref: hapi/model.py _prepare_amp): amp_configs is 'O1'/'O2'
        # or {'level', 'dtype', 'custom_white_list', 'custom_black_list',
        # 'use_loss_scaling', 'init_loss_scaling'}
        self._amp_level = "O0"
        self._amp_kwargs = {}
        self._scaler = None
        if amp_configs is not None:
            if isinstance(amp_configs, str):
                amp_configs = {"level": amp_configs}
            cfg = dict(amp_configs)
            self._amp_level = cfg.pop("level", "O1")
            if self._amp_level not in ("O0", "O1", "O2"):
                raise ValueError(
                    f"amp level must be O0/O1/O2, got {self._amp_level}"
                )
            use_scaling = cfg.pop(
                "use_loss_scaling",
                cfg.get("dtype", "bfloat16") == "float16",
            )
            # scaler knobs go to GradScaler; the rest feed auto_cast
            scaler_kwargs = {
                k: cfg.pop(k)
                for k in (
                    "init_loss_scaling", "incr_ratio", "decr_ratio",
                    "incr_every_n_steps", "decr_every_n_nan_or_inf",
                    "use_dynamic_loss_scaling",
                )
                if k in cfg
            }
            allowed = {"dtype", "custom_white_list", "custom_black_list",
                       "use_promote"}
            unknown = set(cfg) - allowed
            if unknown:
                raise ValueError(f"unknown amp_configs keys: {sorted(unknown)}")
            self._amp_kwargs = cfg
            if self._amp_level != "O0":
                from ..amp import GradScaler, decorate

                if self._amp_level == "O2" and optimizer is not None:
                    self.network, self._optimizer = decorate(
                        models=self.network, optimizers=optimizer,
                        level="O2", dtype=cfg.get("dtype", "bfloat16"),
                    )
                if use_scaling:
                    self._scaler = GradScaler(**scaler_kwargs)

    # ------------------------------------------------------------------
    def _split_batch(self, batch):
        """(inputs..., label) convention: last element is the label when a
        loss is configured (ref: model.py _update_inputs handling)."""
        if isinstance(batch, (list, tuple)) and len(batch) >= 2:
            return list(batch[:-1]), batch[-1]
        return [batch], None

    def _build_train_step(self):
        network, loss_fn, optimizer = self.network, self._loss, self._optimizer
        amp_level, amp_kwargs, scaler = (
            self._amp_level, self._amp_kwargs, self._scaler
        )

        def step(*args):
            *xs, y = args
            if amp_level != "O0":
                from ..amp import auto_cast

                with auto_cast(level=amp_level, **amp_kwargs):
                    out = network(*xs)
                    loss = loss_fn(out, y)
            else:
                out = network(*xs)
                loss = loss_fn(out, y)
            if scaler is not None:
                scaler.scale(loss).backward()
                scaler.step(optimizer)
                scaler.update()
            else:
                loss.backward()
                optimizer.step()
            optimizer.clear_grad()
            return loss, out

        if self._jit:
            from .. import jit

            step = jit.to_static(
                step, layers=[network], optimizers=[optimizer],
                scalers=[scaler] if scaler is not None else (),
            )
        return step

    def _build_eval_step(self):
        network, loss_fn = self.network, self._loss
        amp_level, amp_kwargs = self._amp_level, self._amp_kwargs

        def step(*args):
            *xs, y = args
            if amp_level != "O0":
                from ..amp import auto_cast

                with auto_cast(level=amp_level, **amp_kwargs):
                    out = network(*xs)
            else:
                out = network(*xs)
            loss = loss_fn(out, y) if loss_fn is not None else None
            return loss, out

        if self._jit:
            from .. import jit

            step = jit.to_static(step, layers=[network])
        return step

    # ------------------------------------------------------------------
    def train_batch(self, inputs, labels=None):
        if self._train_step is None:
            self._train_step = self._build_train_step()
        self.network.train()
        args = _to_list(inputs) + _to_list(labels)
        loss, out = self._train_step(*args)
        metrics = self._update_metrics(out, _to_list(labels)[0] if labels else None)
        return [float(np.asarray(loss.numpy()))], metrics

    def eval_batch(self, inputs, labels=None):
        if self._eval_step is None:
            self._eval_step = self._build_eval_step()
        self.network.eval()
        args = _to_list(inputs) + _to_list(labels)
        loss, out = self._eval_step(*args)
        metrics = self._update_metrics(out, _to_list(labels)[0] if labels else None)
        losses = [float(np.asarray(loss.numpy()))] if loss is not None else []
        return losses, metrics

    def predict_batch(self, inputs):
        self.network.eval()
        from ..base.tape import no_grad

        with no_grad():
            out = self.network(*_to_list(inputs))
        return [np.asarray(o.numpy()) for o in _to_list(out)]

    def _update_metrics(self, out, label):
        vals = []
        first = out[0] if isinstance(out, (list, tuple)) else out
        for m in self._metrics:
            computed = m.compute(first, label)
            vals.append(m.update(*computed) if isinstance(computed, tuple) else m.update(computed))
        return vals

    # ------------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        from ..io import DataLoader, Dataset
        from .callbacks import CallbackList, config_callbacks

        if isinstance(train_data, Dataset):
            train_loader = DataLoader(
                train_data, batch_size=batch_size, shuffle=shuffle,
                drop_last=drop_last, num_workers=num_workers,
            )
        else:
            train_loader = train_data
        if isinstance(eval_data, Dataset):
            eval_loader = DataLoader(eval_data, batch_size=batch_size, num_workers=num_workers)
        else:
            eval_loader = eval_data

        try:
            steps = len(train_loader)
        except TypeError:
            steps = None
        cbks = config_callbacks(
            callbacks, model=self, epochs=epochs, steps=steps,
            log_freq=log_freq, save_freq=save_freq, save_dir=save_dir,
            verbose=verbose, metrics=self._metrics_name(),
        )

        self.stop_training = False
        cbks.on_train_begin()
        it = 0
        logs = {}
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step_i, batch in enumerate(train_loader):
                cbks.on_train_batch_begin(step_i)
                xs, y = self._split_batch(batch)
                losses, metrics = self.train_batch(xs, [y] if y is not None else None)
                logs = self._make_logs(losses, metrics)
                logs["step"] = step_i
                logs["batch_size"] = (
                    y.shape[0] if isinstance(y, Tensor) else batch_size
                )
                cbks.on_train_batch_end(step_i, logs)
                it += 1
                if num_iters is not None and it >= num_iters:
                    self.stop_training = True
                    break
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(
                    eval_loader, verbose=0, callbacks=None, _cbks=cbks
                )
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
            if self.stop_training:
                break
        cbks.on_train_end(logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None, _cbks=None):
        from ..io import DataLoader, Dataset
        from .callbacks import config_callbacks

        if isinstance(eval_data, Dataset):
            loader = DataLoader(eval_data, batch_size=batch_size, num_workers=num_workers)
        else:
            loader = eval_data
        cbks = _cbks or config_callbacks(
            callbacks, model=self, log_freq=log_freq, verbose=verbose,
            metrics=self._metrics_name(),
        )
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        logs = {}
        losses_sum, n = 0.0, 0
        for step_i, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step_i)
            xs, y = self._split_batch(batch)
            losses, metrics = self.eval_batch(xs, [y] if y is not None else None)
            if losses:
                losses_sum += losses[0]
                n += 1
            logs = self._make_logs(losses, metrics)
            cbks.on_eval_batch_end(step_i, logs)
            if num_iters is not None and step_i + 1 >= num_iters:
                break
        if n:
            logs["loss"] = [losses_sum / n]
        for m in self._metrics:
            logs[_name_str(m)] = m.accumulate()
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        from ..io import DataLoader, Dataset

        if isinstance(test_data, Dataset):
            loader = DataLoader(test_data, batch_size=batch_size, num_workers=num_workers)
        else:
            loader = test_data
        outputs = []
        for batch in loader:
            if self._loss is not None:
                # dataset yields (inputs..., label): drop the label, as the
                # reference's input-spec slicing does (model.py _run_one_epoch)
                xs, _ = self._split_batch(batch)
            else:
                xs = batch if isinstance(batch, (list, tuple)) else [batch]
            outputs.append(self.predict_batch(list(xs)))
        # transpose: list over batches of list over outputs → per-output
        per_out = list(zip(*outputs))
        if stack_outputs:
            return [np.concatenate(o, axis=0) for o in per_out]
        return [list(o) for o in per_out]

    # ------------------------------------------------------------------
    def _metrics_name(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, (list, tuple)) else [n])
        return names

    def _make_logs(self, losses, metric_vals):
        logs = {}
        if losses:
            logs["loss"] = losses
        for m, v in zip(self._metrics, metric_vals):
            logs[_name_str(m)] = v
        return logs

    # ------------------------------------------------------------------
    def save(self, path, training=True):
        """ref: model.py save — training=True saves .pdparams/.pdopt;
        False exports for inference via jit.save."""
        from .. import framework, jit

        if training:
            framework.io.save(self.network.state_dict(), path + ".pdparams")
            if self._optimizer is not None:
                framework.io.save(self._optimizer.state_dict(), path + ".pdopt")
        else:
            jit.save(self.network, path)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from .. import framework

        self.network.set_state_dict(framework.io.load(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if (
            not reset_optimizer
            and self._optimizer is not None
            and os.path.exists(opt_path)
        ):
            self._optimizer.set_state_dict(framework.io.load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary

        return summary(self.network, input_size, dtypes=dtype)


def _name_str(m: Metric) -> str:
    n = m.name()
    return n[0] if isinstance(n, (list, tuple)) else n
