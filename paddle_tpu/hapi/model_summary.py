"""paddle.summary (ref: python/paddle/hapi/model_summary.py:36).

Walks the layer tree with forward hooks recording output shapes and
parameter counts, printing the familiar table. Runs the forward on
zeros of the given input_size (host-side shapes only — a single tiny
eager forward, no compile).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

__all__ = ["summary"]


def summary(net, input_size=None, dtypes=None, input=None):
    """Returns {'total_params': N, 'trainable_params': M} and prints the
    per-layer table (ref: model_summary.py summary)."""
    from ..base.tensor import Tensor
    from .. import to_tensor

    if input is None:
        if input_size is None:
            raise ValueError("either input_size or input must be given")
        if isinstance(input_size, tuple) or (
            isinstance(input_size, list)
            and input_size
            and isinstance(input_size[0], int)
        ):  # a single shape, possibly given as a list
            sizes = [input_size]
        else:
            sizes = list(input_size)
        dts = dtypes if isinstance(dtypes, (list, tuple)) else [dtypes] * len(sizes)
        inputs = [
            to_tensor(np.zeros([d if d and d > 0 else 1 for d in s],
                               np.dtype(dt or "float32")))
            for s, dt in zip(sizes, dts)
        ]
    else:
        inputs = input if isinstance(input, (list, tuple)) else [input]

    records: List[Tuple[str, str, list, int]] = []
    hooks = []

    def make_hook(name, cls):
        def hook(layer, inp, out):
            out0 = out[0] if isinstance(out, (list, tuple)) else out
            shape = list(out0.shape) if isinstance(out0, Tensor) else []
            n_params = sum(
                int(np.prod(p.shape)) for p in layer.parameters(include_sublayers=False)
            )
            records.append((name, cls, shape, n_params))

        return hook

    for name, sub in net.named_sublayers(include_self=False):
        hooks.append(sub.register_forward_post_hook(make_hook(name, type(sub).__name__)))

    was_training = net.training
    net.eval()
    try:
        net(*inputs)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(
        int(np.prod(p.shape)) for p in net.parameters() if not p.stop_gradient
    )

    w_name, w_shape = 28, 24
    line = "-" * (w_name + w_shape + 34)
    print(line)
    print(f"{'Layer (type)':<{w_name}}{'Output Shape':<{w_shape}}{'Param #':>10}")
    print(line)
    for name, cls, shape, n in records:
        label = f"{name} ({cls})"
        print(f"{label:<{w_name}}{str(shape):<{w_shape}}{n:>10,}")
    print(line)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print(line)
    return {"total_params": total, "trainable_params": trainable}
