"""Training callbacks (ref: python/paddle/hapi/callbacks.py — Callback
:109, ProgBarLogger :261, ModelCheckpoint :507, LRScheduler :572,
EarlyStopping :643, ReduceLROnPlateau-style lives in optimizer.lr).
"""
from __future__ import annotations

import numbers
import time
from typing import List, Optional

import numpy as np

__all__ = [
    "Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
    "LRScheduler", "EarlyStopping", "config_callbacks",
 "ReduceLROnPlateau", "VisualDL", "WandbCallback",]


class Callback:
    """ref: callbacks.py:109 — hooks over train/eval/predict phases."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, cbk):
        self.callbacks.append(cbk)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if not name.startswith("on_"):
            raise AttributeError(name)

        def call(*args, **kwargs):
            for c in self.callbacks:
                getattr(c, name)(*args, **kwargs)

        return call


class ProgBarLogger(Callback):
    """Console logger (ref: callbacks.py:261). verbose: 0 silent,
    1 per-epoch, 2 per-log_freq-steps."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self.steps = self.params.get("steps")

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = time.time()

    def _fmt(self, logs):
        items = []
        for k, v in (logs or {}).items():
            if k in ("step", "batch_size"):
                continue
            if isinstance(v, (list, tuple)):
                v = v[0] if v else None
            if isinstance(v, numbers.Number):
                items.append(f"{k}: {v:.4f}")
        return " - ".join(items)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose >= 2 and self.log_freq and (step + 1) % self.log_freq == 0:
            print(f"Epoch {self._epoch + 1}/{self.epochs} step {step + 1}: {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose >= 1:
            dt = time.time() - self._t0
            print(f"Epoch {epoch + 1}/{self.epochs} - {dt:.1f}s - {self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose >= 1:
            print(f"Eval: {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    """Periodic save (ref: callbacks.py:507)."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(f"{self.save_dir}/final")


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (ref: callbacks.py:572)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        if by_step and by_epoch:
            raise ValueError("by_step and by_epoch are mutually exclusive")
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched

        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step and (s := self._sched()) is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch and (s := self._sched()) is not None:
            s.step()


class EarlyStopping(Callback):
    """Stop when a monitored metric stops improving (ref:
    callbacks.py:643 — same monitor/mode/patience/min_delta/baseline
    semantics, acting on eval logs)."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.baseline = baseline
        self.min_delta = abs(min_delta)
        self.wait_epoch = 0
        self.best_weights = None
        self.stopped_epoch = 0
        self.save_best_model = save_best_model
        if mode not in ("auto", "min", "max"):
            mode = "auto"
        if mode == "min" or (mode == "auto" and "acc" not in self.monitor):
            self.monitor_op = np.less
            self.min_delta *= -1
        else:
            self.monitor_op = np.greater

    def on_train_begin(self, logs=None):
        self.wait_epoch = 0
        if self.baseline is not None:
            self.best_value = self.baseline
        else:
            self.best_value = np.inf if self.monitor_op == np.less else -np.inf

    def on_eval_end(self, logs=None):
        self._eval_count = getattr(self, "_eval_count", 0) + 1
        if logs is None or self.monitor not in logs:
            return
        current = logs[self.monitor]
        if isinstance(current, (list, tuple)):
            current = current[0]
        if self.monitor_op(current - self.min_delta, self.best_value):
            self.best_value = current
            self.wait_epoch = 0
            if self.save_best_model:
                save_dir = self.params.get("save_dir")
                if save_dir:  # ref: callbacks.py — persist best_model
                    self.model.save(f"{save_dir}/best_model")
                # always keep an in-memory snapshot so the stop can
                # restore the best weights regardless of save_dir
                import numpy as np

                self.best_weights = {
                    k: np.asarray(v.numpy())
                    for k, v in self.model.network.state_dict().items()
                }
            return
        self.wait_epoch += 1
        if self.wait_epoch > self.patience:
            self.model.stop_training = True
            self.stopped_epoch = self._eval_count
            if self.best_weights is not None:
                self.model.network.set_state_dict(self.best_weights)
            if self.verbose:
                print(f"EarlyStopping: no improvement in {self.monitor}")


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    """Assemble the default callback stack (ref: callbacks.py:44)."""
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    cbk_list = CallbackList(cbks)
    cbk_list.set_model(model)
    cbk_list.set_params(
        {
            "batch_size": batch_size,
            "epochs": epochs,
            "steps": steps,
            "verbose": verbose,
            "metrics": metrics or ["loss"],
            "save_dir": save_dir,
        }
    )
    return cbk_list


class ReduceLROnPlateau(Callback):
    """ref: hapi/callbacks.py ReduceLROnPlateau — scale the optimizer lr
    when the monitored metric plateaus."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor, self.factor, self.patience = monitor, factor, patience
        self.verbose, self.min_delta, self.cooldown = verbose, min_delta, cooldown
        self.min_lr = min_lr
        if mode == "auto":
            mode = "min" if "loss" in monitor else "max"
        self.mode = mode
        self._best = None
        self._wait = 0
        self._cool = 0

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(cur[0] if isinstance(cur, (list, tuple)) else cur)
        better = (
            self._best is None
            or (self.mode == "min" and cur < self._best - self.min_delta)
            or (self.mode == "max" and cur > self._best + self.min_delta)
        )
        if better:
            self._best, self._wait = cur, 0
            return
        if self._cool > 0:
            self._cool -= 1
            return
        self._wait += 1
        if self._wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is not None:
                new_lr = max(float(opt.get_lr()) * self.factor, self.min_lr)
                opt.set_lr(new_lr)
                if self.verbose:
                    print(f"ReduceLROnPlateau: lr -> {new_lr:.3e}")
            self._wait = 0
            self._cool = self.cooldown


class VisualDL(Callback):
    """ref: hapi/callbacks.py VisualDL. The visualdl package is not
    bundled; scalars append to <log_dir>/scalars.jsonl (one JSON per
    step) which visualdl or any plotting tool can ingest."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir
        self._step = {"train": 0, "eval": 0}

    def _write(self, mode, logs):
        import json as _json
        import os as _os

        _os.makedirs(self.log_dir, exist_ok=True)
        rec = {"mode": mode, "step": self._step[mode]}
        for k, v in (logs or {}).items():
            try:
                rec[k] = float(v[0] if isinstance(v, (list, tuple)) else v)
            except (TypeError, ValueError):
                continue
        with open(_os.path.join(self.log_dir, "scalars.jsonl"), "a") as f:
            f.write(_json.dumps(rec) + "\n")
        self._step[mode] += 1

    def on_train_batch_end(self, step, logs=None):
        self._write("train", logs)

    def on_eval_end(self, logs=None):
        self._write("eval", logs)


class WandbCallback(Callback):
    """ref: hapi/callbacks.py WandbCallback — requires the wandb
    package (not bundled); constructing without it raises with
    guidance."""

    def __init__(self, project=None, run_name=None, **kwargs):
        super().__init__()
        try:
            import wandb  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "WandbCallback requires the 'wandb' package; it is not "
                "bundled in this environment (no network egress)."
            ) from e
        import wandb

        self._run = wandb.init(project=project, name=run_name, **kwargs)

    def on_train_batch_end(self, step, logs=None):
        self._run.log(dict(logs or {}))
