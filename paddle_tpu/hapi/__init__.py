"""paddle_tpu.hapi — high-level Model API (fit/evaluate/predict).

ref: python/paddle/hapi/ — model.py (Model :874), callbacks.py,
model_summary.py. The reference keeps dual dygraph/static engines
inside Model; here there is one engine: the eager tape, optionally
compiled per train/eval step via paddle_tpu.jit.to_static (the
``jit_compile`` knob in prepare()).
"""
from .model import Model  # noqa: F401
from .model_summary import summary  # noqa: F401
from . import callbacks  # noqa: F401

__all__ = ["Model", "summary", "callbacks"]
