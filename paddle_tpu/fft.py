"""paddle_tpu.fft — discrete Fourier transforms.

ref: python/paddle/fft.py — same API surface (fft/ifft/rfft/irfft/
hfft/ihfft, 2-D and N-D variants, fftfreq/rfftfreq/fftshift/ifftshift)
with paddle's norm semantics ('backward' | 'ortho' | 'forward').
All lowered to jnp.fft (XLA implements FFT natively on TPU); grads flow
through the tape like any other op.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp

from .base.tape import apply

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _norm(norm: Optional[str]) -> str:
    if norm is None:
        return "backward"
    if norm not in ("backward", "ortho", "forward"):
        raise ValueError(f"norm must be backward/ortho/forward, got {norm!r}")
    return norm


def _wrap1(jnp_fn, x, n, axis, norm, op_name):
    def f(a):
        return jnp_fn(a, n=n, axis=axis, norm=_norm(norm))

    return apply(f, x, op_name=op_name)


def _wrapn(jnp_fn, x, s, axes, norm, op_name):
    def f(a):
        return jnp_fn(a, s=s, axes=axes, norm=_norm(norm))

    return apply(f, x, op_name=op_name)


def fft(x, n=None, axis=-1, norm=None, name=None):
    return _wrap1(jnp.fft.fft, x, n, axis, norm, "fft")


def ifft(x, n=None, axis=-1, norm=None, name=None):
    return _wrap1(jnp.fft.ifft, x, n, axis, norm, "ifft")


def rfft(x, n=None, axis=-1, norm=None, name=None):
    return _wrap1(jnp.fft.rfft, x, n, axis, norm, "rfft")


def irfft(x, n=None, axis=-1, norm=None, name=None):
    return _wrap1(jnp.fft.irfft, x, n, axis, norm, "irfft")


def hfft(x, n=None, axis=-1, norm=None, name=None):
    return _wrap1(jnp.fft.hfft, x, n, axis, norm, "hfft")


def ihfft(x, n=None, axis=-1, norm=None, name=None):
    return _wrap1(jnp.fft.ihfft, x, n, axis, norm, "ihfft")


def fft2(x, s=None, axes=(-2, -1), norm=None, name=None):
    return _wrapn(jnp.fft.fft2, x, s, axes, norm, "fft2")


def ifft2(x, s=None, axes=(-2, -1), norm=None, name=None):
    return _wrapn(jnp.fft.ifft2, x, s, axes, norm, "ifft2")


def rfft2(x, s=None, axes=(-2, -1), norm=None, name=None):
    return _wrapn(jnp.fft.rfft2, x, s, axes, norm, "rfft2")


def irfft2(x, s=None, axes=(-2, -1), norm=None, name=None):
    return _wrapn(jnp.fft.irfft2, x, s, axes, norm, "irfft2")


def hfft2(x, s=None, axes=(-2, -1), norm=None, name=None):
    def f(a):
        return jnp.fft.hfft2(a, s=s, axes=axes, norm=_norm(norm))

    return apply(f, x, op_name="hfft2")


def ihfft2(x, s=None, axes=(-2, -1), norm=None, name=None):
    def f(a):
        return jnp.fft.ihfft2(a, s=s, axes=axes, norm=_norm(norm))

    return apply(f, x, op_name="ihfft2")


def fftn(x, s=None, axes=None, norm=None, name=None):
    return _wrapn(jnp.fft.fftn, x, s, axes, norm, "fftn")


def ifftn(x, s=None, axes=None, norm=None, name=None):
    return _wrapn(jnp.fft.ifftn, x, s, axes, norm, "ifftn")


def rfftn(x, s=None, axes=None, norm=None, name=None):
    return _wrapn(jnp.fft.rfftn, x, s, axes, norm, "rfftn")


def irfftn(x, s=None, axes=None, norm=None, name=None):
    return _wrapn(jnp.fft.irfftn, x, s, axes, norm, "irfftn")


def hfftn(x, s=None, axes=None, norm=None, name=None):
    def f(a):
        return jnp.fft.hfftn(a, s=s, axes=axes, norm=_norm(norm))

    return apply(f, x, op_name="hfftn")


def ihfftn(x, s=None, axes=None, norm=None, name=None):
    def f(a):
        return jnp.fft.ihfftn(a, s=s, axes=axes, norm=_norm(norm))

    return apply(f, x, op_name="ihfftn")


def fftfreq(n, d=1.0, dtype=None, name=None):
    def f():
        out = jnp.fft.fftfreq(n, d)
        return out.astype(dtype) if dtype is not None else out

    return apply(f, op_name="fftfreq")


def rfftfreq(n, d=1.0, dtype=None, name=None):
    def f():
        out = jnp.fft.rfftfreq(n, d)
        return out.astype(dtype) if dtype is not None else out

    return apply(f, op_name="rfftfreq")


def fftshift(x, axes=None, name=None):
    def f(a):
        return jnp.fft.fftshift(a, axes=axes)

    return apply(f, x, op_name="fftshift")


def ifftshift(x, axes=None, name=None):
    def f(a):
        return jnp.fft.ifftshift(a, axes=axes)

    return apply(f, x, op_name="ifftshift")
