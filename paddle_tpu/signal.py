"""paddle_tpu.signal — STFT / ISTFT (ref: python/paddle/signal.py).

Same frame/window/center semantics as the reference; lowered to
jnp framing + fft (XLA-native FFT on TPU), differentiable through the
tape like every other op.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from .base.tape import apply
from .base.tensor import Tensor

__all__ = ["stft", "istft", "frame", "overlap_add"]


def frame(x, frame_length: int, hop_length: int, axis: int = -1, name=None):
    """Slice into overlapping frames (ref: signal.py frame — same layout
    contract: axis=-1 → [..., frame_length, num_frames]; axis=0 →
    [num_frames, frame_length, ...])."""
    if axis not in (-1, 0):
        raise ValueError("frame only supports axis=-1 or axis=0 (reference API)")

    def f(a):
        n = a.shape[0] if axis == 0 else a.shape[-1]
        num = 1 + (n - frame_length) // hop_length
        starts = jnp.arange(num) * hop_length
        idx = starts[:, None] + jnp.arange(frame_length)[None, :]  # [num, fl]
        if axis == 0:
            return a[idx]  # [num, fl, ...]
        framed = a[..., idx]  # [..., num, fl]
        return jnp.swapaxes(framed, -1, -2)  # [..., fl, num]

    return apply(f, x, op_name="frame")


def overlap_add(x, hop_length: int, axis: int = -1, name=None):
    """Inverse of frame (ref: signal.py overlap_add — axis=-1 input
    [..., frame_length, num_frames] → [..., seq]; axis=0 input
    [num_frames, frame_length, ...] → [seq, ...])."""
    if axis not in (-1, 0):
        raise ValueError("overlap_add only supports axis=-1 or axis=0")

    def f(a):
        if axis == 0:
            num, fl = a.shape[0], a.shape[1]
            rest = a.shape[2:]
            out_len = (num - 1) * hop_length + fl
            starts = jnp.arange(num) * hop_length
            idx = (starts[:, None] + jnp.arange(fl)[None, :]).reshape(-1)
            flat = a.reshape((num * fl, -1))
            out = jnp.zeros((out_len, flat.shape[1]), a.dtype)
            out = out.at[idx].add(flat)
            return out.reshape((out_len,) + rest)
        fl, num = a.shape[-2], a.shape[-1]
        swapped = jnp.swapaxes(a, -1, -2)  # [..., num, fl]
        out_len = (num - 1) * hop_length + fl
        starts = jnp.arange(num) * hop_length
        idx = (starts[:, None] + jnp.arange(fl)[None, :]).reshape(-1)
        flat_batch = swapped.reshape((-1, num * fl))
        out = jnp.zeros((flat_batch.shape[0], out_len), a.dtype)
        out = out.at[:, idx].add(flat_batch)
        return out.reshape(a.shape[:-2] + (out_len,))

    return apply(f, x, op_name="overlap_add")


def _resolve_window(window, n_fft, dtype=jnp.float32):
    if window is None:
        return jnp.ones((n_fft,), dtype)
    if isinstance(window, Tensor):
        return window._data
    return jnp.asarray(window, dtype)


def stft(x, n_fft: int, hop_length: Optional[int] = None,
         win_length: Optional[int] = None, window=None, center: bool = True,
         pad_mode: str = "reflect", normalized: bool = False,
         onesided: bool = True, name=None):
    """Short-time Fourier transform (ref: signal.py stft — same
    defaults: hop = n_fft//4, win = n_fft, centered reflect pad).
    x: [N] or [B, N] → [B?, freq, num_frames] complex."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    win = _resolve_window(window, win_length)
    if win_length < n_fft:  # center-pad the window to n_fft
        lpad = (n_fft - win_length) // 2
        win = jnp.pad(win, (lpad, n_fft - win_length - lpad))

    def f(a, w):
        squeeze = a.ndim == 1
        if squeeze:
            a = a[None, :]
        if center:
            a = jnp.pad(a, ((0, 0), (n_fft // 2, n_fft // 2)), mode=pad_mode)
        n = a.shape[-1]
        num = 1 + (n - n_fft) // hop_length
        starts = jnp.arange(num) * hop_length
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]
        frames = a[:, idx] * w[None, None, :]  # [B, num, n_fft]
        spec = (
            jnp.fft.rfft(frames, axis=-1)
            if onesided
            else jnp.fft.fft(frames, axis=-1)
        )
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        out = jnp.swapaxes(spec, -1, -2)  # [B, freq, num]
        return out[0] if squeeze else out

    return apply(f, x, win, op_name="stft")


def istft(x, n_fft: int, hop_length: Optional[int] = None,
          win_length: Optional[int] = None, window=None, center: bool = True,
          normalized: bool = False, onesided: bool = True,
          length: Optional[int] = None, return_complex: bool = False,
          name=None):
    """Inverse STFT with window-envelope normalization (ref: signal.py
    istft). x: [B?, freq, num_frames] complex."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    win = _resolve_window(window, win_length)
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        win = jnp.pad(win, (lpad, n_fft - win_length - lpad))

    def f(spec, w):
        squeeze = spec.ndim == 2
        if squeeze:
            spec = spec[None]
        frames_f = jnp.swapaxes(spec, -1, -2)  # [B, num, freq]
        if normalized:
            frames_f = frames_f * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        if onesided:
            frames = jnp.fft.irfft(frames_f, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(frames_f, axis=-1)
            if not return_complex:
                frames = frames.real
        frames = frames * w[None, None, :]
        num = frames.shape[1]
        out_len = (num - 1) * hop_length + n_fft
        starts = jnp.arange(num) * hop_length
        idx = (starts[:, None] + jnp.arange(n_fft)[None, :]).reshape(-1)
        out = jnp.zeros((frames.shape[0], out_len), frames.dtype)
        out = out.at[:, idx].add(frames.reshape(frames.shape[0], -1))
        # window envelope for COLA normalization
        env = jnp.zeros((out_len,), jnp.float32)
        env = env.at[idx].add(jnp.tile(w * w, (num,)))
        out = out / jnp.where(env > 1e-11, env, 1.0)
        if center:
            out = out[:, n_fft // 2 : out_len - n_fft // 2]
        if length is not None:
            out = out[:, :length]
        return out[0] if squeeze else out

    return apply(f, x, win, op_name="istft")
