"""paddle_tpu.models — model zoo (BASELINE configs).

llama: decoder LM family (config #3); gpt: decoder LM with learned
positions (config #4); bert: bidirectional encoder + MLM head
(config #2); vision models live in paddle_tpu.vision (config #1).
"""
from .llama import (  # noqa: F401
    LlamaConfig,
    LlamaDecoderLayer,
    LlamaForCausalLM,
    LlamaModel,
)
from .gpt import GPTConfig, GPTForCausalLM, GPTModel  # noqa: F401
from .bert import (  # noqa: F401
    BertConfig,
    BertForMaskedLM,
    BertForSequenceClassification,
    BertModel,
)
from .unet import UNet2DConditionModel, UNetConfig  # noqa: F401
from .generation import generate  # noqa: F401
