"""paddle_tpu.models — model zoo (BASELINE configs).

llama: decoder LM family (configs #3/#4); vision models live in
paddle_tpu.vision (config #1).
"""
from .llama import (  # noqa: F401
    LlamaConfig,
    LlamaDecoderLayer,
    LlamaForCausalLM,
    LlamaModel,
)
