"""BERT family — bidirectional encoder with MLM head (BASELINE.md
config #2: BERT-base MLM fine-tune under DataParallel).

ref: transformer encoder layers (python/paddle/nn/layer/
transformer.py:110 TransformerEncoderLayer) — assembled here the
TPU-native way: non-causal F.scaled_dot_product_attention (Pallas flash
kernel on TPU), tp_axis metadata on every projection, static shapes.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from .. import nn
from ..base.tape import apply
from ..nn import functional as F
from ..tensor import manipulation as M

__all__ = ["BertConfig", "BertModel", "BertForMaskedLM",
           "BertForSequenceClassification"]


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dropout: float = 0.0

    @classmethod
    def tiny(cls):
        return cls(
            vocab_size=512, hidden_size=64, intermediate_size=256,
            num_hidden_layers=2, num_attention_heads=4,
            max_position_embeddings=128,
        )

    @classmethod
    def base(cls):
        return cls()


class BertEmbeddings(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(config.vocab_size, config.hidden_size)
        self.word_embeddings.weight.tp_axis = 0
        self.position_embeddings = nn.Embedding(
            config.max_position_embeddings, config.hidden_size
        )
        self.token_type_embeddings = nn.Embedding(
            config.type_vocab_size, config.hidden_size
        )
        self.layer_norm = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, input_ids, token_type_ids=None):
        b, s = input_ids.shape
        pos = apply(lambda: jnp.arange(s, dtype=jnp.int32)[None, :], op_name="arange")
        x = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is not None:
            x = x + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(x))


class BertLayer(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.head_dim = h // config.num_attention_heads
        self.qkv = nn.Linear(h, 3 * h)
        self.qkv.weight.tp_axis = 1
        self.attn_out = nn.Linear(h, h)
        self.attn_out.weight.tp_axis = 0
        self.attn_norm = nn.LayerNorm(h, epsilon=config.layer_norm_eps)
        self.fc1 = nn.Linear(h, config.intermediate_size)
        self.fc1.weight.tp_axis = 1
        self.fc2 = nn.Linear(config.intermediate_size, h)
        self.fc2.weight.tp_axis = 0
        self.ffn_norm = nn.LayerNorm(h, epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, x, attn_mask=None):
        b, s, h = x.shape
        qkv = M.reshape(self.qkv(x), [b, s, 3, self.num_heads, self.head_dim])
        out = F.scaled_dot_product_attention(
            qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2],
            attn_mask=attn_mask, is_causal=False, training=self.training,
        )
        x = self.attn_norm(x + self.dropout(self.attn_out(M.reshape(out, [b, s, h]))))
        ffn = self.fc2(F.gelu(self.fc1(x)))
        return self.ffn_norm(x + self.dropout(ffn))


class BertModel(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = nn.LayerList(
            [BertLayer(config) for _ in range(config.num_hidden_layers)]
        )
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids)
        mask = None
        if attention_mask is not None:
            # [B, S] 1/0 → additive [B, 1, 1, S] (broadcasts over heads/q)
            def to_additive(m):
                return (1.0 - m.astype(jnp.float32))[:, None, None, :] * -1e9

            mask = apply(to_additive, attention_mask, op_name="attn_mask")
        for layer in self.encoder:
            x = layer(x, mask)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForMaskedLM(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.bert = BertModel(config)
        self.transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.transform_norm = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.decoder = nn.Linear(config.hidden_size, config.vocab_size)
        self.decoder.weight.tp_axis = 1

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        x, _ = self.bert(input_ids, token_type_ids, attention_mask)
        x = self.transform_norm(F.gelu(self.transform(x)))
        return self.decoder(x)

    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())


class BertForSequenceClassification(nn.Layer):
    def __init__(self, config: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(config)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.classifier(pooled)
