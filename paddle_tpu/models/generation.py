"""Autoregressive generation with KV caches.

ref: generation lives downstream of the reference (PaddleNLP
generation_utils: greedy/sampling loops over cached decoders); the
in-repo surface it depends on is the cached attention path this module
drives.

TPU-native design: KV caches are **buffers of a cache-state Layer**, so
``jit.to_static`` threads and DONATES them with the rest of the model
state — each decode step updates the caches in place on device (no
per-token cache copy) and the compiled prefill/decode programs are
cached on the model and reused across ``generate`` calls (static
shapes, no per-length retrace). Sampling keys draw from the framework
RNG (threaded through the compiled step).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..base import random as _random
from ..base.tape import apply
from ..base.tensor import Tensor

__all__ = ["alloc_kv_caches", "update_kv_cache", "generate"]


def alloc_kv_caches(num_layers, batch, max_len, num_kv_heads, head_dim, dtype):
    caches = []
    for _ in range(num_layers):
        k = Tensor(jnp.zeros((batch, max_len, num_kv_heads, head_dim), dtype),
                   _internal=True)
        v = Tensor(jnp.zeros((batch, max_len, num_kv_heads, head_dim), dtype),
                   _internal=True)
        caches.append((k, v))
    return caches


def update_kv_cache(kk, vv, kc, vc, cl, s: int):
    """Shared cache-write + causal-mask protocol (raw jnp arrays; used
    by both Llama and GPT attention): writes the new [B, s, H, D] block
    at position ``cl`` and returns (k_cache, v_cache, mask) where mask
    is the [1, 1, s, max_len] bool mask letting query i see keys
    <= cl + i."""
    max_len = kc.shape[1]
    kc = jax.lax.dynamic_update_slice(kc, kk.astype(kc.dtype), (0, cl, 0, 0))
    vc = jax.lax.dynamic_update_slice(vc, vv.astype(vc.dtype), (0, cl, 0, 0))
    k_idx = jnp.arange(max_len)[None, :]
    q_idx = cl + jnp.arange(s)[:, None]
    return kc, vc, (k_idx <= q_idx)[None, None]


class _KVCacheState:
    """Holds cache tensors as non-persistable buffers of a Layer so the
    compiled step threads + donates them (see module docstring).
    ``block_size`` switches to the paged (block-table) cache layout
    (ops/paged_attention.py)."""

    def __init__(self, model, batch, max_len, block_size=None):
        from ..nn.layer.layers import Layer

        class Holder(Layer):
            pass

        self.holder = Holder()
        # decode-loop state for the CHUNKED path: the current token and
        # the eos-finished mask live on device with the caches, so a
        # lax.scan over decode steps carries them — one dispatch per
        # chunk instead of per token (the tunnel/host RTT otherwise
        # bounds decode throughput; see BASELINE.md decode rows)
        self.holder.register_buffer(
            "tok", Tensor(jnp.zeros((batch,), jnp.int32), _internal=True),
            persistable=False,
        )
        self.holder.register_buffer(
            "finished", Tensor(jnp.zeros((batch,), bool), _internal=True),
            persistable=False,
        )
        self.paged = block_size is not None
        kwargs = {"block_size": block_size} if self.paged else {}
        caches = model.init_cache(batch, max_len, **kwargs)
        self.n = len(caches)
        self.shapes_dtypes = []
        if self.paged:
            from ..ops.paged_attention import PagedLayerCache  # noqa: F401

            self._tables = caches[0].block_tables
            self._contiguous = bool(getattr(caches[0], "contiguous", False))
            for i, c in enumerate(caches):
                self.holder.register_buffer(f"k{i}", c.k_pool, persistable=False)
                self.holder.register_buffer(f"v{i}", c.v_pool, persistable=False)
                self.shapes_dtypes.append(
                    (tuple(c.k_pool.shape), c.k_pool._data.dtype)
                )
        else:
            for i, (k, v) in enumerate(caches):
                self.holder.register_buffer(f"k{i}", k, persistable=False)
                self.holder.register_buffer(f"v{i}", v, persistable=False)
                self.shapes_dtypes.append((tuple(k.shape), k._data.dtype))

    def caches(self):
        if self.paged:
            from ..ops.paged_attention import PagedLayerCache

            return [
                PagedLayerCache(
                    self.holder._buffers[f"k{i}"],
                    self.holder._buffers[f"v{i}"],
                    self._tables,
                    self._contiguous,
                )
                for i in range(self.n)
            ]
        return [
            (self.holder._buffers[f"k{i}"], self.holder._buffers[f"v{i}"])
            for i in range(self.n)
        ]

    def set(self, new_caches):
        for i, c in enumerate(new_caches):
            k, v = (c.k_pool, c.v_pool) if self.paged else (c[0], c[1])
            self.holder._buffers[f"k{i}"]._data = k._data
            self.holder._buffers[f"v{i}"]._data = v._data

    def reset(self):
        for i, (shape, dt) in enumerate(self.shapes_dtypes):
            self.holder._buffers[f"k{i}"]._data = jnp.zeros(shape, dt)
            self.holder._buffers[f"v{i}"]._data = jnp.zeros(shape, dt)
        tok = self.holder._buffers["tok"]
        tok._data = jnp.zeros(tok._data.shape, jnp.int32)
        fin = self.holder._buffers["finished"]
        fin._data = jnp.zeros(fin._data.shape, bool)


def _sample(logits, temperature: float, top_k: int):
    """logits [B, V] → token ids [B]; greedy when temperature == 0."""

    def f(lg):
        if temperature == 0:
            return jnp.argmax(lg, axis=-1).astype(jnp.int32)
        lg = lg.astype(jnp.float32) / temperature
        if top_k > 0:
            kth = jnp.sort(lg, axis=-1)[:, -top_k][:, None]
            lg = jnp.where(lg < kth, -jnp.inf, lg)
        key = _random.next_key()
        return jax.random.categorical(key, lg, axis=-1).astype(jnp.int32)

    return apply(f, logits, op_name="sample_token")


def _get_compiled(model, b, s, max_len, temperature, top_k, use_jit,
                  block_size=None, chunked=False, eos_token_id=None):
    """Build (or fetch) the prefill/decode programs + cache state for
    this (batch, prompt-len, max-len, sampling) signature.

    ``chunked=True`` builds a decode step that reads/writes the token
    and eos-finished mask as HOLDER BUFFERS (device state) instead of
    passing the token host-side — so ``decode.multi_step`` can scan K
    steps in one dispatch. The eos logic is baked into the step, hence
    eos_token_id joins the cache key."""
    from .. import jit

    key = (b, s, max_len, temperature, top_k, use_jit, block_size,
           chunked, eos_token_id if chunked else None)
    store = getattr(model, "_generation_programs", None)
    if store is None:
        store = model._generation_programs = {}
    if key in store:
        state, prefill, decode = store.pop(key)  # re-insert as newest
        store[key] = (state, prefill, decode)
        state.reset()
        return state, prefill, decode
    # bound the program cache: each entry pins full KV buffers + two
    # compiled programs; varying prompt lengths would otherwise grow
    # device memory without limit (LRU, insertion-ordered dict)
    while len(store) >= 4:
        store.pop(next(iter(store)))

    state = _KVCacheState(model, b, max_len, block_size=block_size)

    def prefill(ids, cur_len):
        logits, new = model.forward_with_cache(ids, state.caches(), cur_len)
        state.set(new)
        tok = _sample(logits[:, -1], temperature, top_k)
        state.holder._buffers["tok"]._data = tok._data
        return tok

    if chunked:
        def decode(cur_len):
            prev = state.holder._buffers["tok"]
            fin = state.holder._buffers["finished"]
            logits, new = model.forward_with_cache(
                prev.reshape([b, 1]), state.caches(), cur_len
            )
            state.set(new)
            tok = _sample(logits[:, -1], temperature, top_k)
            if eos_token_id is not None:
                fin2, tok = apply(
                    lambda f, p, t: (
                        f | (p == eos_token_id),
                        jnp.where(f | (p == eos_token_id), eos_token_id, t),
                    ),
                    fin, prev, tok, op_name="eos_freeze",
                )
                state.holder._buffers["finished"]._data = fin2._data
            state.holder._buffers["tok"]._data = tok._data
            return tok
    else:
        def decode(tok, cur_len):
            logits, new = model.forward_with_cache(
                tok.reshape([b, 1]), state.caches(), cur_len
            )
            state.set(new)
            return _sample(logits[:, -1], temperature, top_k)

    if use_jit:
        prefill = jit.to_static(prefill, layers=[model, state.holder])
        decode = jit.to_static(decode, layers=[model, state.holder])
    store[key] = (state, prefill, decode)
    return state, prefill, decode


def _decode_chunked(state, decode, first_tok, s, max_new_tokens,
                    chunk: int, eos_token_id):
    """Drive the chunked decode: one regular call (required before
    multi_step, and it compiles the step), then multi_step scans of up
    to ``chunk`` steps per dispatch. Returns the per-position token
    Tensors ([B] each), eos rows frozen in-program."""
    from .. import to_tensor

    out = [first_tok]
    done = 1  # tokens emitted so far (prefill's sample)
    # regular call: position s + done - 1 writes cache slot for token
    out.append(decode(to_tensor(np.asarray(s + done - 1, np.int32))))
    done += 1
    while done < max_new_tokens:
        k = min(chunk, max_new_tokens - done)
        curs = np.arange(s + done - 1, s + done - 1 + k, dtype=np.int32)
        if k == 1:
            out.append(decode(to_tensor(curs[0])))
        else:
            toks = decode.multi_step(to_tensor(curs))  # [k, B]
            from ..tensor.manipulation import unstack

            out.extend(unstack(toks, axis=0))
        done += k
        if eos_token_id is not None and bool(
            np.asarray(state.holder._buffers["finished"]._data).all()
        ):
            # every row finished: emit frozen eos for the remainder
            # without further dispatches
            while done < max_new_tokens:
                out.append(out[-1])
                done += 1
            break
    return out


def generate(model, input_ids, max_new_tokens: int = 32,
             temperature: float = 0.0, top_k: int = 0,
             eos_token_id: Optional[int] = None, use_jit: bool = True,
             block_size: Optional[int] = None,
             decode_chunk: Optional[int] = None):
    """Generate ``max_new_tokens`` continuations of ``input_ids``
    ([B, S] int Tensor) with KV caching. Returns [B, S + new] ids.

    ``model`` must provide ``init_cache(batch, max_len)`` and
    ``forward_with_cache(ids, caches, cur_len) -> (logits, caches)``
    (models.LlamaForCausalLM / GPTForCausalLM do). ``block_size``
    switches to the paged (block-table) KV cache — same tokens, pool
    memory layout (ref: block_multihead_attention); the model's
    ``init_cache`` must accept ``block_size`` and its attention must
    handle PagedLayerCache (LlamaForCausalLM and GPTForCausalLM do).

    ``decode_chunk=K`` scans K decode steps inside ONE device dispatch
    (lax.scan over the compiled step; token + eos state carried on
    device) — the serving idiom when host↔device latency dominates
    per-token dispatch. Token-identical to the per-token loop; eos rows
    freeze in-program, and generation stops at the first chunk whose
    rows are all finished."""
    from .. import to_tensor
    from ..base.tape import no_grad

    b, s = input_ids.shape
    if max_new_tokens <= 0:
        return input_ids
    max_len = s + max_new_tokens
    limit = getattr(getattr(model, "config", None), "max_position_embeddings", None)
    if limit is not None and max_len > limit:
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) = {max_len} "
            f"exceeds the model's max_position_embeddings ({limit})"
        )

    was_training = model.training
    model.eval()
    chunked = bool(decode_chunk) and use_jit and max_new_tokens > 2
    try:
        with no_grad():
            state, prefill, decode = _get_compiled(
                model, b, s, max_len, temperature, top_k, use_jit,
                block_size=block_size, chunked=chunked,
                eos_token_id=eos_token_id,
            )
            zero = to_tensor(np.asarray(0, np.int32))
            tok = prefill(input_ids, zero)
            if chunked:
                out = _decode_chunked(
                    state, decode, tok, s, max_new_tokens,
                    int(decode_chunk), eos_token_id,
                )
                from ..tensor.manipulation import concat, stack

                new_tokens = stack(out, axis=1)  # [B, new]
                return concat(
                    [input_ids, new_tokens.astype(input_ids.dtype)], axis=1
                )
            out = [tok]
            finished = apply(
                lambda t: jnp.zeros(t.shape, bool), tok, op_name="zeros_like"
            )
            for step_i in range(1, max_new_tokens):
                cur = to_tensor(np.asarray(s + step_i - 1, np.int32))
                tok = decode(tok, cur)
                if eos_token_id is not None:
                    # once a row emits eos, freeze it to eos thereafter
                    finished = apply(
                        lambda f, p: f | (p == eos_token_id),
                        finished, out[-1], op_name="eos_track",
                    )
                    tok = apply(
                        lambda t, f: jnp.where(f, eos_token_id, t),
                        tok, finished, op_name="eos_mask",
                    )
                out.append(tok)
            from ..tensor.manipulation import concat, stack

            new_tokens = stack(out, axis=1)  # [B, new]
            return concat([input_ids, new_tokens.astype(input_ids.dtype)], axis=1)
    finally:
        if was_training:
            model.train()
