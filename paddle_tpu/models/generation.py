"""Autoregressive generation with KV caches.

ref: generation lives downstream of the reference (PaddleNLP
generation_utils: greedy/sampling loops over cached decoders); the
in-repo surface it depends on is the cached attention path this module
drives.

TPU-native design: KV caches are **buffers of a cache-state Layer**, so
``jit.to_static`` threads and DONATES them with the rest of the model
state — each decode step updates the caches in place on device (no
per-token cache copy) and the compiled prefill/decode programs are
cached on the model and reused across ``generate`` calls (static
shapes, no per-length retrace). Sampling keys draw from the framework
RNG (threaded through the compiled step).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..base import random as _random
from ..base.tape import apply
from ..base.tensor import Tensor

__all__ = ["alloc_kv_caches", "update_kv_cache", "generate"]


def alloc_kv_caches(num_layers, batch, max_len, num_kv_heads, head_dim, dtype):
    caches = []
    for _ in range(num_layers):
        k = Tensor(jnp.zeros((batch, max_len, num_kv_heads, head_dim), dtype),
                   _internal=True)
        v = Tensor(jnp.zeros((batch, max_len, num_kv_heads, head_dim), dtype),
                   _internal=True)
        caches.append((k, v))
    return caches


def update_kv_cache(kk, vv, kc, vc, cl, s: int):
    """Shared cache-write + causal-mask protocol (raw jnp arrays; used
    by both Llama and GPT attention): writes the new [B, s, H, D] block
    at position ``cl`` and returns (k_cache, v_cache, mask) where mask
    is the [1, 1, s, max_len] bool mask letting query i see keys
    <= cl + i."""
    max_len = kc.shape[1]
    kc = jax.lax.dynamic_update_slice(kc, kk.astype(kc.dtype), (0, cl, 0, 0))
    vc = jax.lax.dynamic_update_slice(vc, vv.astype(vc.dtype), (0, cl, 0, 0))
    k_idx = jnp.arange(max_len)[None, :]
    q_idx = cl + jnp.arange(s)[:, None]
    return kc, vc, (k_idx <= q_idx)[None, None]


class _KVCacheState:
    """Holds cache tensors as non-persistable buffers of a Layer so the
    compiled step threads + donates them (see module docstring).
    ``block_size`` switches to the paged (block-table) cache layout
    (ops/paged_attention.py)."""

    def __init__(self, model, batch, max_len, block_size=None,
                 kv_dtype=None):
        from ..nn.layer.layers import Layer

        class Holder(Layer):
            pass

        self.holder = Holder()
        # decode-loop state for the CHUNKED path: the current token and
        # the eos-finished mask live on device with the caches, so a
        # lax.scan over decode steps carries them — one dispatch per
        # chunk instead of per token (the tunnel/host RTT otherwise
        # bounds decode throughput; see BASELINE.md decode rows)
        self.holder.register_buffer(
            "tok", Tensor(jnp.zeros((batch,), jnp.int32), _internal=True),
            persistable=False,
        )
        self.holder.register_buffer(
            "finished", Tensor(jnp.zeros((batch,), bool), _internal=True),
            persistable=False,
        )
        self.paged = block_size is not None
        kwargs = {"block_size": block_size} if self.paged else {}
        if kv_dtype is not None:
            kwargs["kv_dtype"] = kv_dtype
        caches = model.init_cache(batch, max_len, **kwargs)
        self.n = len(caches)
        self.shapes_dtypes = []
        self.quantized = False
        if self.paged:
            from ..ops.paged_attention import PagedLayerCache  # noqa: F401

            self._tables = caches[0].block_tables
            self._contiguous = bool(getattr(caches[0], "contiguous", False))
            self.quantized = getattr(caches[0], "k_scale", None) is not None
            for i, c in enumerate(caches):
                self.holder.register_buffer(f"k{i}", c.k_pool, persistable=False)
                self.holder.register_buffer(f"v{i}", c.v_pool, persistable=False)
                self.shapes_dtypes.append(
                    (tuple(c.k_pool.shape), c.k_pool._data.dtype)
                )
                if self.quantized:
                    # int8 KV: the per-block scale pools are device
                    # state exactly like the value pools — registered
                    # so to_static threads + donates them with the rest
                    self.holder.register_buffer(
                        f"ks{i}", c.k_scale, persistable=False)
                    self.holder.register_buffer(
                        f"vs{i}", c.v_scale, persistable=False)
        else:
            for i, (k, v) in enumerate(caches):
                self.holder.register_buffer(f"k{i}", k, persistable=False)
                self.holder.register_buffer(f"v{i}", v, persistable=False)
                self.shapes_dtypes.append((tuple(k.shape), k._data.dtype))

    def caches(self):
        if self.paged:
            from ..ops.paged_attention import PagedLayerCache

            return [
                PagedLayerCache(
                    self.holder._buffers[f"k{i}"],
                    self.holder._buffers[f"v{i}"],
                    self._tables,
                    self._contiguous,
                    *((self.holder._buffers[f"ks{i}"],
                       self.holder._buffers[f"vs{i}"])
                      if self.quantized else ()),
                )
                for i in range(self.n)
            ]
        return [
            (self.holder._buffers[f"k{i}"], self.holder._buffers[f"v{i}"])
            for i in range(self.n)
        ]

    def set(self, new_caches):
        for i, c in enumerate(new_caches):
            k, v = (c.k_pool, c.v_pool) if self.paged else (c[0], c[1])
            self.holder._buffers[f"k{i}"]._data = k._data
            self.holder._buffers[f"v{i}"]._data = v._data
            if self.quantized:
                self.holder._buffers[f"ks{i}"]._data = c.k_scale._data
                self.holder._buffers[f"vs{i}"]._data = c.v_scale._data

    def reset(self):
        for i, (shape, dt) in enumerate(self.shapes_dtypes):
            self.holder._buffers[f"k{i}"]._data = jnp.zeros(shape, dt)
            self.holder._buffers[f"v{i}"]._data = jnp.zeros(shape, dt)
            if self.quantized:
                for nm in (f"ks{i}", f"vs{i}"):
                    buf = self.holder._buffers[nm]
                    buf._data = jnp.zeros(buf._data.shape, buf._data.dtype)
        tok = self.holder._buffers["tok"]
        tok._data = jnp.zeros(tok._data.shape, jnp.int32)
        fin = self.holder._buffers["finished"]
        fin._data = jnp.zeros(fin._data.shape, bool)


def _sample(logits, temperature: float, top_k: int):
    """logits [B, V] → token ids [B]; greedy when temperature == 0."""

    def f(lg):
        if temperature == 0:
            return jnp.argmax(lg, axis=-1).astype(jnp.int32)
        lg = lg.astype(jnp.float32) / temperature
        if top_k > 0:
            kth = jnp.sort(lg, axis=-1)[:, -top_k][:, None]
            lg = jnp.where(lg < kth, -jnp.inf, lg)
        key = _random.next_key()
        return jax.random.categorical(key, lg, axis=-1).astype(jnp.int32)

    return apply(f, logits, op_name="sample_token")


def _get_compiled(model, b, s, max_len, temperature, top_k, use_jit,
                  block_size=None, chunked=False, eos_token_id=None,
                  kv_dtype=None, spec_k=None):
    """Build (or fetch) the prefill/decode programs + cache state for
    this (batch, prompt-len, max-len, sampling) signature.

    ``chunked=True`` builds a decode step that reads/writes the token
    and eos-finished mask as HOLDER BUFFERS (device state) instead of
    passing the token host-side — so ``decode.multi_step`` can scan K
    steps in one dispatch. The eos logic is baked into the step, hence
    eos_token_id joins the cache key.

    ``spec_k=K`` additionally builds the speculative VERIFY program —
    the cached step at width K+1 returning the argmax at EVERY
    position — and the return grows to a 4-tuple
    ``(state, prefill, decode, verify)``."""
    from .. import jit

    key = (b, s, max_len, temperature, top_k, use_jit, block_size,
           chunked, eos_token_id if chunked else None, kv_dtype, spec_k)
    store = getattr(model, "_generation_programs", None)
    if store is None:
        store = model._generation_programs = {}
    if key in store:
        entry = store.pop(key)  # re-insert as newest
        store[key] = entry
        entry[0].reset()
        return entry
    # bound the program cache: each entry pins full KV buffers + two
    # compiled programs; varying prompt lengths would otherwise grow
    # device memory without limit (LRU, insertion-ordered dict)
    while len(store) >= 4:
        store.pop(next(iter(store)))

    state = _KVCacheState(model, b, max_len, block_size=block_size,
                          kv_dtype=kv_dtype)

    def prefill(ids, cur_len):
        logits, new = model.forward_with_cache(ids, state.caches(), cur_len)
        state.set(new)
        tok = _sample(logits[:, -1], temperature, top_k)
        state.holder._buffers["tok"]._data = tok._data
        return tok

    if chunked:
        def decode(cur_len):
            prev = state.holder._buffers["tok"]
            fin = state.holder._buffers["finished"]
            logits, new = model.forward_with_cache(
                prev.reshape([b, 1]), state.caches(), cur_len
            )
            state.set(new)
            tok = _sample(logits[:, -1], temperature, top_k)
            if eos_token_id is not None:
                fin2, tok = apply(
                    lambda f, p, t: (
                        f | (p == eos_token_id),
                        jnp.where(f | (p == eos_token_id), eos_token_id, t),
                    ),
                    fin, prev, tok, op_name="eos_freeze",
                )
                state.holder._buffers["finished"]._data = fin2._data
            state.holder._buffers["tok"]._data = tok._data
            return tok
    else:
        def decode(tok, cur_len):
            logits, new = model.forward_with_cache(
                tok.reshape([b, 1]), state.caches(), cur_len
            )
            state.set(new)
            return _sample(logits[:, -1], temperature, top_k)

    verify = None
    if spec_k:
        def verify(ids, cur_len):
            """Speculative verify: feed [B, spec_k+1] candidate tokens
            at positions cur_len.., write their KV, return the greedy
            argmax at EVERY position (the accept rule runs host-side
            on these K+1 ints — logits never leave the device)."""
            logits, new = model.forward_with_cache(
                ids, state.caches(), cur_len)
            state.set(new)
            return apply(
                lambda lg: jnp.argmax(lg, axis=-1).astype(jnp.int32),
                logits, op_name="verify_argmax")

    if use_jit:
        prefill = jit.to_static(prefill, layers=[model, state.holder])
        decode = jit.to_static(decode, layers=[model, state.holder])
        if verify is not None:
            verify = jit.to_static(verify, layers=[model, state.holder])
    entry = ((state, prefill, decode) if verify is None
             else (state, prefill, decode, verify))
    store[key] = entry
    return entry


def _decode_chunked(state, decode, first_tok, s, max_new_tokens,
                    chunk: int, eos_token_id):
    """Drive the chunked decode: one regular call (required before
    multi_step, and it compiles the step), then multi_step scans of up
    to ``chunk`` steps per dispatch. Returns the per-position token
    Tensors ([B] each), eos rows frozen in-program."""
    from .. import to_tensor

    out = [first_tok]
    done = 1  # tokens emitted so far (prefill's sample)
    # regular call: position s + done - 1 writes cache slot for token
    out.append(decode(to_tensor(np.asarray(s + done - 1, np.int32))))
    done += 1
    while done < max_new_tokens:
        k = min(chunk, max_new_tokens - done)
        curs = np.arange(s + done - 1, s + done - 1 + k, dtype=np.int32)
        if k == 1:
            out.append(decode(to_tensor(curs[0])))
        else:
            toks = decode.multi_step(to_tensor(curs))  # [k, B]
            from ..tensor.manipulation import unstack

            out.extend(unstack(toks, axis=0))
        done += k
        if eos_token_id is not None and bool(
            np.asarray(state.holder._buffers["finished"]._data).all()
        ):
            # every row finished: emit frozen eos for the remainder
            # without further dispatches
            while done < max_new_tokens:
                out.append(out[-1])
                done += 1
            break
    return out


def _decode_speculative(decode, verify, input_ids, first_tok, s,
                        max_new_tokens, k, eos_token_id, proposer):
    """Drive speculative generation: per round, draft k tokens per row
    (n-gram prompt lookup by default), ONE verify dispatch scores all
    k+1 positions, and every row advances by the BATCH-MIN accepted
    prefix + 1 (a uniform advance keeps the scalar ``cur_len`` the
    dense cache-write contract needs; the serving engine's per-slot
    ragged accept lives in inference/serving.py). Token-exact vs the
    plain loop: accepted drafts EQUAL the argmax by construction, and
    the tail (< k+1 positions of budget left) falls back to single-step
    decode. Returns the [B] per-position token arrays (host int32)."""
    from .. import to_tensor
    from ..inference.speculative import accept_length

    b = int(input_ids.shape[0])
    prompt_np = np.asarray(
        input_ids.numpy() if hasattr(input_ids, "numpy") else input_ids,
        np.int32)
    first_np = np.asarray(first_tok.numpy(), np.int32).reshape(b)
    hist = [list(prompt_np[r]) + [int(first_np[r])] for r in range(b)]
    finished = np.zeros((b,), bool)
    if eos_token_id is not None:
        finished |= first_np == eos_token_id
    out = [first_np]
    done = 1
    while done < max_new_tokens:
        if eos_token_id is not None and finished.all():
            while done < max_new_tokens:  # frozen rows: no dispatches
                out.append(out[-1])
                done += 1
            break
        cur = s + done - 1  # position of the token out[-1] writes
        # tail: a k+1-wide verify would write KV past max_len (the
        # dense cache's dynamic_update_slice would SHIFT the window)
        no_spec = done + k > max_new_tokens
        if not no_spec:
            drafts = np.zeros((b, k), np.int32)
            any_draft = False
            for r in range(b):
                if finished[r]:
                    continue  # frozen; full-accept forced below
                d = np.asarray(proposer.propose(
                    np.asarray(hist[r], np.int32), k),
                    np.int32).reshape(-1)[:k]
                drafts[r, : d.size] = d
                any_draft = any_draft or d.size > 0
            # no row has draft signal: a k+1-wide verify would spend
            # (k+1)x the decode compute to advance ~1 token — take the
            # plain step instead (the engine path's zero-cost fallback)
            no_spec = not any_draft
        if no_spec:
            tok = decode(to_tensor(out[-1]),
                         to_tensor(np.asarray(cur, np.int32)))
            t = np.asarray(tok.numpy(), np.int32).reshape(b)
            if eos_token_id is not None:
                t = np.where(finished, eos_token_id, t).astype(np.int32)
                finished = finished | (t == eos_token_id)
            for r in range(b):
                hist[r].append(int(t[r]))
            out.append(t)
            done += 1
            continue
        ids_step = np.concatenate([out[-1][:, None], drafts], axis=1)
        toks = verify(to_tensor(ids_step),
                      to_tensor(np.asarray(cur, np.int32)))
        toks_np = np.asarray(toks.numpy(), np.int32)  # [B, k+1]
        # batch-min accept: rows that accepted more re-propose next
        # round (still exact — an accepted prefix of a correct prefix
        # is correct); finished rows must not drag the minimum down.
        # ONE implementation of the exactness-critical accept rule:
        # speculative.accept_length (the engine's device cumprod is
        # pinned against it in tests)
        acc = np.asarray([
            k if finished[r]
            else accept_length(drafts[r], toks_np[r, :-1])
            for r in range(b)])
        m = min(int(acc.min()) + 1, max_new_tokens - done)
        for j in range(m):
            t = toks_np[:, j]
            if eos_token_id is not None:
                t = np.where(finished, eos_token_id, t)
                finished = finished | (t == eos_token_id)
            for r in range(b):
                hist[r].append(int(t[r]))
            out.append(t.astype(np.int32))
        done += m
    return out


def generate(model, input_ids, max_new_tokens: int = 32,
             temperature: float = 0.0, top_k: int = 0,
             eos_token_id: Optional[int] = None, use_jit: bool = True,
             block_size: Optional[int] = None,
             decode_chunk: Optional[int] = None,
             kv_dtype: Optional[str] = None,
             speculative_k: Optional[int] = None,
             draft_proposer=None):
    """Generate ``max_new_tokens`` continuations of ``input_ids``
    ([B, S] int Tensor) with KV caching. Returns [B, S + new] ids.

    ``model`` must provide ``init_cache(batch, max_len)`` and
    ``forward_with_cache(ids, caches, cur_len) -> (logits, caches)``
    (models.LlamaForCausalLM / GPTForCausalLM do). ``block_size``
    switches to the paged (block-table) KV cache — same tokens, pool
    memory layout (ref: block_multihead_attention); the model's
    ``init_cache`` must accept ``block_size`` and its attention must
    handle PagedLayerCache (LlamaForCausalLM and GPTForCausalLM do).

    ``decode_chunk=K`` scans K decode steps inside ONE device dispatch
    (lax.scan over the compiled step; token + eos state carried on
    device) — the serving idiom when host↔device latency dominates
    per-token dispatch. Token-identical to the per-token loop; eos rows
    freeze in-program, and generation stops at the first chunk whose
    rows are all finished.

    ``speculative_k=K`` turns on self-speculative decoding (greedy
    only): a :class:`~paddle_tpu.inference.speculative.DraftProposer`
    (default n-gram prompt lookup — no second model, no extra
    dispatches) drafts K tokens per round and ONE verify dispatch
    scores all K+1 positions; rows advance by the batch-min accepted
    prefix + 1. Token-identical to the plain loop by greedy
    accept-prefix construction. ``kv_dtype="int8"`` (requires
    ``block_size``) quantizes the KV pools per block — both levers
    compose."""
    from .. import to_tensor
    from ..base.tape import no_grad

    b, s = input_ids.shape
    if max_new_tokens <= 0:
        return input_ids
    if speculative_k is not None:
        if int(speculative_k) < 1:
            raise ValueError(
                f"speculative_k must be >= 1, got {speculative_k}")
        if temperature != 0:
            raise ValueError(
                "speculative decoding is greedy-only: the accept rule "
                "is argmax-prefix equality (temperature must be 0)")
        if decode_chunk:
            raise ValueError(
                "speculative_k and decode_chunk are alternative decode "
                "drivers — pass one, not both")
    max_len = s + max_new_tokens
    limit = getattr(getattr(model, "config", None), "max_position_embeddings", None)
    if limit is not None and max_len > limit:
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) = {max_len} "
            f"exceeds the model's max_position_embeddings ({limit})"
        )

    was_training = model.training
    model.eval()
    chunked = bool(decode_chunk) and use_jit and max_new_tokens > 2
    spec = None if speculative_k is None else min(
        int(speculative_k), max(max_new_tokens - 1, 1))
    try:
        with no_grad():
            if spec is not None:
                from ..inference.speculative import NgramProposer

                state, prefill, decode, verify = _get_compiled(
                    model, b, s, max_len, temperature, top_k, use_jit,
                    block_size=block_size, eos_token_id=eos_token_id,
                    kv_dtype=kv_dtype, spec_k=spec,
                )
                zero = to_tensor(np.asarray(0, np.int32))
                tok = prefill(input_ids, zero)
                out = _decode_speculative(
                    decode, verify, input_ids, tok, s, max_new_tokens,
                    spec, eos_token_id,
                    draft_proposer if draft_proposer is not None
                    else NgramProposer(),
                )
                from ..tensor.manipulation import concat

                new_tokens = to_tensor(
                    np.stack(out, axis=1).astype(np.int32))  # [B, new]
                return concat(
                    [input_ids, new_tokens.astype(input_ids.dtype)], axis=1
                )
            state, prefill, decode = _get_compiled(
                model, b, s, max_len, temperature, top_k, use_jit,
                block_size=block_size, chunked=chunked,
                eos_token_id=eos_token_id, kv_dtype=kv_dtype,
            )
            zero = to_tensor(np.asarray(0, np.int32))
            tok = prefill(input_ids, zero)
            if chunked:
                out = _decode_chunked(
                    state, decode, tok, s, max_new_tokens,
                    int(decode_chunk), eos_token_id,
                )
                from ..tensor.manipulation import concat, stack

                new_tokens = stack(out, axis=1)  # [B, new]
                return concat(
                    [input_ids, new_tokens.astype(input_ids.dtype)], axis=1
                )
            out = [tok]
            finished = apply(
                lambda t: jnp.zeros(t.shape, bool), tok, op_name="zeros_like"
            )
            for step_i in range(1, max_new_tokens):
                cur = to_tensor(np.asarray(s + step_i - 1, np.int32))
                tok = decode(tok, cur)
                if eos_token_id is not None:
                    # once a row emits eos, freeze it to eos thereafter
                    finished = apply(
                        lambda f, p: f | (p == eos_token_id),
                        finished, out[-1], op_name="eos_track",
                    )
                    tok = apply(
                        lambda t, f: jnp.where(f, eos_token_id, t),
                        tok, finished, op_name="eos_mask",
                    )
                out.append(tok)
            from ..tensor.manipulation import concat, stack

            new_tokens = stack(out, axis=1)  # [B, new]
            return concat([input_ids, new_tokens.astype(input_ids.dtype)], axis=1)
    finally:
        if was_training:
            model.train()
