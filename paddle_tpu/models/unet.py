"""Stable-Diffusion-style conditional UNet (BASELINE.md config #5).

ref: the reference runs SD through PPDiffusers' UNet2DConditionModel
(downstream of this repo); the in-repo surface it exercises is conv2d,
GroupNorm, SiLU, and the attention entry
(nn/functional/flash_attention.py scaled_dot_product_attention).

TPU-native assembly rules: NCHW convs lowered by XLA onto the MXU;
self/cross attention reshaped to [B, HW, heads, dim] so it rides the
Pallas flash kernel when shapes qualify; sinusoidal timestep embedding
computed with static shapes; GroupNorm in f32 for bf16 stability.
"""
from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from .. import nn
from ..base.tape import apply
from ..nn import functional as F
from ..tensor import manipulation as M

__all__ = ["UNetConfig", "UNet2DConditionModel"]


@dataclasses.dataclass
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: Tuple[int, ...] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    attention_head_dim: int = 64
    cross_attention_dim: int = 768
    norm_num_groups: int = 32
    attn_resolutions: Tuple[int, ...] = (1, 2, 3)  # block indices with attn

    @classmethod
    def tiny(cls):
        return cls(
            in_channels=4, out_channels=4, block_out_channels=(32, 64),
            layers_per_block=1, attention_head_dim=16,
            cross_attention_dim=32, norm_num_groups=8,
            attn_resolutions=(1,),
        )


def timestep_embedding(t, dim: int, max_period: float = 10000.0):
    """Sinusoidal embedding (the SD convention)."""

    def f(tt):
        half = dim // 2
        freqs = jnp.exp(
            -math.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half
        )
        args = tt.astype(jnp.float32)[:, None] * freqs[None, :]
        return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)

    return apply(f, t, op_name="timestep_embedding")


class ResBlock(nn.Layer):
    def __init__(self, in_c, out_c, temb_c, groups):
        super().__init__()
        self.norm1 = nn.GroupNorm(min(groups, in_c), in_c)
        self.conv1 = nn.Conv2D(in_c, out_c, 3, padding=1)
        self.temb_proj = nn.Linear(temb_c, out_c)
        self.norm2 = nn.GroupNorm(min(groups, out_c), out_c)
        self.conv2 = nn.Conv2D(out_c, out_c, 3, padding=1)
        self.skip = nn.Conv2D(in_c, out_c, 1) if in_c != out_c else None

    def forward(self, x, temb):
        h = self.conv1(F.silu(self.norm1(x)))
        h = h + M.reshape(self.temb_proj(F.silu(temb)), [x.shape[0], -1, 1, 1])
        h = self.conv2(F.silu(self.norm2(h)))
        return h + (self.skip(x) if self.skip is not None else x)


class SpatialTransformer(nn.Layer):
    """Self-attn + cross-attn + geglu FFN on flattened HW tokens."""

    def __init__(self, channels, head_dim, context_dim, groups):
        super().__init__()
        self.num_heads = max(1, channels // head_dim)
        self.head_dim = channels // self.num_heads
        self.norm = nn.GroupNorm(min(groups, channels), channels)
        self.proj_in = nn.Linear(channels, channels)
        self.norm1 = nn.LayerNorm(channels)
        self.to_qkv = nn.Linear(channels, 3 * channels, bias_attr=False)
        self.to_out1 = nn.Linear(channels, channels)
        self.norm2 = nn.LayerNorm(channels)
        self.to_q2 = nn.Linear(channels, channels, bias_attr=False)
        self.to_kv2 = nn.Linear(context_dim, 2 * channels, bias_attr=False)
        self.to_out2 = nn.Linear(channels, channels)
        self.norm3 = nn.LayerNorm(channels)
        self.ff1 = nn.Linear(channels, 4 * channels)
        self.ff2 = nn.Linear(4 * channels, channels)
        self.proj_out = nn.Linear(channels, channels)

    def _attn(self, q, k, v, b, s_kv):
        sq = q.shape[1]
        q = M.reshape(q, [b, sq, self.num_heads, self.head_dim])
        k = M.reshape(k, [b, s_kv, self.num_heads, self.head_dim])
        v = M.reshape(v, [b, s_kv, self.num_heads, self.head_dim])
        out = F.scaled_dot_product_attention(q, k, v, is_causal=False,
                                             training=self.training)
        return M.reshape(out, [b, sq, self.num_heads * self.head_dim])

    def forward(self, x, context):
        b, c, h, w = x.shape
        residual = x
        t = M.reshape(self.norm(x), [b, c, h * w])
        t = M.transpose(t, [0, 2, 1])  # [B, HW, C]
        t = self.proj_in(t)

        # self attention
        qkv = self.to_qkv(self.norm1(t))
        q, k, v = M.split(qkv, 3, axis=-1)
        t = t + self.to_out1(self._attn(q, k, v, b, h * w))
        # cross attention over the conditioning sequence
        q2 = self.to_q2(self.norm2(t))
        kv = self.to_kv2(context)
        k2, v2 = M.split(kv, 2, axis=-1)
        t = t + self.to_out2(self._attn(q2, k2, v2, b, context.shape[1]))
        # ffn
        t = t + self.ff2(F.gelu(self.ff1(self.norm3(t))))

        t = self.proj_out(t)
        t = M.transpose(t, [0, 2, 1])
        return M.reshape(t, [b, c, h, w]) + residual


class UNet2DConditionModel(nn.Layer):
    """Down blocks → mid (res+attn+res) → up blocks with skips."""

    def __init__(self, config: Optional[UNetConfig] = None, **kwargs):
        super().__init__()
        if config is not None and kwargs:
            raise ValueError(
                "pass either a UNetConfig or field kwargs, not both "
                f"(got config and {sorted(kwargs)})"
            )
        config = config or UNetConfig(**kwargs)
        self.config = config
        chs = config.block_out_channels
        temb_c = chs[0] * 4
        g = config.norm_num_groups

        self.time_embed = nn.Sequential(
            nn.Linear(chs[0], temb_c), nn.Silu(), nn.Linear(temb_c, temb_c)
        )
        self.conv_in = nn.Conv2D(config.in_channels, chs[0], 3, padding=1)

        # down
        self.down_res = nn.LayerList()
        self.down_attn = nn.LayerList()
        self.downsamplers = nn.LayerList()
        skip_chs = [chs[0]]
        in_c = chs[0]
        for i, out_c in enumerate(chs):
            for _ in range(config.layers_per_block):
                self.down_res.append(ResBlock(in_c, out_c, temb_c, g))
                self.down_attn.append(
                    SpatialTransformer(out_c, config.attention_head_dim,
                                       config.cross_attention_dim, g)
                    if i in config.attn_resolutions
                    else None
                )
                in_c = out_c
                skip_chs.append(out_c)
            if i < len(chs) - 1:
                self.downsamplers.append(nn.Conv2D(out_c, out_c, 3, stride=2, padding=1))
                skip_chs.append(out_c)

        # mid
        self.mid_res1 = ResBlock(in_c, in_c, temb_c, g)
        self.mid_attn = SpatialTransformer(
            in_c, config.attention_head_dim, config.cross_attention_dim, g
        )
        self.mid_res2 = ResBlock(in_c, in_c, temb_c, g)

        # up
        self.up_res = nn.LayerList()
        self.up_attn = nn.LayerList()
        self.upsamplers = nn.LayerList()
        for i, out_c in reversed(list(enumerate(chs))):
            for _ in range(config.layers_per_block + 1):
                skip = skip_chs.pop()
                self.up_res.append(ResBlock(in_c + skip, out_c, temb_c, g))
                self.up_attn.append(
                    SpatialTransformer(out_c, config.attention_head_dim,
                                       config.cross_attention_dim, g)
                    if i in config.attn_resolutions
                    else None
                )
                in_c = out_c
            if i > 0:
                self.upsamplers.append(nn.Conv2D(out_c, out_c, 3, padding=1))

        self.norm_out = nn.GroupNorm(min(g, chs[0]), chs[0])
        self.conv_out = nn.Conv2D(chs[0], config.out_channels, 3, padding=1)

    def forward(self, sample, timestep, encoder_hidden_states):
        """sample [B, C, H, W]; timestep [B]; context [B, L, D]."""
        config = self.config
        emb = timestep_embedding(timestep, config.block_out_channels[0])
        # the sinusoid is computed in f32; run the rest of the net in the
        # parameter dtype (bf16 under model.bfloat16())
        emb = emb.astype(self.conv_in.weight.dtype)
        temb = self.time_embed(emb)

        x = self.conv_in(sample)
        skips = [x]
        li = 0
        n_down = len(config.block_out_channels)
        for i in range(n_down):
            for _ in range(config.layers_per_block):
                x = self.down_res[li](x, temb)
                if self.down_attn[li] is not None:
                    x = self.down_attn[li](x, encoder_hidden_states)
                skips.append(x)
                li += 1
            if i < n_down - 1:
                x = self.downsamplers[i](x)
                skips.append(x)

        x = self.mid_res1(x, temb)
        x = self.mid_attn(x, encoder_hidden_states)
        x = self.mid_res2(x, temb)

        li = 0
        for j, i in enumerate(reversed(range(n_down))):
            for _ in range(config.layers_per_block + 1):
                x = M.concat([x, skips.pop()], axis=1)
                x = self.up_res[li](x, temb)
                if self.up_attn[li] is not None:
                    x = self.up_attn[li](x, encoder_hidden_states)
                li += 1
            if i > 0:
                x = F.interpolate(x, scale_factor=2, mode="nearest")
                x = self.upsamplers[j](x)

        return self.conv_out(F.silu(self.norm_out(x)))

    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())
