"""GPT family — decoder-only LM with learned positions (BASELINE.md
config #4: GPT-3-13B hybrid TP+PP+DP).

ref: the reference trains GPT via PaddleNLP's gpt modeling (downstream
of this repo); in-repo counterparts are the transformer layers
(python/paddle/nn/layer/transformer.py) and fleet's TP layers this
model's tp_axis metadata targets (fleet/layers/mpu/mp_layers.py).

TPU-native notes, same design rules as models/llama.py:
- attention lowers to F.scaled_dot_product_attention → Pallas flash
  attention on TPU;
- all projections carry ``tp_axis`` so hybrid placement shards them
  (column-parallel qkv/fc1, row-parallel proj/fc2);
- static shapes, no data-dependent control flow — jit/scan friendly.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import numpy as np

from .. import nn
from ..base import random as _random
from ..base.tensor import Tensor
from ..nn import functional as F
from ..tensor import manipulation as M

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM"]


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 1024
    layer_norm_epsilon: float = 1e-5
    dropout: float = 0.0

    @classmethod
    def tiny(cls):
        return cls(
            vocab_size=512, hidden_size=64, intermediate_size=256,
            num_hidden_layers=2, num_attention_heads=4,
            max_position_embeddings=128,
        )

    @classmethod
    def gpt3_13b(cls):
        return cls(
            vocab_size=50304, hidden_size=5120, intermediate_size=20480,
            num_hidden_layers=40, num_attention_heads=40,
            max_position_embeddings=2048,
        )


class GPTAttention(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.num_heads = config.num_attention_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        h = config.hidden_size
        self.qkv_proj = nn.Linear(h, 3 * h)
        self.out_proj = nn.Linear(h, h)
        self.qkv_proj.weight.tp_axis = 1  # column parallel
        self.out_proj.weight.tp_axis = 0  # row parallel
        self.dropout = config.dropout

    def forward(self, x, cache=None, cur_len=None):
        b, s, h = x.shape
        qkv = self.qkv_proj(x)  # [B, S, 3H]
        qkv = M.reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
        q = qkv[:, :, 0]
        k = qkv[:, :, 1]
        v = qkv[:, :, 2]
        if cache is None:
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True, dropout_p=self.dropout,
                training=self.training,
            )
            return self.out_proj(M.reshape(out, [b, s, h]))

        from ..base.tape import apply
        from ..ops.paged_attention import PagedLayerCache

        if isinstance(cache, PagedLayerCache):
            from ..ops.paged_attention import paged_attention_step

            if self.training and self.dropout > 0 and s == 1:
                raise ValueError(
                    "the paged KV decode path has no attention-probability "
                    "dropout (the dense cache path does) — call "
                    "model.eval() before paged-cache generation"
                )
            if s == 1:
                out, new_cache = paged_attention_step(
                    q, k, v, cache, cur_len, 1)
                return self.out_proj(M.reshape(out, [b, s, h])), new_cache

            q, kc, vc, mask, new_cache = paged_attention_step(
                q, k, v, cache, cur_len, s)
            out = F.scaled_dot_product_attention(
                q, kc, vc, attn_mask=mask, is_causal=False,
                dropout_p=self.dropout, training=self.training,
            )
            return self.out_proj(M.reshape(out, [b, s, h])), new_cache

        from .generation import update_kv_cache

        k_cache, v_cache = cache

        def step(kk, vv, kc, vc, cl):
            return update_kv_cache(kk, vv, kc, vc, cl, s)

        k_cache, v_cache, mask = apply(
            step, k, v, k_cache, v_cache, cur_len, op_name="kv_cache_update"
        )
        out = F.scaled_dot_product_attention(
            q, k_cache, v_cache, attn_mask=mask, is_causal=False,
            dropout_p=self.dropout, training=self.training,
        )
        return self.out_proj(M.reshape(out, [b, s, h])), (k_cache, v_cache)


class GPTBlock(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.fc1 = nn.Linear(config.hidden_size, config.intermediate_size)
        self.fc2 = nn.Linear(config.intermediate_size, config.hidden_size)
        self.fc1.weight.tp_axis = 1
        self.fc2.weight.tp_axis = 0
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, x, cache=None, cur_len=None):
        if cache is None:
            x = x + self.attn(self.ln_1(x))
        else:
            attn_out, cache = self.attn(self.ln_1(x), cache=cache, cur_len=cur_len)
            x = x + attn_out
        h = self.fc2(F.gelu(self.fc1(self.ln_2(x))))
        out = x + self.dropout(h)
        return out if cache is None else (out, cache)


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size)
        self.wte.weight.tp_axis = 0  # vocab parallel
        self.wpe = nn.Embedding(config.max_position_embeddings, config.hidden_size)
        self.h = nn.LayerList([GPTBlock(config) for _ in range(config.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.drop = nn.Dropout(config.dropout)

    def forward(self, input_ids, caches=None, cur_len=None):
        b, s = input_ids.shape
        import jax.numpy as jnp

        from ..base.tape import apply

        if caches is None:
            pos = apply(lambda: jnp.arange(s, dtype=jnp.int32)[None, :], op_name="arange")
        else:
            pos = apply(
                lambda cl: (cl + jnp.arange(s, dtype=jnp.int32))[None, :],
                cur_len, op_name="arange_offset",
            )
        x = self.wte(input_ids) + self.wpe(pos)
        x = self.drop(x)
        if caches is None:
            for block in self.h:
                x = block(x)
            return self.ln_f(x)
        new_caches = []
        for block, cache in zip(self.h, caches):
            x, cache = block(x, cache=cache, cur_len=cur_len)
            new_caches.append(cache)
        return self.ln_f(x), new_caches


class GPTForCausalLM(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.transformer = GPTModel(config)
        self.lm_head = nn.Linear(config.hidden_size, config.vocab_size, bias_attr=False)
        self.lm_head.weight.tp_axis = 1

    def forward(self, input_ids):
        return self.lm_head(self.transformer(input_ids))

    def init_cache(self, batch: int, max_len: int, dtype=None,
                   block_size=None, num_blocks=None, tables=None,
                   kv_dtype=None):
        """Dense caches by default; ``block_size`` switches to the paged
        (block-table) layout (ops/paged_attention.py) — same protocol as
        LlamaForCausalLM.init_cache (incl. ``kv_dtype="int8"``)."""
        c = self.config
        dt = dtype or self.transformer.wte.weight.dtype
        head_dim = c.hidden_size // c.num_attention_heads
        if block_size is not None:
            from ..ops.paged_attention import alloc_paged_kv_caches

            return alloc_paged_kv_caches(
                c.num_hidden_layers, batch, max_len, c.num_attention_heads,
                head_dim, dt, block_size=block_size, num_blocks=num_blocks,
                tables=tables, kv_dtype=kv_dtype,
            )
        if kv_dtype is not None:
            raise ValueError(
                "kv_dtype quantization requires the paged cache "
                "(pass block_size)")
        from .generation import alloc_kv_caches

        return alloc_kv_caches(
            c.num_hidden_layers, batch, max_len, c.num_attention_heads,
            head_dim, dt,
        )

    def forward_with_cache(self, input_ids, caches, cur_len):
        h, caches = self.transformer(input_ids, caches=caches, cur_len=cur_len)
        return self.lm_head(h), caches

    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())

    def flops_per_token(self, seq_len: int) -> float:
        n = self.num_params()
        c = self.config
        attn = 12 * c.num_hidden_layers * c.hidden_size * seq_len
        return 6 * n + attn
