"""Llama-family decoder LM — the flagship model (BASELINE config #3).

TPU-native re-design of the Llama architecture as expressed in the
reference's building blocks (fused_rms_norm, fused_rope, flash_attn —
ref: paddle/phi/kernels/fusion/gpu/, python/paddle/nn/functional/
flash_attention.py:198; model assembly lives in PaddleNLP downstream).

Design notes for the MXU/HBM:
- all matmuls are [B*S, D] x [D, *] GEMMs — large, batched, bf16-ready
- attention goes through F.scaled_dot_product_attention → Pallas flash
  attention on TPU, jnp fallback elsewhere
- RoPE is computed on the fly (no HBM cache of cos/sin beyond one pair)
- weights carry `tp_axis` metadata so distributed wrappers can shard
  them over a mesh 'mp' axis (column/row parallel) without rewriting
  the model.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..base.tape import apply
from ..base.tensor import Tensor
from .. import nn
from ..nn import functional as F


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False

    @staticmethod
    def tiny(**kw):
        base = dict(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=256,
        )
        base.update(kw)
        return LlamaConfig(**base)

    @staticmethod
    def llama2_7b():
        return LlamaConfig()


def _rope(q, k, theta, position_offset=0):
    """Rotary position embedding on [B, S, H, D] (half-split layout).
    ``position_offset`` may be a traced scalar (KV-cache decode) or a
    per-sequence [B] array (ragged serving batches)."""
    d = q.shape[-1]
    s = q.shape[1]
    off = jnp.asarray(position_offset, jnp.float32)
    # [B, S] positions ([1, S] when the offset is shared)
    pos = jnp.arange(s, dtype=jnp.float32)[None, :] + off.reshape(-1, 1)
    inv_freq = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = pos[..., None] * inv_freq  # [B|1, S, D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]

    def rot(x):
        x1, x2 = jnp.split(x, 2, axis=-1)
        c = cos.astype(x.dtype)
        s_ = sin.astype(x.dtype)
        return jnp.concatenate([x1 * c - x2 * s_, x2 * c + x1 * s_], axis=-1)

    return rot(q), rot(k)


class LlamaAttention(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        h = config.hidden_size
        self.q_proj = nn.Linear(h, self.num_heads * self.head_dim, bias_attr=False)
        self.k_proj = nn.Linear(h, self.num_kv_heads * self.head_dim, bias_attr=False)
        self.v_proj = nn.Linear(h, self.num_kv_heads * self.head_dim, bias_attr=False)
        self.o_proj = nn.Linear(self.num_heads * self.head_dim, h, bias_attr=False)
        # sharding metadata consumed by distributed wrappers (TP)
        self.q_proj.weight.tp_axis = 1  # column parallel
        self.k_proj.weight.tp_axis = 1
        self.v_proj.weight.tp_axis = 1
        self.o_proj.weight.tp_axis = 0  # row parallel

    def forward(self, x, position_offset=0, cache=None, cur_len=None):
        """cache: optional (k_cache, v_cache) Tensors [B, max_len, Hkv, D]
        with ``cur_len`` (scalar Tensor) valid entries; returns
        (out, new_cache) when caching (KV-cache decode path)."""
        b, s = x.shape[0], x.shape[1]
        from ..tensor import manipulation as M

        q = M.reshape(self.q_proj(x), [b, s, self.num_heads, self.head_dim])
        k = M.reshape(self.k_proj(x), [b, s, self.num_kv_heads, self.head_dim])
        v = M.reshape(self.v_proj(x), [b, s, self.num_kv_heads, self.head_dim])
        theta = self.config.rope_theta

        if cache is None:
            q, k = apply(
                lambda qq, kk: _rope(qq, kk, theta, position_offset), q, k, op_name="rope"
            )
            out = F.scaled_dot_product_attention(q, k, v, is_causal=True, training=self.training)
            out = M.reshape(out, [b, s, self.num_heads * self.head_dim])
            return self.o_proj(out)

        from ..ops.paged_attention import PagedLayerCache

        if isinstance(cache, PagedLayerCache):
            from ..ops.paged_attention import paged_attention_step

            rope_fn = lambda qq, kk, cl: _rope(  # noqa: E731
                qq, kk, theta, cl.astype(jnp.float32))
            if s == 1:
                # decode: contiguous tables take the reshape-view XLA
                # path; ragged tables run the Pallas paged-attention
                # kernel (no padded-view gather either way)
                out, new_cache = paged_attention_step(
                    q, k, v, cache, cur_len, 1, rope_fn=rope_fn)
                out = M.reshape(out, [b, s, self.num_heads * self.head_dim])
                return self.o_proj(out), new_cache

            # prefill: scatter into pools, attend over the gathered
            # view — token-for-token identical to dense
            q, kc, vc, mask, new_cache = paged_attention_step(
                q, k, v, cache, cur_len, s, rope_fn=rope_fn)
            out = F.scaled_dot_product_attention(
                q, kc, vc, attn_mask=mask, is_causal=False,
                training=self.training,
            )
            out = M.reshape(out, [b, s, self.num_heads * self.head_dim])
            return self.o_proj(out), new_cache

        k_cache, v_cache = cache

        def step(qq, kk, vv, kc, vc, cl):
            from .generation import update_kv_cache

            qq, kk = _rope(qq, kk, theta, cl.astype(jnp.float32))
            kc, vc, mask = update_kv_cache(kk, vv, kc, vc, cl, s)
            return qq, kc, vc, mask

        q, k_cache, v_cache, mask = apply(
            step, q, k, v, k_cache, v_cache, cur_len, op_name="kv_cache_update"
        )
        out = F.scaled_dot_product_attention(
            q, k_cache, v_cache, attn_mask=mask, is_causal=False,
            training=self.training,
        )
        out = M.reshape(out, [b, s, self.num_heads * self.head_dim])
        return self.o_proj(out), (k_cache, v_cache)


class LlamaMLP(nn.Layer):
    """SwiGLU feed-forward."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        h, i = config.hidden_size, config.intermediate_size
        self.gate_proj = nn.Linear(h, i, bias_attr=False)
        self.up_proj = nn.Linear(h, i, bias_attr=False)
        self.down_proj = nn.Linear(i, h, bias_attr=False)
        self.gate_proj.weight.tp_axis = 1
        self.up_proj.weight.tp_axis = 1
        self.down_proj.weight.tp_axis = 0

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)
        self.mlp = LlamaMLP(config)

    def forward(self, x, cache=None, cur_len=None):
        if cache is None:
            x = x + self.self_attn(self.input_layernorm(x))
        else:
            attn_out, cache = self.self_attn(
                self.input_layernorm(x), cache=cache, cur_len=cur_len
            )
            x = x + attn_out
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x if cache is None else (x, cache)


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = nn.Embedding(config.vocab_size, config.hidden_size)
        self.embed_tokens.weight.tp_axis = 1  # vocab-parallel friendly
        self.layers = nn.LayerList([LlamaDecoderLayer(config) for _ in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)

    def forward(self, input_ids, caches=None, cur_len=None):
        x = self.embed_tokens(input_ids)
        if caches is None:
            for layer in self.layers:
                x = layer(x)
            return self.norm(x)
        new_caches = []
        for layer, cache in zip(self.layers, caches):
            x, cache = layer(x, cache=cache, cur_len=cur_len)
            new_caches.append(cache)
        return self.norm(x), new_caches


class LlamaForCausalLM(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size, bias_attr=False)
            self.lm_head.weight.tp_axis = 1

    def forward(self, input_ids):
        h = self.llama(input_ids)
        return self._head(h)

    def _head(self, h):
        if self.lm_head is None:
            w = self.llama.embed_tokens.weight
            return apply(lambda a, ww: a @ ww.T, h, w, op_name="tied_lm_head")
        return self.lm_head(h)

    # -- KV-cache generation (see models/generation.py) -----------------
    def init_cache(self, batch: int, max_len: int, dtype=None,
                   block_size: Optional[int] = None, num_blocks=None,
                   tables=None, kv_dtype: Optional[str] = None):
        """Dense caches by default; pass ``block_size`` for a paged
        (block-table) cache (ref: block_multihead_attention serving
        layout — see ops/paged_attention.py). ``kv_dtype="int8"``
        (paged only) quantizes the KV pools with per-block scale
        pools."""
        c = self.config
        dt = dtype or self.llama.embed_tokens.weight.dtype
        head_dim = c.hidden_size // c.num_attention_heads
        if block_size is not None:
            from ..ops.paged_attention import alloc_paged_kv_caches

            return alloc_paged_kv_caches(
                c.num_hidden_layers, batch, max_len, c.num_key_value_heads,
                head_dim, dt, block_size=block_size, num_blocks=num_blocks,
                tables=tables, kv_dtype=kv_dtype,
            )
        if kv_dtype is not None:
            raise ValueError(
                "kv_dtype quantization requires the paged cache "
                "(pass block_size)")
        from .generation import alloc_kv_caches

        return alloc_kv_caches(
            c.num_hidden_layers, batch, max_len, c.num_key_value_heads,
            head_dim, dt,
        )

    def forward_with_cache(self, input_ids, caches, cur_len):
        h, caches = self.llama(input_ids, caches=caches, cur_len=cur_len)
        return self._head(h), caches

    def loss(self, input_ids, labels):
        logits = self(input_ids)
        from ..tensor import manipulation as M

        b, s, v = logits.shape
        return F.cross_entropy(M.reshape(logits, [b * s, v]), M.reshape(labels, [b * s]))

    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())

    def flops_per_token(self, seq_len: int) -> float:
        """~6*N + attention flops per token (train fwd+bwd)."""
        n = self.num_params()
        c = self.config
        attn = 12 * c.num_hidden_layers * c.hidden_size * seq_len
        return 6 * n + attn
