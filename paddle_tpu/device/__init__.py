"""paddle_tpu.device — device management, streams/events, memory stats.

ref: python/paddle/device/ — __init__.py (set_device/get_device/
synchronize), cuda/ (Stream/Event, memory stats :places). TPU-native
mapping:

- Streams/events: XLA owns scheduling — there is exactly one compute
  stream per TPU core and the runtime orders collectives/compute for
  you (the latency-hiding scheduler). Stream/Event keep the reference
  API; recording an Event snapshots a marker array and
  ``synchronize``/``wait`` block on it (real device sync points).
- Memory stats come from jax's per-device allocator telemetry
  (device.memory_stats()), replacing the reference's
  StatAllocator counters (§2.10).
"""
from __future__ import annotations

from typing import Optional

import jax

from ..base.device import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    Place,
    TPUPlace,
    device_count,
    get_device,
    get_place,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    set_device,
)

__all__ = [
    "set_device", "get_device", "device_count", "synchronize", "Stream",
    "Event", "current_stream", "stream_guard", "max_memory_allocated",
    "max_memory_reserved", "memory_allocated", "memory_reserved",
    "empty_cache", "get_device_properties", "Place", "CPUPlace",
    "TPUPlace", "CUDAPlace",
]


def _jax_device(device=None) -> jax.Device:
    if device is None:
        return jax.devices()[0]
    if isinstance(device, jax.Device):
        return device
    if isinstance(device, Place):
        return device.jax_device()
    if isinstance(device, int):
        return jax.devices()[device]
    return jax.devices()[0]


def synchronize(device=None):
    """Block until all queued work on the device is done (ref:
    device/__init__.py synchronize — cudaDeviceSynchronize)."""
    d = _jax_device(device)
    import jax.numpy as jnp

    from ..distributed.communication.watchdog import watch

    # a trivial computation ordered after everything in-flight
    with watch(f"device.synchronize({d})"):
        jax.device_put(jnp.zeros(()), d).block_until_ready()


# ---------------------------------------------------------------------------
# memory stats (ref: device/cuda/__init__.py max_memory_allocated etc.)
# ---------------------------------------------------------------------------


def _stats(device=None) -> dict:
    d = _jax_device(device)
    try:
        return d.memory_stats() or {}
    except Exception:
        return {}


def memory_allocated(device=None) -> int:
    return int(_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    s = _stats(device)
    return int(s.get("peak_bytes_in_use", s.get("bytes_in_use", 0)))


def memory_reserved(device=None) -> int:
    s = _stats(device)
    return int(s.get("bytes_reserved", s.get("bytes_in_use", 0)))


def max_memory_reserved(device=None) -> int:
    s = _stats(device)
    return int(s.get("peak_bytes_reserved", s.get("peak_bytes_in_use", 0)))


def empty_cache():
    """ref: device/cuda empty_cache — XLA's allocator has no user-facing
    cache flush; provided as a no-op for API parity."""


def get_device_properties(device=None):
    d = _jax_device(device)

    class _Props:
        name = getattr(d, "device_kind", str(d))
        total_memory = int(_stats(device).get("bytes_limit", 0))
        multi_processor_count = getattr(d, "core_count", 1)
        major, minor = 0, 0

        def __repr__(self):
            return (
                f"DeviceProperties(name='{self.name}', "
                f"total_memory={self.total_memory})"
            )

    return _Props()


# ---------------------------------------------------------------------------
# streams / events (ref: device/__init__.py Stream :797, Event :700)
# ---------------------------------------------------------------------------


class Event:
    """ref: device Event — record/query/synchronize. Recording captures
    a marker ordered after currently-queued work."""

    def __init__(self, device=None, enable_timing: bool = False,
                 blocking: bool = False, interprocess: bool = False):
        self._device = _jax_device(device)
        self._marker = None
        self._enable_timing = enable_timing
        self._t = None

    def record(self, stream: Optional["Stream"] = None):
        import time

        import jax.numpy as jnp

        self._marker = jax.device_put(jnp.zeros(()), self._device)
        if self._enable_timing:
            self._t = time.perf_counter()

    def query(self) -> bool:
        if self._marker is None:
            return True
        return self._marker.is_ready() if hasattr(self._marker, "is_ready") else True

    def synchronize(self):
        if self._marker is not None:
            self._marker.block_until_ready()

    def elapsed_time(self, end: "Event") -> float:
        if self._t is None or end._t is None:
            raise RuntimeError("events must be created with enable_timing=True")
        return (end._t - self._t) * 1000.0


class Stream:
    """ref: device Stream — on TPU there is one XLA compute stream per
    core; this object exists for API parity and to order host-side
    waits (wait_event/wait_stream/synchronize are real sync points)."""

    def __init__(self, device=None, priority: int = 2):
        self._device = _jax_device(device)
        self.priority = priority

    def wait_event(self, event: Event):
        event.synchronize()

    def wait_stream(self, stream: "Stream"):
        synchronize(stream._device)

    def record_event(self, event: Optional[Event] = None) -> Event:
        event = event or Event(self._device)
        event.record(self)
        return event

    def synchronize(self):
        synchronize(self._device)

    def query(self) -> bool:
        return True


_current_streams: dict = {}
_stream_override: Optional[Stream] = None


def current_stream(device=None) -> Stream:
    d = _jax_device(device)
    # a stream_guard override applies only to its own device
    if _stream_override is not None and (
        device is None or _stream_override._device.id == d.id
    ):
        return _stream_override
    if d.id not in _current_streams:
        _current_streams[d.id] = Stream(d)
    return _current_streams[d.id]


class stream_guard:
    """ref: device stream_guard — context selecting the ambient stream;
    single-stream on TPU, so this only swaps the handle."""

    def __init__(self, stream: Stream):
        self._stream = stream
        self._prev = None

    def __enter__(self):
        global _stream_override
        self._prev = _stream_override
        _stream_override = self._stream
        return self._stream

    def __exit__(self, *exc):
        global _stream_override
        _stream_override = self._prev
        return False


# cuda-namespace parity (paddle.device.cuda.*) — maps to the TPU
class cuda:
    Stream = Stream
    Event = Event
    current_stream = staticmethod(current_stream)
    stream_guard = stream_guard
    synchronize = staticmethod(synchronize)
    max_memory_allocated = staticmethod(max_memory_allocated)
    max_memory_reserved = staticmethod(max_memory_reserved)
    memory_allocated = staticmethod(memory_allocated)
    memory_reserved = staticmethod(memory_reserved)
    empty_cache = staticmethod(empty_cache)
    get_device_properties = staticmethod(get_device_properties)

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def get_device_name(device=None):
        """ref: device/cuda get_device_name — the accelerator's name
        (here the TPU device kind, e.g. 'TPU v5 lite')."""
        props = get_device_properties(device)
        return getattr(props, "name", str(props))

    @staticmethod
    def get_device_capability(device=None):
        """ref: device/cuda get_device_capability — (major, minor). CUDA
        compute capability has no TPU analogue; the TPU generation is
        reported as (generation, 0), parsed from the device kind."""
        import re

        name = cuda.get_device_name(device)
        m = re.search(r"v(\d+)", str(name))
        return (int(m.group(1)), 0) if m else (0, 0)


class xpu:
    """paddle.device.xpu parity (ref: device/xpu/__init__.py — one
    public name; XPU has no TPU analogue, synchronize maps to the
    device barrier)."""

    synchronize = staticmethod(synchronize)


# -- parity sweep (ref: python/paddle/device/__init__.py remaining) ---------
from ..base.device import CPUPlace as _CPUPlace


class XPUPlace(_CPUPlace):
    """XPU has no TPU analogue; kept as a CPU place for ported code."""


class IPUPlace(_CPUPlace):
    """IPU has no TPU analogue; kept as a CPU place for ported code."""


def get_cudnn_version():
    """No cuDNN on TPU (ref device get_cudnn_version -> None when absent)."""
    return None


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    """XLA plays CINN's role; the CINN-specific API reports False."""
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_distribute() -> bool:
    """Distributed is always built in (XLA collectives)."""
    return True


def is_compiled_with_custom_device(device_type: str) -> bool:
    """TPU is the 'custom device' of this build (ref custom_device query)."""
    return device_type in ("tpu", "axon")


def get_all_device_type():
    import jax as _jax

    kinds = {"cpu"}
    try:
        kinds.update(d.platform for d in _jax.devices())
    except Exception:
        pass
    return sorted(kinds)


def get_all_custom_device_type():
    return [t for t in get_all_device_type() if t not in ("cpu", "gpu")]


def get_available_device():
    import jax as _jax

    return [f"{d.platform}:{d.id}" for d in _jax.devices()]


def get_available_custom_device():
    return [d for d in get_available_device() if not d.startswith(("cpu", "gpu"))]


def set_stream(stream=None):
    """XLA orders work per-device automatically; returns the current
    stream for parity (ref device set_stream)."""
    return current_stream()
