"""Deadline budgets + retry policies — the shared fault-tolerance layer.

Every blocking surface in the framework (bench supervisor, TCP KV
store, comm watchdog, elastic manager, serving engine) used to carry
its own hardcoded timeout; a single hung operation could then outlive
the caller's window (BENCH_r05: one 1800s attempt timeout ate the whole
driver capture). This module replaces those ad-hoc constants with one
audited discipline:

- :class:`Deadline` — an ABSOLUTE wall-clock budget. Built-in consumers
  (bench supervisor, store, watchdog, elastic, serving) each receive a
  whole Deadline and bound every blocking step against it; CALLERS
  dividing one job budget across phases carve slices with ``sub()``
  (which inherits the parent's clock and can never outlive it), e.g.
  ``register(deadline=job.sub(fraction=0.25))``.
- :class:`RetryPolicy` — exponential backoff with optional
  deterministic jitter and a transient-vs-fatal classifier, bounded by
  a Deadline: retrying never extends past the budget.
- :func:`classify_text` — the shared infrastructure-error taxonomy
  (backend bring-up failures, connection loss, gRPC UNAVAILABLE) used
  by the bench supervisor and anything else that classifies stderr.

Intentionally stdlib-only: ``bench.py``'s supervisor loads this file by
path before any framework/JAX import so a broken backend can never take
the retry layer down with it.
"""
from __future__ import annotations

import random
import time
from typing import Callable, Iterable, Optional, Tuple

__all__ = [
    "BudgetExceeded",
    "Deadline",
    "RetryPolicy",
    "classify_text",
    "TRANSIENT_PATTERNS",
    "FATAL_OVERRIDES",
]


class BudgetExceeded(TimeoutError):
    """A Deadline ran out (subclass of TimeoutError/OSError so existing
    ``except OSError`` / ``except TimeoutError`` handlers keep working)."""


def _now(clock) -> float:
    """Clock values: a plain callable (time.monotonic) or an object with
    ``now()`` (e.g. testing.chaos.ChaosClock)."""
    now = getattr(clock, "now", None)
    return now() if now is not None else clock()


class Deadline:
    """Absolute wall-clock budget that nested operations split/inherit.

    ``Deadline(None)`` is unbounded (remaining() == inf, never expires);
    every bounded deadline records its original ``budget`` so callers
    can reason in fractions (the watchdog ladder fires at fractions of
    the wait's deadline). ``clock`` is injectable for deterministic
    chaos tests.
    """

    __slots__ = ("budget", "_start", "_end", "_clock", "parent")

    def __init__(self, seconds: Optional[float] = None, *, clock=None,
                 parent: Optional["Deadline"] = None):
        self._clock = clock if clock is not None else (
            parent._clock if parent is not None else time.monotonic
        )
        self._start = _now(self._clock)
        self.budget = None if seconds is None else max(0.0, float(seconds))
        self._end = None if self.budget is None else self._start + self.budget
        self.parent = parent

    # -- constructors ---------------------------------------------------
    @classmethod
    def unbounded(cls, *, clock=None) -> "Deadline":
        return cls(None, clock=clock)

    @classmethod
    def coerce(cls, value, *, clock=None) -> "Deadline":
        """None → unbounded; a number → Deadline(seconds); a Deadline
        passes through (so APIs accept either)."""
        if value is None:
            return cls(None, clock=clock)
        if isinstance(value, Deadline):
            return value
        return cls(float(value), clock=clock)

    # -- queries --------------------------------------------------------
    def remaining(self) -> float:
        if self._end is None:
            return float("inf")
        return max(0.0, self._end - _now(self._clock))

    def elapsed(self) -> float:
        return _now(self._clock) - self._start

    def expired(self) -> bool:
        return self._end is not None and _now(self._clock) >= self._end

    def fraction_consumed(self) -> float:
        """elapsed/budget in [0, inf); 0.0 for unbounded deadlines."""
        if self.budget is None:
            return 0.0
        if self.budget <= 0.0:
            return float("inf")
        return self.elapsed() / self.budget

    def timeout(self, default: Optional[float] = None,
                floor: float = 0.0) -> Optional[float]:
        """A value usable as a socket/subprocess timeout: the smaller of
        ``default`` and the remaining budget (never below ``floor``).
        Returns None (block forever) only when both are unbounded."""
        if self._end is None:
            return default
        rem = self.remaining()
        if default is not None:
            rem = min(rem, float(default))
        return max(float(floor), rem)

    def check(self, what: str = "operation") -> None:
        if self.expired():
            raise BudgetExceeded(
                f"{what} exceeded its deadline "
                f"({self.budget:.3f}s budget, {self.elapsed():.3f}s elapsed)"
            )

    # -- splitting ------------------------------------------------------
    def sub(self, seconds: Optional[float] = None,
            fraction: Optional[float] = None) -> "Deadline":
        """A child deadline capped by this one. ``fraction`` takes that
        share of the REMAINING budget; ``seconds`` asks for an absolute
        slice (still clipped to the parent). With neither, the child
        simply mirrors the parent's remaining budget."""
        rem = self.remaining()
        if fraction is not None:
            want = None if rem == float("inf") else rem * float(fraction)
        else:
            want = seconds
        if rem == float("inf"):
            budget = want
        else:
            budget = rem if want is None else min(float(want), rem)
        return Deadline(budget, clock=self._clock, parent=self)

    def sleep(self, seconds: float) -> float:
        """Sleep min(seconds, remaining); returns the time actually
        slept. Uses the clock's own ``sleep`` when it has one (chaos
        clocks advance virtually)."""
        span = min(float(seconds), self.remaining())
        if span <= 0:
            return 0.0
        sleeper = getattr(self._clock, "sleep", time.sleep)
        sleeper(span)
        return span

    def __repr__(self):
        if self.budget is None:
            return "Deadline(unbounded)"
        return (f"Deadline(budget={self.budget:.3f}s, "
                f"remaining={self.remaining():.3f}s)")


# ---------------------------------------------------------------------------
# Transient-vs-fatal classification (shared with bench.py's supervisor).
# lowercase substrings marking a failure as transient infrastructure
# (worth retrying) rather than a real bug in the caller or framework.
TRANSIENT_PATTERNS: Tuple[str, ...] = (
    "unable to initialize backend",
    "failed to connect",
    "connection refused",
    "connection reset",
    "broken pipe",
    "socket closed",
    "unavailable:",  # gRPC status prefix ("UNAVAILABLE: ..."), not the
    # bare word — a traceback merely containing "unavailable" is a bug
    "deadline exceeded",
    "grant unclaimed",
)

# checked BEFORE the transient list: these ride inside "Unable to
# initialize backend ..." messages but mean the backend plugin was never
# registered in this process — no retry can fix that
FATAL_OVERRIDES: Tuple[str, ...] = ("not in the list of known backends",)


def classify_text(text: str) -> str:
    """'transient' | 'fatal' for a stderr/exception string."""
    t = (text or "").lower()
    if any(p in t for p in FATAL_OVERRIDES):
        return "fatal"
    if any(p in t for p in TRANSIENT_PATTERNS):
        return "transient"
    return "fatal"


class RetryPolicy:
    """Exponential backoff + jitter + transient classification, bounded
    by a Deadline.

    ``transient`` is the exception classifier: a tuple of exception
    types, or a callable ``exc -> bool``. ``seed`` makes the jitter
    stream deterministic (chaos tests); ``sleep`` is injectable the same
    way. ConnectionResetError raised with a fatal message still counts
    as transient — types win over text for exceptions; ``classify_text``
    is for subprocess stderr where only text survives.
    """

    def __init__(
        self,
        max_attempts: int = 5,
        base_delay: float = 0.5,
        max_delay: float = 30.0,
        multiplier: float = 2.0,
        jitter: float = 0.0,
        transient=(ConnectionError, TimeoutError, InterruptedError),
        seed: Optional[int] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if multiplier < 1.0:
            raise ValueError("multiplier must be >= 1")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self._transient = transient
        self._rng = random.Random(seed)
        self._sleep = sleep

    def is_transient(self, exc: BaseException) -> bool:
        if callable(self._transient) and not isinstance(self._transient,
                                                        (tuple, type)):
            return bool(self._transient(exc))
        return isinstance(exc, self._transient)

    def delay(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (1-based: the delay
        after the attempt-th failure)."""
        d = min(self.base_delay * self.multiplier ** (attempt - 1),
                self.max_delay)
        if self.jitter:
            d *= 1.0 + self.jitter * self._rng.random()
        return d

    def delays(self) -> Iterable[float]:
        for attempt in range(1, self.max_attempts):
            yield self.delay(attempt)

    def call(self, fn: Callable, *args, deadline: Optional[Deadline] = None,
             describe: str = "", **kw):
        """Run ``fn`` with retries on transient errors; never past the
        deadline. Fatal errors propagate immediately; exhaustion
        re-raises the last transient error (chained under
        BudgetExceeded when the budget, not the attempt count, ran out).
        """
        dl = Deadline.coerce(deadline)
        what = describe or getattr(fn, "__name__", "operation")
        last: Optional[BaseException] = None
        for attempt in range(1, self.max_attempts + 1):
            if dl.expired():
                break
            try:
                return fn(*args, **kw)
            except BaseException as e:  # noqa: BLE001 — reclassified below
                if not self.is_transient(e):
                    raise
                last = e
                if attempt >= self.max_attempts:
                    break
                # backoff through the policy's own sleeper (injectable),
                # clamped so it can never outlive the deadline
                span = min(self.delay(attempt), dl.remaining())
                if span > 0:
                    self._sleep(span)
                elif dl.expired():
                    break
        if last is not None and not dl.expired():
            raise last
        raise BudgetExceeded(
            f"{what} did not succeed within its deadline "
            f"({dl.elapsed():.3f}s elapsed, last error: {last!r})"
        ) from last
