"""graft-race runtime half — a lockdep-style lock-order sanitizer.

``TracedLock`` is a drop-in ``threading.Lock``/``RLock`` replacement
that records, per thread, the set of held locks and their acquisition
sites, and maintains a global lock-ORDER graph (edge A -> B: some
thread held A while acquiring B, stamped with the stack that first
recorded it). Acquiring in an order that closes a cycle raises
:class:`LockOrderViolation` naming BOTH stacks — the one recorded
when the opposite order was first taken and the current one — BEFORE
blocking, so the seeded two-lock inversion tests (and a real inverted
pair in production) fail loudly instead of deadlocking silently.

Like the kernel's lockdep, ordering is tracked per lock CLASS (the
construction site, or an explicit ``name=``), not per instance: two
instance locks born on the same line share an order discipline.

Extras wired into the existing observability stack (all lazy — this
module stays importable with nothing but the stdlib):

- max hold-times per lock class are pushed to the obs registry gauge
  ``lock_hold_seconds_max{lock=...}``;
- a ``flight_recorder.register_dump_extra`` hook renders every
  thread's held locks + pending acquisition into CommWatchdog /
  supervisor hang dumps — a hung pod names its deadlock;
- every release first passes the ``thread.preempt`` chaos site, so a
  seeded schedule can stretch critical sections and shake out latent
  interleavings (the release itself ALWAYS happens — ``drop`` merely
  returns False).

Default OFF: framework code constructs plain ``threading.Lock``s.
:func:`instrument_locks` monkey-patches the ``threading.Lock`` /
``threading.RLock`` factories so locks constructed AFTER the call are
traced (the 2-process serving proofs enable it via
``PADDLE_LOCK_SANITIZER=1``); :func:`uninstrument_locks` restores
them. When off, the hot path pays nothing.
"""
from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional, Set, Tuple

import _thread

__all__ = [
    "LockOrderViolation",
    "TracedLock",
    "instrument_locks",
    "uninstrument_locks",
    "held_locks",
    "lock_order_edges",
    "max_hold_times",
    "violation_count",
    "reset",
]

# real factories, bound BEFORE any patching can occur
_ALLOCATE = _thread.allocate_lock
_REAL_RLOCK = threading.RLock


class LockOrderViolation(RuntimeError):
    """Two lock classes were acquired in both orders (A then B, and B
    then A) — a deadlock waiting for the right interleaving."""


# -- global sanitizer state (guarded by _state_mu; the sanitizer's own
# lock is a raw _thread lock so it can never trace itself) ------------
_state_mu = _ALLOCATE()
_graph: Dict[str, Set[str]] = {}  # lock class -> classes acquired under it
_edge_stacks: Dict[Tuple[str, str], str] = {}  # first stack per edge
_threads: Dict[int, dict] = {}  # ident -> {"held": [...], "pending": ...}
_hold_max: Dict[str, float] = {}  # lock class -> max hold seconds
_violations = [0]


def _caller_frame(skip: int = 2):
    """First frame OUTSIDE this module (skipping __enter__/acquire
    wrappers), so sites point at user code."""
    f = sys._getframe(skip)
    while f.f_back is not None and f.f_code.co_filename == __file__:
        f = f.f_back
    return f


def _stack(skip: int = 2) -> str:
    return "".join(traceback.format_stack(
        _caller_frame(skip + 1), limit=12))


def _site(skip: int = 2) -> str:
    f = _caller_frame(skip + 1)
    return (f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno} "
            f"in {f.f_code.co_name}")


def _thread_state() -> dict:
    ident = threading.get_ident()
    st = _threads.get(ident)
    if st is None:
        st = {"held": [], "pending": None}
        with _state_mu:
            _threads.setdefault(ident, st)
            st = _threads[ident]
    return st


def _reaches(src: str, dst: str) -> Optional[List[str]]:
    """DFS in the order graph: the edge path src -> ... -> dst, or
    None. Called under _state_mu."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for nxt in sorted(_graph.get(node, ())):
            if nxt == dst:
                return path + [dst]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _chaos_preempt() -> None:
    """The ``thread.preempt`` chaos site: a seeded schedule stretches
    the critical section right before the lock is dropped (``drop``'s
    False return is deliberately ignored — the release itself is
    never skipped; the caller runs it in a ``finally``)."""
    try:
        from ..testing import chaos
    except Exception:  # pragma: no cover — stdlib-only contexts
        return
    chaos.inject("thread.preempt")


class _HeldRecord:
    __slots__ = ("lock", "name", "site", "t0", "count")

    def __init__(self, lock: "TracedLock", site: str):
        self.lock = lock
        self.name = lock.name
        self.site = site
        self.t0 = time.monotonic()
        self.count = 1


class TracedLock:
    """Drop-in Lock/RLock wrapper with lockdep-style order checking.
    Supports the full Lock protocol (``acquire(blocking, timeout)`` /
    ``release`` / ``locked`` / context manager), so it also survives
    being wrapped by ``threading.Condition``."""

    def __init__(self, name: Optional[str] = None,
                 reentrant: bool = False, _depth: int = 2):
        self._lk = _REAL_RLOCK() if reentrant else _ALLOCATE()
        self._reentrant = reentrant
        if name is None:
            f = sys._getframe(_depth - 1)
            name = (f"{'RLock' if reentrant else 'Lock'}@"
                    f"{os.path.basename(f.f_code.co_filename)}:"
                    f"{f.f_lineno}")
        self.name = name

    # -- acquire -------------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        st = _thread_state()
        for rec in st["held"]:
            if rec.lock is self:  # reentrant re-acquire: no new edge
                ok = self._lk.acquire(blocking, timeout)
                if ok:
                    rec.count += 1
                return ok
        site = _site()
        with _state_mu:
            for rec in st["held"]:
                if rec.name == self.name:
                    continue  # same class, different instance: no edge
                path = _reaches(self.name, rec.name)
                if path is not None:
                    first = _edge_stacks.get(
                        (path[0], path[1]), "(stack not recorded)")
                    _violations[0] += 1
                    chain = " -> ".join(f"`{n}`" for n in path)
                    raise LockOrderViolation(
                        f"lock-order inversion: acquiring `{self.name}` "
                        f"while holding `{rec.name}`, but the opposite "
                        f"order {chain} is already established.\n"
                        f"--- established order: `{path[0]}` held while "
                        f"acquiring `{path[1]}` at ---\n{first}"
                        f"--- this thread ({threading.current_thread().name}): "
                        f"holding `{rec.name}` (acquired at {rec.site}), "
                        f"acquiring `{self.name}` at ---\n{_stack()}")
                edge = (rec.name, self.name)
                if edge not in _edge_stacks:
                    # full stacks are captured ONLY when a NEW edge (or
                    # a violation) appears — steady state re-walks known
                    # edges and pays a single-frame site lookup per
                    # acquire, which is what keeps instrumented serving
                    # steps within the <2% overhead budget
                    _edge_stacks[edge] = _stack()
                    _graph.setdefault(rec.name, set()).add(self.name)
            st["pending"] = (self.name, site, time.monotonic())
        try:
            if timeout != -1:
                ok = self._lk.acquire(blocking, timeout)
            elif blocking:
                ok = self._lk.acquire()
            else:
                ok = self._lk.acquire(False)
        finally:
            st["pending"] = None
        if ok:
            st["held"].append(_HeldRecord(self, site))
        return ok

    # -- release -------------------------------------------------------
    def release(self) -> None:
        st = _thread_state()
        for i in range(len(st["held"]) - 1, -1, -1):
            rec = st["held"][i]
            if rec.lock is self:
                rec.count -= 1
                if rec.count == 0:
                    del st["held"][i]
                    self._note_hold(time.monotonic() - rec.t0)
                break
        try:
            _chaos_preempt()
        finally:
            self._lk.release()

    def _note_hold(self, dt: float) -> None:
        with _state_mu:
            if dt <= _hold_max.get(self.name, 0.0):
                return
            _hold_max[self.name] = dt
        try:
            from ..obs.metrics import registry

            registry().gauge("lock_hold_seconds_max",
                             {"lock": self.name}).set(dt)
        except Exception:  # obs may be absent/uninitialized
            pass

    # -- Condition protocol --------------------------------------------
    # threading.Condition probes these on its lock; delegating to the
    # real RLock keeps wait() semantics exact for recursive locks (the
    # held RECORD stays during the wait — the bookkeeping re-syncs at
    # _acquire_restore, and order edges are only ever added by our own
    # acquire(), so no false cycles result)
    def _is_owned(self) -> bool:
        owned = getattr(self._lk, "_is_owned", None)
        if owned is not None:
            return owned()
        if self._lk.acquire(False):
            self._lk.release()
            return False
        return True

    def __getattr__(self, attr: str):
        if attr in ("_release_save", "_acquire_restore"):
            return getattr(self._lk, attr)
        raise AttributeError(attr)

    # -- protocol ------------------------------------------------------
    def locked(self) -> bool:
        probe = getattr(self._lk, "locked", None)
        if probe is not None:
            return probe()
        if self._lk.acquire(False):  # RLock pre-3.14 has no .locked()
            self._lk.release()
            return False
        return True

    def __enter__(self) -> "TracedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<TracedLock {self.name}>"


# -- factory patching -------------------------------------------------
_instrumented = [False]


def _lock_factory():
    return TracedLock(_depth=3)


def _rlock_factory():
    return TracedLock(reentrant=True, _depth=3)


def instrument_locks() -> bool:
    """Patch ``threading.Lock``/``threading.RLock`` so locks built
    from here on are traced; also registers the held-locks hang-dump
    hook. Idempotent; returns True when newly installed."""
    if _instrumented[0]:
        return False
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    _instrumented[0] = True
    try:
        from ..distributed.communication.flight_recorder import (
            register_dump_extra,
        )

        register_dump_extra(_dump_held_locks)
    except Exception:  # flight recorder optional at this layer
        pass
    return True


def uninstrument_locks() -> None:
    """Restore the real factories (existing TracedLocks keep working)."""
    if not _instrumented[0]:
        return
    threading.Lock = _ALLOCATE
    threading.RLock = _REAL_RLOCK
    _instrumented[0] = False
    try:
        from ..distributed.communication.flight_recorder import (
            unregister_dump_extra,
        )

        unregister_dump_extra(_dump_held_locks)
    except Exception:
        pass


# -- introspection / test API -----------------------------------------
def held_locks() -> Dict[str, List[Tuple[str, str, float]]]:
    """thread name -> [(lock class, acquisition site, held seconds)]."""
    names = {t.ident: t.name for t in threading.enumerate()}
    now = time.monotonic()
    out: Dict[str, List[Tuple[str, str, float]]] = {}
    with _state_mu:
        for ident, st in _threads.items():
            if st["held"]:
                out[names.get(ident, str(ident))] = [
                    (r.name, r.site, now - r.t0) for r in st["held"]]
    return out


def lock_order_edges() -> Dict[Tuple[str, str], str]:
    with _state_mu:
        return dict(_edge_stacks)


def max_hold_times() -> Dict[str, float]:
    with _state_mu:
        return dict(_hold_max)


def violation_count() -> int:
    return _violations[0]


def reset() -> None:
    """Clear the order graph / held sets / hold-time maxima (tests)."""
    with _state_mu:
        _graph.clear()
        _edge_stacks.clear()
        _threads.clear()
        _hold_max.clear()
        _violations[0] = 0


def _dump_held_locks(file) -> None:
    """flight_recorder dump extra: every thread's held locks and the
    acquisition it is blocked on — a hung pod names its deadlock."""
    names = {t.ident: t.name for t in threading.enumerate()}
    now = time.monotonic()
    with _state_mu:
        snap = [(ident, list(st["held"]), st["pending"])
                for ident, st in sorted(_threads.items())]
    lines = ["", "-- graft-race: per-thread held locks --"]
    busy = False
    for ident, held, pending in snap:
        if not held and pending is None:
            continue
        busy = True
        lines.append(f"thread {names.get(ident, ident)}:")
        for r in held:
            lines.append(f"  holds `{r.name}` for {now - r.t0:.3f}s "
                         f"(acquired at {r.site})")
        if pending is not None:
            pname, psite, pt0 = pending
            lines.append(f"  WAITING for `{pname}` since "
                         f"{now - pt0:.3f}s at {psite}")
    if not busy:
        lines.append("(no locks held)")
    file.write("\n".join(lines) + "\n")
