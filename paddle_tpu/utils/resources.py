"""graft-own runtime half — a resource-accounting leak sanitizer.

:class:`ResourceLedger` mirrors every acquire/release of the serving
stack's ref-counted resources — KV blocks (``BlockManager``), engine
slots, disagg handoff holds, outstanding transfer records, host-tier
frames — each stamped with the acquisition site, so
:meth:`~ResourceLedger.leak_check` can name WHERE every outstanding
resource was taken, and :meth:`~ResourceLedger.verify` can assert the
conservation invariant against a live ``BlockManager``:

    free + live-referenced == pool total
    ledger per-block refcounts == the manager's reference table

The static rules (OWN001-003 in ``analysis/ownership.py``) prove
error-path release discipline at review time; the ledger catches at
RUN time what name-based static analysis cannot see — callbacks,
``getattr`` dispatch, resources threaded through retry helpers.

Instrumentation is factory-stamped, like the lock sanitizer's
patched constructors: :func:`instrument_resources` wraps
``BlockManager``'s five reference primitives (``allocate``/``adopt``/
``fork``/``ref``/``release`` — ``free_sequence`` and
``import_blocks`` delegate to those, so wrapping them too would
double-count) and stamps every BlockManager / engine / host tier
constructed AFTER the call with ``self._graft_ledger``; objects built
while the sanitizer is off carry ``None`` and pay one attribute check
per operation. The 2-process serving proofs enable it via
``PADDLE_LEAK_SANITIZER=1`` (mirroring ``PADDLE_LOCK_SANITIZER``).

Every ledger release first passes the ``leak.hold`` chaos site: a
seeded ``drop`` DEFERS that accounting decrement (the underlying
release itself always happens), manufacturing exactly the outstanding
record ``leak_check()`` must catch — the sanitizer's own smoke test.

Wired into the existing observability stack (all lazy — this module
stays importable with nothing but the stdlib): the
``kv_blocks_outstanding`` gauge tracks live ledger-counted blocks and
``resource_leaks_total`` counts entries a failed ``leak_check`` named;
a ``flight_recorder.register_dump_extra`` hook renders outstanding
resources into hang dumps.
"""
from __future__ import annotations

import os
import sys
import time
from typing import Dict, List, Optional, Tuple

import _thread

__all__ = [
    "ResourceLeakError",
    "ResourceLedger",
    "instrument_resources",
    "uninstrument_resources",
    "current",
]

_state_mu = _thread.allocate_lock()


class ResourceLeakError(AssertionError):
    """Outstanding resources at a leak checkpoint, or a conservation
    violation between the ledger and a BlockManager's own tables."""


def _caller_frame(skip: int = 2):
    """First frame outside this module AND outside the instrumented
    primitive (paged_attention wrappers call through here), so sites
    point at the serving code that took the resource."""
    f = sys._getframe(skip)
    while f.f_back is not None and (
            f.f_code.co_filename == __file__
            or f.f_code.co_filename.endswith("paged_attention.py")):
        f = f.f_back
    return f


def _site(skip: int = 2) -> str:
    f = _caller_frame(skip + 1)
    return (f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno} "
            f"in {f.f_code.co_name}")


def _chaos_hold() -> bool:
    """The ``leak.hold`` chaos site: a seeded ``drop`` returns False
    and the caller SKIPS one accounting decrement — an artificial
    deferred release the sanitizer must then report."""
    try:
        from ..testing import chaos
    except Exception:  # pragma: no cover — stdlib-only contexts
        return True
    return chaos.inject("leak.hold")


class _Entry:
    __slots__ = ("site", "t0", "n")

    def __init__(self, site: str):
        self.site = site
        self.t0 = time.monotonic()
        self.n = 0


class ResourceLedger:
    """Refcounted acquire/release accounting keyed ``(kind, key)``.

    ``kind`` is one of the graft-own resource kinds (``kv.block``,
    ``engine.slot``, ``handoff.hold``, ``handoff.part``,
    ``host.frame``); ``key`` identifies the instance — for KV blocks
    ``(id(manager), physical_block)``, so two managers' block 7 never
    collide. The entry keeps the FIRST acquisition site (the
    steady-state re-acquire of a shared block pays no stack walk) and
    a live count; the entry dies when the count returns to zero."""

    def __init__(self) -> None:
        self._live: Dict[Tuple[str, object], _Entry] = {}
        self._violations: List[str] = []
        self._kv_gauge = [-1]

    # -- accounting ----------------------------------------------------
    def acquire(self, kind: str, key, site: Optional[str] = None,
                n: int = 1) -> None:
        with _state_mu:
            e = self._live.get((kind, key))
            if e is None:
                e = _Entry(site if site is not None else _site())
                self._live[(kind, key)] = e
            e.n += n
        if kind == "kv.block":
            self._push_kv_gauge()

    def release(self, kind: str, key, n: int = 1) -> None:
        """Drop ``n`` references. A release the ledger never saw
        acquired is recorded as a violation (it would drive a real
        refcount negative) rather than raised — the underlying
        operation already happened; ``leak_check`` surfaces it."""
        if not _chaos_hold():
            return  # chaos-deferred decrement: now visibly leaked
        with _state_mu:
            e = self._live.get((kind, key))
            if e is None or e.n < n:
                self._violations.append(
                    f"release without acquire: {kind} {key!r} at "
                    f"{_site()}")
                if e is not None:
                    del self._live[(kind, key)]
            else:
                e.n -= n
                if e.n == 0:
                    del self._live[(kind, key)]
        if kind == "kv.block":
            self._push_kv_gauge()

    # -- checks --------------------------------------------------------
    def outstanding(self, kind: Optional[str] = None
                    ) -> List[Tuple[str, object, int, str]]:
        """``(kind, key, live count, acquisition site)`` per entry."""
        with _state_mu:
            return sorted(
                (k, key, e.n, e.site)
                for (k, key), e in self._live.items()
                if kind is None or k == kind)

    def violation_count(self) -> int:
        with _state_mu:
            return len(self._violations)

    def leak_check(self, ignore: Tuple[str, ...] = ()) -> int:
        """Assert nothing is outstanding (``ignore`` skips kinds that
        legitimately live for the process — e.g. ``host.frame`` cache
        state at worker exit). Raises :class:`ResourceLeakError`
        naming every entry's acquisition site; returns 0 when clean."""
        leaks = [x for x in self.outstanding() if x[0] not in ignore]
        with _state_mu:
            viol = list(self._violations)
        if not leaks and not viol:
            return 0
        self._count_leaks(len(leaks) + len(viol))
        lines = [f"{len(leaks)} outstanding resource(s), "
                 f"{len(viol)} accounting violation(s):"]
        for kind, key, n, site in leaks:
            lines.append(
                f"  LEAKED {kind} {key!r} (live count {n}) — "
                f"acquired at {site}")
        lines.extend(f"  {v}" for v in viol)
        raise ResourceLeakError("\n".join(lines))

    def verify(self, manager) -> None:
        """Conservation against a live ``BlockManager``:
        ``free + live-referenced == total``, the ledger's per-block
        counts equal the manager's reference table exactly, and every
        block-table reference is backed by a live refcount."""
        acct = manager.accounting()
        if acct["free"] + len(acct["refs"]) != acct["total"]:
            raise ResourceLeakError(
                f"block conservation violated: {acct['free']} free + "
                f"{len(acct['refs'])} live != pool total "
                f"{acct['total']}")
        table_refs: Dict[int, int] = {}
        for blocks in acct["owned"].values():
            for b in blocks:
                table_refs[b] = table_refs.get(b, 0) + 1
        for b, c in table_refs.items():
            if acct["refs"].get(b, 0) < c:
                raise ResourceLeakError(
                    f"block {b} appears {c}x in block tables but "
                    f"holds {acct['refs'].get(b, 0)} refs")
        with _state_mu:
            mine = {key[1]: e.n for (k, key), e in self._live.items()
                    if k == "kv.block" and isinstance(key, tuple)
                    and key[0] == id(manager)}
        if mine != acct["refs"]:
            extra = {b: n for b, n in mine.items()
                     if acct["refs"].get(b) != n}
            missing = {b: n for b, n in acct["refs"].items()
                       if mine.get(b) != n}
            raise ResourceLeakError(
                "ledger refcounts diverge from the manager's table: "
                f"ledger-side {extra}, manager-side {missing}")

    def reset(self) -> None:
        with _state_mu:
            self._live.clear()
            self._violations.clear()

    # -- obs (lazy; absent/uninitialized registries are fine) ----------
    def _push_kv_gauge(self) -> None:
        with _state_mu:
            val = sum(1 for (k, _key) in self._live if k == "kv.block")
            if val == self._kv_gauge[0]:
                return
            self._kv_gauge[0] = val
        try:
            from ..obs.metrics import registry

            registry().gauge("kv_blocks_outstanding", {}).set(val)
        except Exception:
            pass

    @staticmethod
    def _count_leaks(n: int) -> None:
        try:
            from ..obs.metrics import registry

            registry().counter("resource_leaks_total", {}).inc(n)
        except Exception:
            pass


# -- BlockManager instrumentation -------------------------------------
_instrumented = [False]
_current: List[Optional[ResourceLedger]] = [None]
_real: Dict[str, object] = {}


def current() -> Optional[ResourceLedger]:
    """The active ledger (None when the sanitizer is off). Engine /
    host-tier constructors stamp this onto ``self._graft_ledger`` so
    per-request hooks gate on one attribute load."""
    return _current[0]


def _wrapped_init(real):
    def __init__(self, *a, **kw):
        real(self, *a, **kw)
        self._graft_ledger = _current[0]
    return __init__


def _wrapped_allocate(real):
    def allocate(self, seq_id, num_tokens):
        led = getattr(self, "_graft_ledger", None)
        if led is None:
            return real(self, seq_id, num_tokens)
        before = len(self._free)
        out = real(self, seq_id, num_tokens)
        n_new = before - len(self._free)
        if n_new > 0:
            site = _site()
            for b in out[len(out) - n_new:]:
                led.acquire("kv.block", (id(self), int(b)), site=site)
        return out
    return allocate


def _wrapped_adopt(real):
    def adopt(self, seq_id, blocks):
        led = getattr(self, "_graft_ledger", None)
        out = real(self, seq_id, blocks)
        if led is not None:
            site = _site()
            for b in blocks:
                led.acquire("kv.block", (id(self), int(b)), site=site)
        return out
    return adopt


def _wrapped_fork(real):
    def fork(self, seq_id, logical_index):
        led = getattr(self, "_graft_ledger", None)
        old, new = real(self, seq_id, logical_index)
        if led is not None and new != old:
            # one reference moved: the sequence's ref leaves `old`
            # and lands on the fresh private block
            led.acquire("kv.block", (id(self), int(new)), site=_site())
            led.release("kv.block", (id(self), int(old)))
        return old, new
    return fork


def _wrapped_ref(real):
    def ref(self, block):
        out = real(self, block)
        led = getattr(self, "_graft_ledger", None)
        if led is not None:
            led.acquire("kv.block", (id(self), int(block)))
        return out
    return ref


def _wrapped_release(real):
    def release(self, block):
        out = real(self, block)  # raises on dead blocks BEFORE we count
        led = getattr(self, "_graft_ledger", None)
        if led is not None:
            led.release("kv.block", (id(self), int(block)))
        return out
    return release


_WRAPPERS = {
    "__init__": _wrapped_init,
    "allocate": _wrapped_allocate,
    "adopt": _wrapped_adopt,
    "fork": _wrapped_fork,
    "ref": _wrapped_ref,
    "release": _wrapped_release,
}


def instrument_resources() -> ResourceLedger:
    """Install the ledger and wrap ``BlockManager``'s reference
    primitives; managers/engines/tiers constructed AFTER this call are
    stamped with the ledger. Idempotent — returns the active ledger."""
    if _instrumented[0]:
        return _current[0]
    from ..ops.paged_attention import BlockManager

    ledger = ResourceLedger()
    _current[0] = ledger
    for name, wrap in _WRAPPERS.items():
        real = BlockManager.__dict__[name]
        _real[name] = real
        setattr(BlockManager, name, wrap(real))
    _instrumented[0] = True
    try:
        from ..distributed.communication.flight_recorder import (
            register_dump_extra,
        )

        register_dump_extra(_dump_outstanding)
    except Exception:  # flight recorder optional at this layer
        pass
    return ledger


def uninstrument_resources() -> None:
    """Restore the real primitives and drop the ledger (managers
    stamped earlier keep their reference, but the restored methods no
    longer consult it)."""
    if not _instrumented[0]:
        return
    from ..ops.paged_attention import BlockManager

    for name, real in _real.items():
        setattr(BlockManager, name, real)
    _real.clear()
    _current[0] = None
    _instrumented[0] = False
    try:
        from ..distributed.communication.flight_recorder import (
            unregister_dump_extra,
        )

        unregister_dump_extra(_dump_outstanding)
    except Exception:
        pass


def _dump_outstanding(file) -> None:
    """flight_recorder dump extra: every outstanding resource and its
    acquisition site — a hung pod names what it never gave back."""
    led = _current[0]
    lines = ["", "-- graft-own: outstanding resources --"]
    if led is None:
        lines.append("(leak sanitizer off)")
    else:
        now = time.monotonic()
        with _state_mu:
            snap = [(k, key, e.n, e.site, now - e.t0)
                    for (k, key), e in sorted(
                        led._live.items(), key=lambda kv: str(kv[0]))]
        if not snap:
            lines.append("(nothing outstanding)")
        for k, key, n, site, age in snap[:200]:
            lines.append(f"  {k} {key!r} n={n} for {age:.3f}s "
                         f"(acquired at {site})")
        if len(snap) > 200:
            lines.append(f"  ... and {len(snap) - 200} more")
    file.write("\n".join(lines) + "\n")
