"""Version-bridging shims for jax API drift.

The build targets current jax but must come up on older releases too
(the container baking the toolchain may lag): each symbol here prefers
the modern location and falls back to where the same object lived
before. Keep every shim to a getattr-probe + import fallback — no
behavioral patches.
"""
from __future__ import annotations

import jax


def shard_map(f=None, /, **kwargs):
    """``jax.shard_map`` (graduated in newer jax) with the
    ``jax.experimental.shard_map`` fallback for older releases. Same
    calling conventions (direct or partial application); the modern
    kwargs are translated for the old signature:

    - ``check_vma``   -> ``check_rep`` (rename)
    - ``axis_names``  -> ``auto`` (the COMPLEMENT: modern code names
      the manual axes, the old API names the axes left automatic)
    """
    fn = getattr(jax, "shard_map", None)
    if fn is None:  # pre-graduation jax
        from jax.experimental.shard_map import shard_map as fn

        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        if "axis_names" in kwargs:
            manual = set(kwargs.pop("axis_names"))
            kwargs["auto"] = frozenset(
                kwargs["mesh"].axis_names) - manual
    if f is None:
        import functools

        return functools.partial(fn, **kwargs)
    return fn(f, **kwargs)


def pvary(x, axes):
    """Mark a value device-varying over ``axes`` for shard_map scan
    carries: ``lax.pcast(..., to="varying")`` on current jax,
    ``lax.pvary`` on the release that introduced it, and IDENTITY on
    pre-VMA jax — there is no varying-manual-axes type system to
    satisfy, so no cast is needed."""
    lax = jax.lax
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axes)
    return x


def global_device_put(value, sharding):
    """Place host/process-local ``value`` with ``sharding``, safely in
    multi-controller mode.

    ``jax.device_put`` onto a sharding with non-addressable devices
    first runs ``multihost_utils.assert_equal`` — a cross-process
    broadcast per call. Besides the per-array sync cost, interleaving
    many of those small gloo broadcasts has been observed to desync the
    transport (``op.preamble.length <= op.nbytes`` aborts) on the CPU
    backend. ``make_array_from_process_local_data`` builds the same
    global array purely from each process's addressable shards — no
    collective at all — so placement loops (parameter sharding, stacked
    pipeline stages, optimizer state) go through here. Single-process
    (or fully-addressable target) falls back to plain device_put.
    """
    import numpy as np

    if jax.process_count() > 1 and not sharding.is_fully_addressable:
        host = np.asarray(value)
        return jax.make_array_from_process_local_data(
            sharding, host, host.shape)
    return jax.device_put(value, sharding)


def get_abstract_mesh():
    """``jax.sharding.get_abstract_mesh()`` or None where the
    abstract-mesh introspection API does not exist yet."""
    fn = getattr(jax.sharding, "get_abstract_mesh", None)
    return fn() if fn is not None else None


def manual_axis_names() -> tuple:
    """Axis names bound manually in the current trace context; empty
    outside any shard_map OR on jax without mesh introspection (there,
    callers inside a manual region must pass axes explicitly — the same
    contract those releases always had)."""
    am = get_abstract_mesh()
    if am is None or getattr(am, "empty", True):
        return ()
    from jax.sharding import AxisType

    return tuple(
        n for n, t in zip(am.axis_names, am.axis_types)
        if t == AxisType.Manual
    )
