"""paddle_tpu.utils — logging, lazy import, misc helpers.

ref: python/paddle/utils/ — the reference bundles cpp_extension,
download, gast…; the TPU build needs the observability pieces: VLOG
logging (utils/log.py here, backing FLAGS_log_level), deprecated-API
decorator, and unique_name (re-exported from base).
"""
from . import log  # noqa: F401
from .log import get_logger  # noqa: F401


def try_import(module_name: str):
    """ref: utils/lazy_import.py try_import — import or raise with a
    helpful message (no pip in this environment)."""
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            f"{module_name} is required but not installed in this "
            "environment (package installs are unavailable)"
        ) from e


def deprecated(since: str = "", update_to: str = "", level: int = 0, reason: str = ""):
    """ref: utils/deprecated.py — warn once per call site."""
    import functools
    import warnings

    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API {fn.__module__}.{fn.__name__} is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f"; use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            if level > 1:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return wrapper

    return decorator
