"""paddle_tpu.utils — logging, lazy import, native extensions, misc.

ref: python/paddle/utils/ — VLOG logging (utils/log.py here, backing
FLAGS_log_level), deprecated-API decorator, unique_name (re-exported
from base), and cpp_extension (native custom-op build + load).
"""
from . import cpp_extension  # noqa: F401
from . import locks  # noqa: F401
from . import log  # noqa: F401
from . import retries  # noqa: F401
from .log import get_logger  # noqa: F401
from .retries import Deadline, RetryPolicy  # noqa: F401


def try_import(module_name: str):
    """ref: utils/lazy_import.py try_import — import or raise with a
    helpful message (no pip in this environment)."""
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            f"{module_name} is required but not installed in this "
            "environment (package installs are unavailable)"
        ) from e


def deprecated(since: str = "", update_to: str = "", level: int = 0, reason: str = ""):
    """ref: utils/deprecated.py — warn once per call site."""
    import functools
    import warnings

    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API {fn.__module__}.{fn.__name__} is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f"; use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            if level > 1:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return wrapper

    return decorator


def run_check():
    """ref: utils/install_check.py run_check — verify the accelerator
    works end-to-end: a tiny train step on the default device."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt

    dev = paddle.device.get_device()
    m = nn.Linear(4, 2)
    o = opt.SGD(learning_rate=0.1, parameters=m.parameters())
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    loss = (m(x) ** 2).mean()
    loss.backward()
    o.step()
    o.clear_grad()
    print(f"PaddlePaddle-TPU works on {dev}: train step ok (loss {float(loss):.4f})")


def require_version(min_version, max_version=None):
    """ref: utils/__init__.py require_version — validate the installed
    framework version against [min, max]."""
    import paddle_tpu

    def parse(v):
        return tuple(int(x) for x in str(v).split(".")[:3] if x.isdigit())

    cur = parse(getattr(paddle_tpu, "__version__", "0.0.0"))
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {cur} < required minimum {min_version}"
        )
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {cur} > allowed maximum {max_version}"
        )
