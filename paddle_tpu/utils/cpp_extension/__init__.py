"""paddle_tpu.utils.cpp_extension — build + load native custom ops.

ref: python/paddle/utils/cpp_extension/__init__.py (CppExtension /
CUDAExtension / load / setup / get_build_directory in cpp_extension.py,
extension_utils.py). The reference JIT-compiles user C++/CUDA into its
kernel registry via setuptools + nvcc; a TPU has no user-facing device
toolchain, so the TPU-native design is:

- ``load(name, sources)`` compiles the C++ with g++ into a cached
  shared library (content-hashed — rebuilds only when sources/flags
  change) and returns an :class:`ExtensionModule`.
- ``ExtensionModule.def_op`` wraps an exported C-ABI symbol (see
  ``paddle_tpu_ext.h``) into a framework op: host execution via
  ``jax.pure_callback`` (works eagerly AND inside ``jit``/``to_static``
  — XLA inserts the device↔host transfers), optional custom backward,
  recorded on the autograd tape like any built-in op.
- Raw symbols stay reachable via ``ExtensionModule.lib`` (ctypes) for
  non-op native code.

Device-compute custom kernels should be written as Pallas kernels in
Python (``ops/flash_attention.py`` is the in-tree model); this module
is the escape hatch for host-side native code — the role the
reference's CPU custom kernels play inside GPU models.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Callable, Optional, Sequence

import numpy as np

__all__ = [
    "CppExtension",
    "CUDAExtension",
    "load",
    "setup",
    "get_build_directory",
    "BuildExtension",
    "ExtensionModule",
]

_HERE = os.path.dirname(os.path.abspath(__file__))

# keep in sync with PTDtype in paddle_tpu_ext.h
_DTYPE_CODES = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
    np.dtype(np.uint8): 4,
    np.dtype(np.bool_): 5,
}


class _PTTensor(ctypes.Structure):
    _fields_ = [
        ("data", ctypes.c_void_p),
        ("shape", ctypes.POINTER(ctypes.c_int64)),
        ("ndim", ctypes.c_int32),
        ("dtype", ctypes.c_int32),
    ]


def get_build_directory(verbose: bool = False) -> str:
    """ref: extension_utils.py get_build_directory — honors
    PADDLE_EXTENSION_DIR, defaults to a per-user cache dir."""
    root = os.environ.get("PADDLE_EXTENSION_DIR")
    if not root:
        root = os.path.join(
            os.path.expanduser("~"), ".cache", "paddle_tpu_extensions"
        )
    os.makedirs(root, exist_ok=True)
    return root


class CppExtension:
    """Source + flags bundle (ref: cpp_extension.py CppExtension — the
    setuptools.Extension factory collapses to a descriptor here)."""

    def __init__(self, sources: Sequence[str], *, name: Optional[str] = None,
                 extra_compile_args: Sequence[str] = (),
                 include_dirs: Sequence[str] = (), **kwargs):
        self.name = name
        self.sources = list(sources)
        self.extra_compile_args = list(extra_compile_args)
        self.include_dirs = list(include_dirs)


class CUDAExtension(CppExtension):
    """ref: cpp_extension.py CUDAExtension. There is no nvcc on a TPU
    host: .cu sources are rejected with guidance (device kernels belong
    in Pallas), plain .cc/.cpp sources build exactly like CppExtension."""

    def __init__(self, sources: Sequence[str], **kwargs):
        cu = [s for s in sources if s.endswith((".cu", ".cuh"))]
        if cu:
            raise RuntimeError(
                f"CUDAExtension: no CUDA toolchain on a TPU host (sources "
                f"{cu}). Write device kernels as Pallas kernels "
                "(paddle_tpu/ops/ has in-tree examples); host-side C++ "
                "builds via CppExtension."
            )
        super().__init__(sources, **kwargs)


class ExtensionModule:
    """A loaded extension: raw ctypes access plus op wrapping."""

    def __init__(self, name: str, so_path: str):
        self.name = name
        self.so_path = so_path
        self.lib = ctypes.CDLL(so_path)
        self._ops = {}

    def __getattr__(self, item):
        ops = self.__dict__.get("_ops", {})
        if item in ops:
            return ops[item]
        if "lib" not in self.__dict__:  # pre-__init__ probes (pickle/copy)
            raise AttributeError(item)
        try:
            return getattr(self.__dict__["lib"], item)
        except AttributeError:
            raise AttributeError(
                f"extension '{self.name}' has no op or symbol {item!r}"
            ) from None

    # -- op wrapping -----------------------------------------------------
    def def_op(
        self,
        op_name: str,
        forward: str,
        backward: Optional[str] = None,
        infer_shape: Optional[Callable] = None,
        infer_dtype: Optional[Callable] = None,
        num_outputs: int = 1,
    ):
        """Wrap exported symbols into a differentiable framework op.

        - ``forward``/``backward``: exported symbol names following the
          ``paddle_tpu_ext.h`` contract. The backward receives
          ``inputs + grad_outputs`` and fills one gradient per input.
        - ``infer_shape(*in_shapes) -> [out_shapes]`` and
          ``infer_dtype(*in_dtypes) -> [out_dtypes]`` play the
          reference's InferShapeFn/InferDtypeFn roles (ref:
          op_meta_info.h SetInferShapeFn); both default to
          first-input passthrough.
        """
        import jax
        import jax.numpy as jnp

        from ...base import tape as _tape

        fwd_sym = getattr(self.lib, forward)
        fwd_sym.restype = ctypes.c_int
        bwd_sym = None
        if backward is not None:
            bwd_sym = getattr(self.lib, backward)
            bwd_sym.restype = ctypes.c_int

        def _call_native(sym, in_arrays, out_shapes, out_dtypes):
            ins = [np.ascontiguousarray(a) for a in in_arrays]
            outs = [np.empty(s, d) for s, d in zip(out_shapes, out_dtypes)]
            all_t = ins + outs
            shape_bufs = [
                (ctypes.c_int64 * max(a.ndim, 1))(*(a.shape or (0,)))
                for a in all_t
            ]
            descs = (_PTTensor * len(all_t))()
            for i, a in enumerate(all_t):
                code = _DTYPE_CODES.get(a.dtype)
                if code is None:
                    raise TypeError(
                        f"custom op '{op_name}': unsupported dtype {a.dtype} "
                        f"(supported: {sorted(str(k) for k in _DTYPE_CODES)})"
                    )
                descs[i] = _PTTensor(
                    a.ctypes.data_as(ctypes.c_void_p), shape_bufs[i],
                    a.ndim, code,
                )
            rc = sym(
                ctypes.byref(descs), ctypes.c_int(len(ins)),
                ctypes.byref(descs, ctypes.sizeof(_PTTensor) * len(ins)),
                ctypes.c_int(len(outs)),
            )
            if rc != 0:
                raise RuntimeError(
                    f"custom op '{op_name}' ({sym}) returned error code {rc}"
                )
            return tuple(outs)

        def _shapes_dtypes(arrs):
            in_shapes = [tuple(a.shape) for a in arrs]
            in_dtypes = [np.dtype(a.dtype) for a in arrs]
            out_shapes = (
                list(infer_shape(*in_shapes)) if infer_shape
                else [in_shapes[0]] * num_outputs
            )
            out_dtypes = (
                [np.dtype(d) for d in infer_dtype(*in_dtypes)] if infer_dtype
                else [in_dtypes[0]] * num_outputs
            )
            return out_shapes, out_dtypes

        def _dispatch(sym, arrs, out_shapes, out_dtypes):
            # Concrete inputs (eager, incl. the primal pass inside the
            # tape's jax.vjp): fetch to host and call directly — no
            # callback machinery, and it works on PJRT backends without
            # host-callback support (e.g. tunneled devices). Tracers
            # (inside jit/to_static): jax.pure_callback, which XLA wires
            # as a host call on backends that support it.
            if any(isinstance(a, jax.core.Tracer) for a in arrs):
                return jax.pure_callback(
                    lambda *a: _call_native(sym, a, out_shapes, out_dtypes),
                    tuple(jax.ShapeDtypeStruct(s, d)
                          for s, d in zip(out_shapes, out_dtypes)),
                    *arrs,
                )
            host = _call_native(sym, [np.asarray(a) for a in arrs],
                                out_shapes, out_dtypes)
            return tuple(jnp.asarray(h) for h in host)

        def fwd_arrays(*arrs):
            out_shapes, out_dtypes = _shapes_dtypes(arrs)
            return _dispatch(fwd_sym, arrs, out_shapes, out_dtypes)

        # ALWAYS custom_vjp (even forward-only): the tape's jax.vjp runs
        # the primal under JVP tracing, where a bare pure_callback is
        # rejected — custom_vjp keeps the forward runnable and defers
        # the no-backward complaint to the moment a gradient is pulled
        @jax.custom_vjp
        def op_core(*arrs):
            return fwd_arrays(*arrs)

        def op_fwd(*arrs):
            return op_core(*arrs), arrs

        def op_bwd(saved, gouts):
            if bwd_sym is None:
                raise RuntimeError(
                    f"custom op '{op_name}' has no backward registered; "
                    "pass backward= to def_op (or mark its inputs "
                    "stop_gradient=True)"
                )
            in_shapes = [tuple(a.shape) for a in saved]
            in_dtypes = [np.dtype(a.dtype) for a in saved]
            return _dispatch(bwd_sym, (*saved, *gouts), in_shapes,
                             in_dtypes)

        op_core.defvjp(op_fwd, op_bwd)

        def op(*tensors):
            from ...base.tensor import Tensor

            def run(*xs):
                outs = op_core(*[x for x in xs])
                return outs[0] if num_outputs == 1 else outs

            wrapped = [
                t if isinstance(t, Tensor) else Tensor(jnp.asarray(t), _internal=True)
                for t in tensors
            ]
            return _tape.apply(run, *wrapped, op_name=f"custom.{op_name}")

        op.__name__ = op_name
        self._ops[op_name] = op
        return op


def _build(name: str, sources: Sequence[str], extra_compile_args=(),
           include_dirs=(), build_directory: Optional[str] = None,
           verbose: bool = False, extra_ldflags=()) -> str:
    build_dir = build_directory or get_build_directory()
    os.makedirs(build_dir, exist_ok=True)
    srcs = [os.path.abspath(s) for s in sources]
    for s in srcs:
        if not os.path.exists(s):
            raise FileNotFoundError(f"cpp_extension source not found: {s}")
    h = hashlib.sha256()
    for s in srcs:
        h.update(open(s, "rb").read())
        h.update(b"\x00")
    # flags and include roots are inputs too: hash per-element (a joined
    # string would collide ["-DA B"] with ["-DA", "-B"]), plus the
    # bundled ABI header's contents so its changes force a rebuild
    for part in (*extra_compile_args, b"--ld--", *extra_ldflags):
        h.update(part if isinstance(part, bytes) else part.encode())
        h.update(b"\x00")
    # header CONTENTS are build inputs too: the bundled ABI header, any
    # header next to a source file, and everything under include_dirs
    # (headers reached through other -I roots or system paths are not
    # tracked — delete the cached .so to force a rebuild)
    header_files = {os.path.join(_HERE, "paddle_tpu_ext.h")}
    for s in srcs:
        src_dir = os.path.dirname(s)
        header_files.update(
            os.path.join(src_dir, f) for f in os.listdir(src_dir)
            if f.endswith((".h", ".hpp", ".hh", ".cuh"))
        )
    for d in include_dirs:
        h.update(os.path.abspath(d).encode() + b"\x00")
        for root, _, files in os.walk(d):
            header_files.update(
                os.path.join(root, f) for f in files
                if f.endswith((".h", ".hpp", ".hh", ".cuh"))
            )
    for hf in sorted(header_files):
        h.update(hf.encode() + b"\x00")
        h.update(open(hf, "rb").read())
        h.update(b"\x00")
    so_path = os.path.join(build_dir, f"{name}_{h.hexdigest()[:12]}.so")
    if os.path.exists(so_path):
        return so_path
    # per-process temp output: concurrent builds of the same extension
    # must not share an intermediate path (a parallel g++ writing into
    # the inode after os.replace would corrupt the cached artifact)
    fd, tmp = tempfile.mkstemp(suffix=".so", prefix=f"{name}_",
                               dir=build_dir)
    os.close(fd)
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        f"-I{_HERE}", *[f"-I{d}" for d in include_dirs],
        *extra_compile_args, "-o", tmp, *srcs, *extra_ldflags,
    ]
    if verbose:
        print("cpp_extension:", " ".join(cmd))
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except subprocess.CalledProcessError as e:
        os.unlink(tmp)
        raise RuntimeError(
            f"cpp_extension build failed for '{name}':\n{e.stderr}"
        ) from e
    except OSError as e:  # compiler missing from PATH etc.
        os.unlink(tmp)
        raise RuntimeError(
            f"cpp_extension build failed for '{name}': cannot run g++ "
            f"({e})"
        ) from e
    os.replace(tmp, so_path)  # atomic publish
    return so_path


def load(name: str, sources: Sequence[str] = (), *,
         extension: Optional[CppExtension] = None,
         extra_cxx_cflags: Sequence[str] = (),
         extra_ldflags: Sequence[str] = (),
         extra_include_paths: Sequence[str] = (),
         build_directory: Optional[str] = None,
         verbose: bool = False, **kwargs) -> ExtensionModule:
    """JIT-compile + load a custom-op extension (ref: cpp_extension.py
    load). Returns an :class:`ExtensionModule`; see ``def_op``."""
    if kwargs:
        import warnings

        warnings.warn(
            f"cpp_extension.load: ignoring unsupported options "
            f"{sorted(kwargs)} (no CUDA toolchain on a TPU host)",
            stacklevel=2,
        )
    if extension is not None:
        sources = extension.sources
        extra_cxx_cflags = list(extra_cxx_cflags) + extension.extra_compile_args
        extra_include_paths = list(extra_include_paths) + extension.include_dirs
    so = _build(name, sources, extra_cxx_cflags, extra_include_paths,
                build_directory, verbose, extra_ldflags)
    return ExtensionModule(name, so)


def setup(name: str = None, ext_modules=None, *, build_directory=None,
          verbose: bool = False, **kwargs):
    """AOT-build extensions (ref: cpp_extension.py setup — the
    setuptools egg install collapses to: build each extension into the
    shared cache and drop a ``<name>.py`` loader next to it, so
    ``import <name>`` works from the build directory)."""
    exts = ext_modules if isinstance(ext_modules, (list, tuple)) else [ext_modules]
    build_dir = build_directory or get_build_directory()
    loaders = []
    for ext in exts:
        if ext is None:
            continue
        ext_name = ext.name or name
        if not ext_name:
            raise ValueError("setup: an extension (or setup) needs a name")
        so = _build(ext_name, ext.sources, ext.extra_compile_args,
                    ext.include_dirs, build_dir, verbose)
        loader = os.path.join(build_dir, f"{ext_name}.py")
        with open(loader, "w") as f:
            f.write(
                "# generated by paddle_tpu.utils.cpp_extension.setup\n"
                "from paddle_tpu.utils.cpp_extension import ExtensionModule\n"
                f"_mod = ExtensionModule({ext_name!r}, {so!r})\n"
                "lib = _mod.lib\n"
                "def_op = _mod.def_op\n"
            )
        loaders.append(loader)
    return loaders


class BuildExtension:
    """API-compat cmdclass stand-in (ref: cpp_extension.py
    BuildExtension.with_options). The setuptools build is replaced by
    :func:`setup` above; this class only preserves the
    ``cmdclass={'build_ext': BuildExtension.with_options(...)}`` idiom."""

    @classmethod
    def with_options(cls, **options):
        return cls

    def __init__(self, *a, **k):
        pass
