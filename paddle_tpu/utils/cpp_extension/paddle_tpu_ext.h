/* paddle_tpu custom-op C ABI.
 *
 * TPU-native counterpart of the reference's custom-operator headers
 * (ref: paddle/phi/api/ext/op_meta_info.h PD_BUILD_OP; python/paddle/
 * utils/cpp_extension/). The reference registers C++ kernels into its
 * dispatch runtime; here a custom op is a plain C-ABI function over
 * tensor descriptors, loaded with utils.cpp_extension.load() and routed
 * through jax.pure_callback (host execution — on TPU the array is
 * fetched to the host, computed, and shipped back, like the reference
 * running a CPU custom kernel inside a GPU model).
 *
 * Contract: an op is
 *     PT_EXPORT int my_op(const PTTensor* inputs, int n_in,
 *                         PTTensor* outputs, int n_out);
 * Inputs are read-only; output buffers are pre-allocated by the caller
 * (shapes from the Python-side infer_shape, the InferMeta role).
 * Return 0 on success, nonzero on failure.
 */
#ifndef PADDLE_TPU_EXT_H_
#define PADDLE_TPU_EXT_H_

#include <stdint.h>

#ifdef __cplusplus
#define PT_EXPORT extern "C" __attribute__((visibility("default")))
#else
#define PT_EXPORT __attribute__((visibility("default")))
#endif

/* dtype codes — keep in sync with _DTYPE_CODES in __init__.py */
enum PTDtype {
  PT_FLOAT32 = 0,
  PT_FLOAT64 = 1,
  PT_INT32 = 2,
  PT_INT64 = 3,
  PT_UINT8 = 4,
  PT_BOOL = 5,
};

typedef struct {
  void* data;           /* contiguous, C-order */
  const int64_t* shape; /* ndim entries */
  int32_t ndim;
  int32_t dtype; /* PTDtype */
} PTTensor;

static inline int64_t pt_numel(const PTTensor* t) {
  int64_t n = 1;
  for (int32_t i = 0; i < t->ndim; ++i) n *= t->shape[i];
  return n;
}

#endif /* PADDLE_TPU_EXT_H_ */
