"""VLOG-style logging (backs FLAGS_log_level; the reference's glog
VLOG(n) discipline, paddle/common/flags.cc v/vmodule).

Usage: ``log.vlog(2, "...")`` emits only when FLAGS_log_level >= 2;
``get_logger(name)`` returns a standard logging.Logger wired to the
same threshold.
"""
from __future__ import annotations

import logging
import sys
from typing import Optional

from ..base.flags import flag

_loggers = {}


def get_logger(name: str = "paddle_tpu", level: Optional[int] = None) -> logging.Logger:
    if name in _loggers:
        return _loggers[name]
    logger = logging.getLogger(name)
    if not logger.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(
            logging.Formatter("%(levelname)s %(asctime)s %(name)s] %(message)s",
                              datefmt="%H:%M:%S")
        )
        logger.addHandler(h)
        logger.propagate = False
    logger.setLevel(level if level is not None else logging.INFO)
    _loggers[name] = logger
    return logger


def vlog(level: int, msg: str, *args):
    """Emit when FLAGS_log_level >= level (glog VLOG parity)."""
    if flag("log_level") >= level:
        get_logger().info(msg, *args)


def warning(msg: str, *args):
    get_logger().warning(msg, *args)


def error(msg: str, *args):
    get_logger().error(msg, *args)
