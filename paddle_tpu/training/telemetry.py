"""Cross-rank training telemetry: straggler and SDC detection.

MegaScale-style per-rank diagnosis: every step each rank publishes a
tiny record — ``(step, step_time, ewma_step_time, gradient
fingerprint)`` — through the shared KV store, and mirrors it into the
collective flight recorder ring (op ``train_step``, rank-divergent by
design) so a CommWatchdog hang dump shows the last steps every rank
completed and how long they took.

Two detectors read the exchange:

- **SDC (silent data corruption)** — data-parallel replicas compute
  bit-identical gradients from identical state + data, so their
  gradient-norm FINGERPRINTS must agree at every step. A fingerprint
  that diverges from the dp-group consensus at the same step is the
  signature of a corrupted gradient (bad HBM bit, broken reduction,
  diverged replica) that loss values alone would never reveal. The
  verdict names the suspect rank(s); the supervisor treats it as an
  anomaly (recompute-or-rollback).
- **Straggler** — each record carries the rank's EWMA step time; a rank
  whose EWMA exceeds ``straggler_factor`` × the cross-rank median for
  ``straggler_patience`` consecutive checks is a persistent straggler.
  The verdict is exposed in ``health()`` and — via the flight
  recorder's dump-extra hook — NAMED in the CommWatchdog hang dump, so
  a hang investigation answers "who is slow", not just "we are hung".

The exchange is deliberately non-blocking: one ``dump()`` round trip
per check, stale records (older than ``stale_s`` on the store's clock)
ignored. A dead peer makes the exchange less informative, never makes
it wedge training — liveness is the ElasticManager's job.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..distributed.communication import flight_recorder as _fr
from ..distributed.store import KVStore
from ..obs.metrics import registry as _obs_registry
from ..utils.retries import Deadline, RetryPolicy

__all__ = ["TrainTelemetry", "TelemetryVerdict", "grad_fingerprint"]


def grad_fingerprint(grad_norm) -> str:
    """Bit-exact fingerprint of a gradient statistic: the f32 bit
    pattern, hex. dp replicas running the same step on the same data
    must agree EXACTLY (same XLA program, same inputs); any tolerance
    would let a slowly-diverging replica hide inside it."""
    return np.float32(grad_norm).tobytes().hex()


@dataclass
class TelemetryVerdict:
    """One check()'s conclusion. ``sdc_suspects`` — ranks whose
    fingerprint left the dp consensus this step (self included when WE
    are the minority; the supervisor only rolls back when SELF is a
    suspect — the recompute-or-rollback remedy is the suspect's);
    ``stragglers`` — ranks persistently slower than the median;
    ``peers_seen`` — ranks with a fresh record."""

    step: int
    sdc_suspects: List[int] = field(default_factory=list)
    stragglers: List[int] = field(default_factory=list)
    peers_seen: List[int] = field(default_factory=list)
    detail: str = ""

    @property
    def sdc(self) -> bool:
        return bool(self.sdc_suspects)


class TrainTelemetry:
    """``ring_len`` — each rank's store record keeps its last-N per-step
    entries, so free-running ranks within N steps of each other still
    compare fingerprints at EXACTLY the same step. ``lockstep=True``
    additionally makes :meth:`check` wait (under
    ``lockstep_deadline_s``) until every dp peer has reached the
    checked step — deterministic detection latency at the cost of
    pacing to the slowest rank; a dead peer only ever costs the
    deadline, never a wedge."""

    def __init__(self, store: KVStore, rank: int, world_size: int, *,
                 tag: str = "trainsnap", dp_group: Optional[List[int]] = None,
                 straggler_factor: float = 2.0, straggler_patience: int = 5,
                 stale_s: float = 120.0, deadline_s: float = 10.0,
                 ring_len: int = 16, lockstep: bool = False,
                 lockstep_deadline_s: float = 10.0,
                 retry: Optional[RetryPolicy] = None):
        self.store = store
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.tag = tag
        # the ranks whose fingerprints must agree with ours (default:
        # everyone — pure dp). Hybrid meshes pass their dp replica group.
        self.dp_group = sorted(dp_group) if dp_group is not None \
            else list(range(world_size))
        self.straggler_factor = float(straggler_factor)
        self.straggler_patience = int(straggler_patience)
        self.stale_s = float(stale_s)
        self.deadline_s = float(deadline_s)
        self.ring_len = max(1, int(ring_len))
        self.lockstep = bool(lockstep)
        self.lockstep_deadline_s = float(lockstep_deadline_s)
        self.retry = retry if retry is not None else RetryPolicy(
            max_attempts=3, base_delay=0.05, max_delay=0.5,
            transient=(OSError, ValueError))
        self._ring: List[dict] = []
        self._ewma_dt: Optional[float] = None
        self._outlier_streak: Dict[int, int] = {}
        self._stragglers: List[int] = []
        self.last_verdict: Optional[TelemetryVerdict] = None
        self.n_published = 0
        # obs registry mirror (ISSUE 12): step times land in a shared
        # histogram so `python -m paddle_tpu.obs dump` shows training
        # latency percentiles without reaching into the store rings
        self._h_step = _obs_registry().histogram(
            "train_step_seconds", {"tag": self.tag, "rank": self.rank},
            help="per-rank training step wall time")
        # persistent stragglers get NAMED in the watchdog hang dump;
        # close() unregisters (a rebuilt supervisor incarnation must not
        # leave its dead telemetry writing stale verdicts into dumps)
        _fr.register_dump_extra(self._dump_extra)

    def close(self) -> None:
        """Detach from the watchdog dump. Call when retiring this
        telemetry instance (e.g. rebuilding the supervisor after
        ``TrainingGaveUp``); safe to call twice."""
        _fr.unregister_dump_extra(self._dump_extra)

    def _key(self, rank: Optional[int] = None) -> str:
        r = self.rank if rank is None else rank
        return f"{self.tag}/tele/{r}"

    # -- publish ---------------------------------------------------------
    def publish(self, step: int, step_time: float, fingerprint: str):
        """One store write + one flight-recorder append per step. Store
        errors are swallowed after the retry budget — telemetry must
        never take a healthy step down with it."""
        self._ewma_dt = (step_time if self._ewma_dt is None
                         else self._ewma_dt + 0.2 * (step_time
                                                     - self._ewma_dt))
        self._h_step.observe(float(step_time))
        rec = {"step": int(step), "dt": float(step_time),
               "ewma_dt": float(self._ewma_dt), "fp": fingerprint}
        _fr.record("train_step", group=f"{self.tag}/dp",
                   detail=f"step={step} dt={step_time * 1e3:.1f}ms "
                          f"fp={fingerprint}")
        # the ring REPLACES a replayed step's entry (post-rollback the
        # clean fingerprint supersedes the anomalous one at that step)
        self._ring = [r for r in self._ring if r["step"] != rec["step"]]
        self._ring.append(rec)
        del self._ring[:-self.ring_len]
        try:
            self.retry.call(
                lambda: self.store.set(
                    self._key(), json.dumps({"ring": self._ring})),
                deadline=Deadline(self.deadline_s),
                describe="telemetry publish")
            self.n_published += 1
        except (OSError, ValueError, RuntimeError, TimeoutError):
            pass

    # -- check -----------------------------------------------------------
    def _fetch_rings(self) -> Dict[int, List[dict]]:
        """One dump() round trip -> per-rank record rings (stale and
        malformed entries dropped)."""
        try:
            entries = self.retry.call(
                lambda: self.store.dump(f"{self.tag}/tele/"),
                deadline=Deadline(self.deadline_s),
                describe="telemetry dump")
        except (OSError, ValueError, RuntimeError, TimeoutError):
            return {}
        rings: Dict[int, List[dict]] = {}
        prefix = f"{self.tag}/tele/"
        for key, val, age in entries:
            if age > self.stale_s:
                continue  # a dead rank's last words — not evidence
            try:
                r = int(key[len(prefix):])
                ring = json.loads(val).get("ring", [])
                if isinstance(ring, list):
                    rings[r] = ring
            except (ValueError, KeyError, AttributeError):
                continue
        return rings

    def high_water(self) -> Optional[int]:
        """The max step ANY rank ever published under this tag — stale
        entries included on purpose: a killed rank's last words are
        exactly the evidence a relaunched incarnation needs to charge
        its replayed steps to the rollback goodput bucket instead of
        counting them as fresh progress."""
        try:
            entries = self.retry.call(
                lambda: self.store.dump(f"{self.tag}/tele/"),
                deadline=Deadline(self.deadline_s),
                describe="telemetry high-water dump")
        except (OSError, ValueError, RuntimeError, TimeoutError):
            return None
        best = None
        for _key, val, _age in entries:
            try:
                for rec in json.loads(val).get("ring", []):
                    s = int(rec.get("step", -1))
                    if best is None or s > best:
                        best = s
            except (ValueError, KeyError, AttributeError, TypeError):
                continue
        return best

    def wait_for_peers(self, step: int, deadline=None) -> bool:
        """Block (bounded) until every dp peer has published a record
        at/past ``step``; False when the deadline lapsed first — a dead
        peer costs the budget, never a wedge."""
        dl = Deadline.coerce(deadline) if deadline is not None \
            else Deadline(self.lockstep_deadline_s)
        others = [r for r in self.dp_group if r != self.rank]
        while True:
            rings = self._fetch_rings()
            ready = [r for r in others
                     if any(rec.get("step", -1) >= step
                            for rec in rings.get(r, ()))]
            if len(ready) == len(others):
                return True
            if dl.expired():
                return False
            dl.sleep(0.02)

    def check(self, step: int, fingerprint: Optional[str] = None
              ) -> TelemetryVerdict:
        """Compare fresh peer records. SDC is only judged among records
        AT ``step`` (a peer mid-step simply hasn't published yet — not
        a divergence): 2 divergent replicas are unattributable so BOTH
        are suspects; with >=3 the majority fingerprint is the
        consensus and the minority the suspects — every rank computes
        the same suspect set. Straggling is judged on the EWMAs
        whatever step each peer is on."""
        verdict = TelemetryVerdict(step=int(step))
        if self.lockstep:
            self.wait_for_peers(step)
        rings = self._fetch_rings()
        verdict.peers_seen = sorted(rings)
        records = {r: ring[-1] for r, ring in rings.items() if ring}
        # -- SDC: dp-group fingerprint consensus at THIS step ----------
        same_step: Dict[int, dict] = {}
        for r, ring in rings.items():
            if r not in self.dp_group:
                continue
            for rec in ring:
                if rec.get("step") == step and rec.get("fp"):
                    same_step[r] = rec
        if fingerprint is not None:
            same_step[self.rank] = {"fp": fingerprint, "step": step}
        if len(same_step) >= 2:
            counts: Dict[str, int] = {}
            for rec in same_step.values():
                counts[rec["fp"]] = counts.get(rec["fp"], 0) + 1
            if len(counts) > 1 and len(same_step) == 2:
                # two replicas disagreeing cannot attribute the fault —
                # BOTH recompute (rollback+replay is clean for the
                # healthy rank and curative for the corrupt one)
                verdict.sdc_suspects = sorted(same_step)
                verdict.detail = (
                    f"step {step}: fingerprints {counts} — 2-replica "
                    "divergence, unattributable: both recompute")
            elif len(counts) > 1:
                # >=3 replicas: the majority fingerprint is the
                # consensus (ties broken toward the lowest rank holding
                # one, so every rank names the same suspects)
                consensus = max(
                    counts,
                    key=lambda fp: (counts[fp], -min(
                        r for r, rec in same_step.items()
                        if rec["fp"] == fp)))
                verdict.sdc_suspects = sorted(
                    r for r, rec in same_step.items()
                    if rec["fp"] != consensus)
                verdict.detail = (
                    f"step {step}: fingerprints {counts} — suspect "
                    f"rank(s) {verdict.sdc_suspects} off the consensus")
        # -- stragglers: persistent EWMA outliers ----------------------
        ewmas = {r: float(rec["ewma_dt"]) for r, rec in records.items()
                 if "ewma_dt" in rec}
        if len(ewmas) >= 2:
            for r, e in ewmas.items():
                # leave-one-out median: judging a rank against a median
                # it participates in lets a single slow rank drag the
                # reference up (fatal at world=2, where the midpoint
                # halves any outlier's apparent factor)
                others = [v for rr, v in ewmas.items() if rr != r]
                ref = float(np.median(others))
                if ref > 0 and e > self.straggler_factor * ref:
                    self._outlier_streak[r] = \
                        self._outlier_streak.get(r, 0) + 1
                else:
                    self._outlier_streak[r] = 0
            self._stragglers = sorted(
                r for r, n in self._outlier_streak.items()
                if n >= self.straggler_patience)
            verdict.stragglers = list(self._stragglers)
        self.last_verdict = verdict
        return verdict

    def stragglers(self) -> List[int]:
        return list(self._stragglers)

    # -- watchdog dump hook ----------------------------------------------
    def _dump_extra(self, file):
        if self._stragglers:
            file.write(
                f"TrainTelemetry: rank(s) {self._stragglers} are "
                f"PERSISTENT stragglers (> {self.straggler_factor}x the "
                f"median EWMA step time for >= {self.straggler_patience} "
                "consecutive checks) — the hang's likeliest origin\n")
        if self.last_verdict is not None and self.last_verdict.sdc:
            file.write(
                f"TrainTelemetry: SDC suspicion at step "
                f"{self.last_verdict.step}: {self.last_verdict.detail}\n")
