"""TrainingSupervisor — anomaly-triggered rollback + two-tier recovery.

The training analogue of ``inference/supervisor.py``'s
ServingSupervisor: wrap the step function, watch every step's health
word, and when something goes wrong make the failure CHEAP instead of
run-ending. The failure taxonomy and what happens per class:

- **anomaly** (non-finite loss/grads, EWMA+MAD loss or grad-norm
  spike, a run of GradScaler found_inf skips, cross-rank SDC
  suspicion) — ROLL BACK: restore the last good in-RAM snapshot
  (params + optimizer moments + LR scheduler + GradScaler + RNG +
  data cursor — token-exact), and replay. Deterministic data and
  restored RNG make the replay bit-identical to a run that never saw
  the anomaly (the loss-parity proof in tests/test_trainfault.py).
- **poison batch** — the same step anomalous ``max_rollback_retries``
  times means the DATA is the trigger, not transient state: the
  offending batch index is quarantined in the :class:`DataCursor`
  (subsequent steps draw the next clean batch) and training proceeds.
- **rollback budget exhausted** — more than ``rollback_budget`` total
  rollbacks means the fault is not transient and not one batch;
  escalate crash-only: ``escalate="raise"`` raises
  :class:`TrainingGaveUp`, ``escalate="exit"`` dies loudly
  (``os._exit(TRAINFAULT_EXIT_CODE)``) for an external relaunch that
  restores from the freshest checkpoint tier.
- **kill / power loss** — in-process recovery is impossible;
  :meth:`resume` on the relaunched rank restores from the FRESHEST
  VERIFIED tier: the peer-RAM snapshot (``PeerReplicator``, RAM-speed)
  when it is at least as new as the newest verified disk checkpoint
  (``AutoCheckpoint``), else disk. A corrupt peer payload (CRC frame)
  falls back to disk automatically.

Snapshot cost model: the in-RAM snapshot DEVICE-COPIES each array leaf
by default (an async HBM-bandwidth op per interval, no host sync) —
``jit.to_static`` compiles steps with ``donate_state=True``, which
hands the old param/moment buffers back to XLA, so a reference capture
would be deleted by the next compiled step. Eager or non-donating
loops can opt into zero-cost reference captures with
``copy_snapshots=False`` (jax arrays are immutable). Either way
rollback is a rebind, RAM-tier recovery a deserialize, and only the
async peer publish serializes (on a worker thread, off the train
path).

Chaos sites (``testing/chaos.py``): ``train.nan`` / ``train.spike`` /
``train.sdc`` corrupt the BATCH before the step runs — a NaN'd batch
poisons params through a real optimizer step, which is exactly what
rollback must provably undo; ``ckpt.peer`` faults the peer-publish
legs. Sites fire once per EXECUTED step, so a schedule's step index
counts executions (replayed steps advance it).
"""
from __future__ import annotations

import os
import sys
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import obs as _obs
from ..incubate.checkpoint.auto_checkpoint import AutoCheckpoint
from ..testing import chaos as _chaos
from .anomaly import Anomaly, AnomalyDetector, unpack_health
from .peer_snapshot import PeerReplicator
from .telemetry import TrainTelemetry, grad_fingerprint

__all__ = ["TrainingSupervisor", "TrainingGaveUp", "DataCursor",
           "TRAINFAULT_EXIT_CODE"]

# crash-only escalation exit code: distinct from the watchdog's 124 and
# elastic's 101 so the relauncher can tell "training gave up on this
# state" (restore a tier, maybe alert) from "hang" / "membership change"
TRAINFAULT_EXIT_CODE = 113


class TrainingGaveUp(RuntimeError):
    """The rollback budget is exhausted — the anomaly is not transient
    state and not a single poison batch; a fresh incarnation restoring
    from a checkpoint tier (or a human) has to take over."""


class DataCursor:
    """Deterministic ``step -> batch`` with quarantine and a
    checkpointable position.

    ``batch_fn(index)`` must be pure in ``index`` (the replay
    guarantee every rollback and resume relies on). Logical step ``s``
    draws data index ``s`` until quarantines shift the mapping: a
    quarantined index is skipped by EVERY subsequent step, so the
    post-quarantine run is the run that never had the poison batch in
    its stream."""

    def __init__(self, batch_fn: Callable[[int], object]):
        self._fn = batch_fn
        self.quarantined: List[int] = []

    def index(self, step: int) -> int:
        """The data index logical ``step`` draws: the step-th element
        of the non-quarantined index sequence (1-based steps)."""
        idx = int(step)
        for q in sorted(self.quarantined):
            if q <= idx:
                idx += 1
        return idx

    def batch(self, step: int):
        return self._fn(self.index(step))

    def quarantine(self, data_index: int) -> None:
        if data_index not in self.quarantined:
            self.quarantined.append(int(data_index))

    def state_dict(self) -> dict:
        return {"quarantined": sorted(self.quarantined)}

    def set_state_dict(self, state: dict) -> None:
        self.quarantined = [int(q) for q in state.get("quarantined", [])]


def _map_batch(batch, fn):
    """Apply ``fn`` to the FIRST float array leaf of a nested batch
    (dict/list/tuple of numpy arrays or Tensors) — the chaos corruption
    hook's shape. Returns (new_batch, applied?)."""
    from ..base.tensor import Tensor

    if isinstance(batch, Tensor):
        if np.dtype(batch.dtype).kind == "f":
            return Tensor(fn(np.asarray(batch.numpy())), _internal=True), \
                True
        return batch, False
    if isinstance(batch, np.ndarray):
        if batch.dtype.kind == "f":
            return fn(batch), True
        return batch, False
    if isinstance(batch, dict):
        out, done = {}, False
        for k, v in batch.items():
            if done:
                out[k] = v
            else:
                out[k], done = _map_batch(v, fn)
        return out, done
    if isinstance(batch, (list, tuple)):
        out, done = [], False
        for v in batch:
            if done:
                out.append(v)
            else:
                v2, done = _map_batch(v, fn)
                out.append(v2)
        return type(batch)(out), done
    return batch, False


class TrainingSupervisor:
    """Supervise a training loop: ``run(total_steps)`` drives
    ``step_fn(batch)`` over the :class:`DataCursor` with anomaly
    detection, rollback, two-tier checkpointing, and telemetry.

    ``step_fn`` returns the step's health: a scalar loss, a
    ``(loss, grad_norm)`` pair, the packed array from
    :func:`training.pack_health` (the one-transfer jit idiom), or a
    dict with keys ``loss`` / ``grad_norm`` / ``fingerprint``.
    """

    def __init__(
        self,
        step_fn: Callable,
        data: Callable[[int], object],
        *,
        layers: Sequence = (),
        optimizers: Sequence = (),
        lr_schedulers: Sequence = (),
        scaler=None,
        detector: Optional[AnomalyDetector] = None,
        snapshot_interval: int = 10,
        snapshots_kept: int = 2,
        max_rollback_retries: int = 2,
        rollback_budget: int = 8,
        escalate: str = "raise",
        peer: Optional[PeerReplicator] = None,
        peer_interval: Optional[int] = None,
        auto_checkpoint: Optional[AutoCheckpoint] = None,
        telemetry: Optional[TrainTelemetry] = None,
        telemetry_interval: int = 1,
        copy_snapshots: bool = True,
        extra_state=None,
        set_extra_state=None,
        rank: Optional[int] = None,
        elastic=None,
        sharded_state: bool = False,
        state_layout: Optional[dict] = None,
    ):
        if escalate not in ("raise", "exit"):
            raise ValueError("escalate must be 'raise' or 'exit'")
        if snapshot_interval < 1:
            raise ValueError("snapshot_interval must be >= 1")
        self.step_fn = step_fn
        self.cursor = data if isinstance(data, DataCursor) else \
            DataCursor(data)
        self.layers = list(layers)
        self.optimizers = list(optimizers)
        self.lr_schedulers = list(lr_schedulers)
        self.scaler = scaler
        self.detector = detector if detector is not None else \
            AnomalyDetector()
        if scaler is not None:
            # found_inf skips feed the detector (satellite: observable
            # skips); chain an existing callback instead of replacing it
            prev = getattr(scaler, "_on_skip", None)

            def _feed(step_ix, _prev=prev):
                self.detector.notify_scaler_skip(step_ix)
                if _prev is not None:
                    _prev(step_ix)

            scaler.set_on_skip(_feed)
        self.snapshot_interval = int(snapshot_interval)
        self.snapshots_kept = max(1, int(snapshots_kept))
        self.max_rollback_retries = int(max_rollback_retries)
        self.rollback_budget = int(rollback_budget)
        self.escalate = escalate
        self.peer = peer
        self.peer_interval = int(peer_interval) if peer_interval \
            else self.snapshot_interval
        if self.peer_interval % self.snapshot_interval != 0:
            # peer publishes ride snapshots (they serialize the captured
            # state), so the cadence must be a multiple — a misaligned
            # value would silently publish only at common multiples
            raise ValueError(
                f"peer_interval ({self.peer_interval}) must be a "
                f"multiple of snapshot_interval "
                f"({self.snapshot_interval}) — peer publishes mirror "
                "existing snapshots")
        self.auto_checkpoint = auto_checkpoint
        if auto_checkpoint is not None:
            if auto_checkpoint.data_cursor is None:
                auto_checkpoint.data_cursor = self.cursor  # disk tier too
            if copy_snapshots:
                # the disk tier races the same donated compiled state
                # the RAM tier does — align its capture mode (an async
                # save pickling a donated-then-deleted buffer would
                # fail the save)
                auto_checkpoint.copy_capture = True
        self.telemetry = telemetry
        self.telemetry_interval = max(1, int(telemetry_interval))
        # copy_snapshots=True (default): snapshot leaves are DEVICE
        # COPIES, not references. jit.to_static compiles steps with
        # donate_state=True by default, which hands the OLD param/
        # moment buffers to XLA — a reference capture would be deleted
        # by the very next compiled step and rollback would restore
        # tombstones. The copy is an async HBM-bandwidth device op per
        # snapshot interval (µs–ms), not a host sync. Eager loops (and
        # donate_state=False compiled ones) may pass False for
        # zero-cost reference captures.
        self.copy_snapshots = bool(copy_snapshots)
        self._extra_state = extra_state
        self._set_extra_state = set_extra_state
        # in-RAM snapshot ring: (step, state) — references, not copies
        self._snapshots: List[Tuple[int, dict]] = []
        self._retries_at: Dict[int, int] = {}
        self.rollbacks = 0
        self.anomalies: List[Tuple[int, str]] = []
        self.events: List[Tuple[str, str]] = []
        self.last_loss: Optional[float] = None
        self._step = 0
        # goodput ledger (ISSUE 14): every second of run() wall time is
        # attributed to exactly one bucket — productive (healthy
        # FIRST-TIME step compute), rollback (anomalous step compute +
        # restore + replayed-step compute), checkpoint (snapshot/
        # auto-checkpoint/peer-wait), stall (everything else: data,
        # detector, telemetry, loop overhead)
        self._wall: Dict[str, float] = {
            "productive": 0.0, "rollback": 0.0,
            "checkpoint": 0.0, "stall": 0.0,
        }
        self._wall_gauges = {
            b: _obs.registry().gauge(
                "training_wall_seconds", {"bucket": b},
                help="run() wall time attributed per goodput bucket")
            for b in self._wall
        }
        # alertable series (ISSUE 15): the default training rules
        # (rollback storms, goodput floor, straggler verdicts) read
        # these registry mirrors, not supervisor attributes
        self._c_rollbacks = _obs.registry().counter(
            "training_rollbacks_total",
            help="anomaly rollbacks performed")
        self._g_goodput = _obs.registry().gauge(
            "training_goodput_frac",
            help="productive fraction of attributed run() wall time")
        self._g_stragglers = _obs.registry().gauge(
            "training_straggler_ranks",
            help="ranks currently flagged by the straggler detector")
        self._goodput_high_water = 0  # highest step ever healthy
        # pod-scale elastic surfaces (ISSUE 16): explicit rank (falls
        # back to telemetry's, then the peer ring slot), the elastic
        # membership manager whose health() this supervisor embeds,
        # and the sharded-state mode where peer snapshots carry only
        # locally-owned shards (restored via the cross-topology
        # checkpoint reshard)
        self._rank_override = rank
        self.elastic = elastic
        self.sharded_state = bool(sharded_state)
        self.state_layout = state_layout
        self.reshard_resumes = 0
        self._g_world = _obs.registry().gauge(
            "training_world_size",
            help="registered elastic world size (0 before register)")
        self._g_remesh = _obs.registry().gauge(
            "training_remesh_events",
            help="distinct re-mesh decisions the elastic manager took")
        self._c_reshard = _obs.registry().counter(
            "training_reshard_resume_total",
            help="resumes that restored state saved on a different "
                 "topology (cross-topology reshard on the peer tier)")

    @property
    def rank(self) -> int:
        """This supervisor's rank: explicit override, else telemetry's,
        else the peer ring slot, else 0 — the suffix the
        ``train.kill_rank.<rank>`` chaos site fires under."""
        if self._rank_override is not None:
            return int(self._rank_override)
        if self.telemetry is not None:
            return int(self.telemetry.rank)
        if self.peer is not None:
            return int(self.peer.rank)
        return 0

    # -- state capture / restore ----------------------------------------
    def _snap_tree(self, obj):
        """AutoCheckpoint._snapshot's value-pinning walk, but DEVICE-
        COPYING each array leaf when ``copy_snapshots`` (see __init__:
        donated compiled state deletes referenced buffers)."""
        if not self.copy_snapshots:
            return AutoCheckpoint._snapshot(obj)
        if isinstance(obj, dict):
            return {k: self._snap_tree(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)) and not hasattr(obj, "_fields"):
            return type(obj)(self._snap_tree(v) for v in obj)
        data = getattr(obj, "_data", None)
        if data is not None:
            import jax.numpy as jnp

            from ..base.tensor import Tensor

            return Tensor(jnp.copy(data), _internal=True)
        return obj

    def _capture(self, step: int) -> dict:
        from ..base import random as _random

        state = {
            "step": int(step),
            "model": [self._snap_tree(l.state_dict())
                      for l in self.layers],
            "optim": [self._snap_tree(o.state_dict())
                      for o in self.optimizers],
            "sched": [s.state_dict() for s in self.lr_schedulers],
            # rng keys land on HOST (encoded): a generator key threaded
            # through donated compiled state would die like the params
            "rng": _random.encode_rng_state(_random.get_rng_state()),
            "cursor": self.cursor.state_dict(),
        }
        if self.scaler is not None:
            state["scaler"] = self.scaler.state_dict()
        if self._extra_state is not None:
            state["extra"] = self._extra_state()
        return state

    def _restore(self, state: dict) -> int:
        from ..base import random as _random

        for layer, sd in zip(self.layers, state.get("model", [])):
            layer.set_state_dict(sd)
        for opt, sd in zip(self.optimizers, state.get("optim", [])):
            opt.set_state_dict(sd)
        for sched, sd in zip(self.lr_schedulers, state.get("sched", [])):
            sched.set_state_dict(sd)
        if self.scaler is not None and state.get("scaler"):
            self.scaler.load_state_dict(state["scaler"])
        if "rng" in state:
            _random.restore_rng_state(state["rng"])
        if "cursor" in state:
            # quarantines are MONOTONIC knowledge about the data, not
            # model state: a rollback to a pre-quarantine snapshot must
            # not forget batches proven poisonous since (two poison
            # batches would otherwise wipe each other's quarantine and
            # burn the budget) — union, never replace
            known = list(self.cursor.quarantined)
            self.cursor.set_state_dict(state["cursor"])
            for q in known:
                self.cursor.quarantine(q)
        if self._set_extra_state is not None and "extra" in state:
            self._set_extra_state(state["extra"])
        return int(state["step"])

    def _serialize(self, state: dict) -> bytes:
        """Peer-tier wire form: the SNAPSHOT's RNG keys lowered to plain
        arrays, the whole tree through framework.io's format-stable
        pickling. Runs on the replicator's worker thread (the captured
        tree is immutable references, so deferring is safe) — the train
        thread never pays the device_get + pickle."""
        from ..base import random as _random
        from ..framework import io as fio

        wire = dict(state)
        wire["rng"] = _random.encode_rng_state(state["rng"])
        if self.sharded_state:
            # each rank ships only the shards its devices own; the
            # restoring incarnation gathers every rank's payload and
            # assembles the full host tree (reshard-on-resume)
            from ..distributed.checkpoint import reshard

            return reshard.dumps_sharded(wire, layout=self.state_layout)
        return fio.dumps(wire)

    def _deserialize(self, payload: bytes) -> dict:
        from ..framework import io as fio

        return fio.loads(payload)

    # -- snapshot ring ---------------------------------------------------
    def _take_snapshot(self, step: int):
        state = self._capture(step)
        self._snapshots.append((step, state))
        del self._snapshots[:-self.snapshots_kept]
        if self.peer is not None and (
                step % self.peer_interval == 0 or step == 0):
            try:
                self.peer.publish(
                    step, lambda state=state: self._serialize(state))
            except RuntimeError as e:
                # a failed PREVIOUS publish surfaces here; note it and
                # keep training — the disk tier still advances
                self._note("peer_error", str(e))

    def _newest_snapshot(self) -> Tuple[int, dict]:
        if not self._snapshots:
            raise TrainingGaveUp(
                "anomaly before any snapshot exists — nothing to roll "
                "back to (run() snapshots step 0 before training)")
        return self._snapshots[-1]

    # -- recovery tiers --------------------------------------------------
    def resume(self) -> int:
        """Restore the freshest VERIFIED tier; returns the next step to
        run (1 on a fresh start). Order: peer RAM when its committed
        step >= the newest verified disk step (RAM wins ties — it is
        the cheaper restore and never older), else disk; a corrupt or
        unreadable peer payload falls back to disk.

        Goodput accounting (ISSUE 16): the restore itself is charged to
        the ``checkpoint`` wall bucket, and the fleet's pre-kill
        high-water step is learned from the telemetry rings — every
        replayed step up to it then lands in the ``rollback`` bucket,
        so wall lost to a killed incarnation shows up in THIS
        incarnation's ledger instead of silently counting as progress.

        An incompatible sharded layout raises
        :class:`...checkpoint.reshard.ReshardLayoutError` — permanent,
        never a tier fallback."""
        t_resume = time.monotonic()
        try:
            return self._resume_tiers()
        finally:
            self._ledger("checkpoint", time.monotonic() - t_resume)
            if self.telemetry is not None:
                hw = self.telemetry.high_water()
                if hw is not None and hw > self._goodput_high_water:
                    self._goodput_high_water = hw

    def _peer_cut(self):
        """The newest restorable peer step: the committed step for the
        plain (whole-payload) mode; in sharded mode the CONSISTENT CUT
        — the newest step at which EVERY saved rank has a committed
        payload (publish cadence is deterministic, so min-of-newest is
        that cut)."""
        if self.peer is None:
            return None, None
        if not self.sharded_state:
            return self.peer.latest_step(), None
        ranks = self.peer.ranks()
        if not ranks:
            return None, None
        steps = [self.peer.latest_step(r) for r in ranks]
        if any(s is None for s in steps):
            return None, None
        return min(steps), ranks

    def _restore_sharded_peer(self, step: int, ranks) -> Optional[int]:
        """Gather every saved rank's payload at ``step``, assemble the
        full host tree through the cross-topology reshard, restore.
        Returns the restored step, or None to fall to the next tier
        (missing/corrupt payloads); an incompatible layout RAISES."""
        from ..distributed.checkpoint import reshard

        payloads = []
        for r in ranks:
            p = self.peer.fetch_at(r, step)
            if p is None:
                self._note("resume_peer_failed",
                           f"sharded cut at step {step}: rank {r}'s "
                           "payload missing or corrupt")
                return None
            payloads.append(p)
        try:
            state, saved_layout = reshard.loads_combined(
                payloads, target_layout=self.state_layout)
        except reshard.ReshardLayoutError:
            raise  # permanent: a mesh mismatch, not a bad tier
        except Exception as e:  # noqa: BLE001 — tier fallback
            self._note("resume_peer_failed",
                       f"{type(e).__name__}: {e}")
            return None
        restored = self._restore(state)
        if saved_layout is not None and self.state_layout is not None \
                and saved_layout != self.state_layout:
            self.reshard_resumes += 1
            self._c_reshard.inc()
            self._note("reshard_resume",
                       f"state saved on layout {saved_layout} restored "
                       f"onto {self.state_layout}")
        self._note("resume",
                   f"peer RAM tier (sharded, {len(payloads)} rank "
                   f"payloads) at step {restored}")
        return restored

    def _resume_tiers(self) -> int:
        peer_step, peer_ranks = self._peer_cut()
        disk_step = self.auto_checkpoint.latest_step() \
            if self.auto_checkpoint is not None else None
        if peer_step is not None and (disk_step is None
                                      or peer_step >= disk_step) \
                and self.sharded_state:
            restored = self._restore_sharded_peer(peer_step, peer_ranks)
            if restored is not None:
                self._snapshots = [(restored, self._capture(restored))]
                self._step = restored
                return restored + 1
        elif peer_step is not None and (disk_step is None
                                        or peer_step >= disk_step):
            got = self.peer.fetch()
            # fetch() may fall back to an OLDER verified replica when
            # the newest payload is corrupt — re-compare the step we
            # actually got, or a stale peer replica would shadow a
            # fresher verified disk checkpoint
            if got is not None and disk_step is not None \
                    and got[0] < disk_step:
                self._note("resume_peer_stale",
                           f"verified peer replica is step {got[0]} < "
                           f"disk step {disk_step}; using disk")
                got = None
            if got is not None:
                step, payload = got
                try:
                    state = self._deserialize(payload)
                    restored = self._restore(state)
                    self._snapshots = [(restored, self._capture(restored))]
                    self._step = restored
                    self._note("resume",
                               f"peer RAM tier at step {restored}")
                    return restored + 1
                except Exception as e:  # noqa: BLE001 — tier fallback
                    self._note("resume_peer_failed",
                               f"{type(e).__name__}: {e}")
        if self.auto_checkpoint is not None:
            nxt = self.auto_checkpoint.resume()
            if nxt:
                self._step = nxt - 1
                self._snapshots = [(nxt - 1, self._capture(nxt - 1))]
                self._note("resume", f"disk tier at step {nxt - 1}")
                return nxt
        self._note("resume", "fresh start")
        return 1

    # -- chaos corruption hooks ------------------------------------------
    @staticmethod
    def _corrupt(batch):
        """Apply any scheduled train.nan/spike/sdc fault to the batch —
        the corruption enters through the DATA so a poisoned step
        corrupts params via a real optimizer step (what rollback must
        undo), and a quarantined batch genuinely removes the trigger."""
        if not _chaos.inject("train.nan"):
            batch, _ = _map_batch(batch, lambda a: a * np.float32("nan"))
        if not _chaos.inject("train.spike"):
            batch, _ = _map_batch(
                batch, lambda a: a * np.float32(1e4))
        if not _chaos.inject("train.sdc"):
            def flip(a):
                out = np.array(a)
                out.flat[0] = out.flat[0] + np.float32(1e-3)
                return out
            batch, _ = _map_batch(batch, flip)
        return batch

    # -- result parsing --------------------------------------------------
    @staticmethod
    def _parse_result(out) -> Tuple[float, Optional[float], bool, bool,
                                    Optional[str]]:
        """(loss, grad_norm, loss_finite, grad_finite, fingerprint)."""
        fp = None
        if isinstance(out, dict):
            fp = out.get("fingerprint")
            gn = out.get("grad_norm")
            loss = out["loss"]
            loss = float(np.asarray(getattr(loss, "_data", loss)))
            gn = None if gn is None else \
                float(np.asarray(getattr(gn, "_data", gn)))
            import math as _math
            return (loss, gn, _math.isfinite(loss),
                    gn is None or _math.isfinite(gn), fp)
        if isinstance(out, tuple) and len(out) == 2:
            loss = float(np.asarray(getattr(out[0], "_data", out[0])))
            gn = float(np.asarray(getattr(out[1], "_data", out[1])))
            import math as _math
            return loss, gn, _math.isfinite(loss), _math.isfinite(gn), None
        arr = np.asarray(getattr(out, "_data", out), np.float32).reshape(-1)
        if arr.size >= 4:
            loss, gn, lfin, gfin = unpack_health(arr)
            return loss, gn, lfin, gfin, None
        loss = float(arr[0])
        import math as _math
        return loss, None, _math.isfinite(loss), True, None

    # -- the loop --------------------------------------------------------
    def run(self, total_steps: int, *, start: Optional[int] = None) -> dict:
        """Train steps ``start..total_steps`` (1-based; ``start``
        defaults to where :meth:`resume`/the last run() left off + 1).
        Returns a report dict (final loss, rollbacks, quarantined...).
        """
        step = int(start) if start is not None else self._step + 1
        if not self._snapshots:
            # the rollback floor: state as of "before step `step`"
            t_ck = time.monotonic()
            self._take_snapshot(step - 1)
            self._ledger("checkpoint", time.monotonic() - t_ck)
        while step <= total_steps:
            t_iter = time.monotonic()
            batch = self._corrupt(self.cursor.batch(step))
            # pod-scale worker-death fault: a no-arg ``kill`` scheduled
            # on ``train.kill_rank.<rank>`` SIGKILLs exactly this rank
            # at its N-th executed step — other ranks share the spec
            # but their suffix never matches
            _chaos.inject(f"train.kill_rank.{self.rank}")
            t0 = time.monotonic()
            out = self.step_fn(batch)
            loss, gn, lfin, gfin, fp = self._parse_result(out)
            # timed THROUGH the parse: jax dispatch returns immediately,
            # so the host read inside _parse_result is where the step's
            # device compute is actually waited out — timing only the
            # dispatch would hand the straggler detector pure noise
            dt = time.monotonic() - t0
            anomaly = self.detector.observe(
                loss, gn, loss_finite=lfin, grad_finite=gfin)
            if anomaly is None and self.telemetry is not None:
                fp = fp if fp is not None else (
                    grad_fingerprint(gn) if gn is not None
                    else grad_fingerprint(loss))
                self.telemetry.publish(step, dt, fp)
                if step % self.telemetry_interval == 0:
                    verdict = self.telemetry.check(step, fp)
                    if verdict.sdc and self.telemetry.rank in \
                            verdict.sdc_suspects:
                        # recompute-or-rollback is the SUSPECT's remedy;
                        # consensus holders keep going (their state was
                        # never corrupted, and rolling everyone back
                        # would double the blast radius of one bad HBM
                        # bit)
                        anomaly = Anomaly("sdc", verdict.detail)
                        self.detector._flag(anomaly)
            if anomaly is not None:
                t_roll = time.monotonic()
                step = self._handle_anomaly(step, anomaly)
                now = time.monotonic()
                # the anomalous step's compute was wasted work — it
                # rides the rollback bucket along with the restore
                self._ledger("rollback", dt + (now - t_roll))
                self._ledger("stall",
                             max(0.0, (now - t_iter) - dt
                                 - (now - t_roll)))
                continue
            # healthy step: let the tiers advance
            self.last_loss = loss
            self._step = step
            self._retries_at.pop(step, None)
            t_ck = time.monotonic()
            if self.auto_checkpoint is not None:
                self.auto_checkpoint.step(step)
            if step % self.snapshot_interval == 0:
                self._take_snapshot(step)
            now = time.monotonic()
            ck = now - t_ck
            if step > self._goodput_high_water:
                self._goodput_high_water = step
                self._ledger("productive", dt)
            else:
                # a REPLAYED step: healthy this time, but the run only
                # needs it because an anomaly threw the first execution
                # away — rollback cost, not progress
                self._ledger("rollback", dt)
            self._ledger("checkpoint", ck)
            self._ledger("stall", max(0.0, (now - t_iter) - dt - ck))
            step += 1
        t_ck = time.monotonic()
        if self.auto_checkpoint is not None:
            self.auto_checkpoint.wait()
        if self.peer is not None:
            try:
                self.peer.wait()
            except RuntimeError as e:
                self._note("peer_error", str(e))
        self._ledger("checkpoint", time.monotonic() - t_ck)
        return self.report()

    # -- goodput ledger (ISSUE 14) ---------------------------------------
    def _ledger(self, bucket: str, seconds: float) -> None:
        self._wall[bucket] += seconds
        self._wall_gauges[bucket].set(self._wall[bucket])
        self._g_goodput.set(self.goodput_frac())
        if self.telemetry is not None:
            self._g_stragglers.set(
                float(len(self.telemetry.stragglers())))

    def goodput_frac(self) -> Optional[float]:
        """Fraction of attributed run() wall time spent on healthy
        first-time steps. None before any wall time accrues."""
        total = sum(self._wall.values())
        return self._wall["productive"] / total if total > 0 else None

    def _handle_anomaly(self, step: int, anomaly: Anomaly) -> int:
        """Roll back; returns the step to run next."""
        self.anomalies.append((step, str(anomaly)))
        self.rollbacks += 1
        self._c_rollbacks.inc()
        if self.rollbacks > self.rollback_budget:
            msg = (f"rollback budget exhausted ({self.rollbacks} > "
                   f"{self.rollback_budget}) at step {step}: {anomaly}")
            self._note("gave_up", msg)
            if self.escalate == "exit":
                sys.stderr.write(f"TrainingSupervisor: {msg}\n"
                                 "TrainingSupervisor: exiting crash-only "
                                 f"({TRAINFAULT_EXIT_CODE}) for relaunch\n")
                sys.stderr.flush()
                os._exit(TRAINFAULT_EXIT_CODE)
            raise TrainingGaveUp(msg)
        retries = self._retries_at.get(step, 0) + 1
        self._retries_at[step] = retries
        snap_step, state = self._newest_snapshot()
        # the offending data index under the CURRENT quarantine mapping,
        # resolved before the restore rebinds the cursor state
        bad_index = self.cursor.index(step)
        self._restore(state)
        if retries > self.max_rollback_retries:
            # deterministic replay reproduced the anomaly at the same
            # step each time: the BATCH is the trigger — quarantine its
            # data index (AFTER the restore: quarantines are monotonic
            # knowledge about the data, not rolled-back model state),
            # and the replay draws clean data there
            self.cursor.quarantine(bad_index)
            self._retries_at.pop(step, None)
            self._note("quarantine",
                       f"step {step}: batch index {bad_index} after "
                       f"{retries - 1} rollback retries ({anomaly})")
        self._note("rollback",
                   f"step {step} anomaly ({anomaly}) -> restored "
                   f"snapshot of step {snap_step}")
        return snap_step + 1

    def _note(self, kind: str, detail: str):
        self.events.append((kind, detail))
        _obs.instant(f"train_{kind}", tid="train", detail=detail)
        if kind in ("rollback", "quarantine", "gave_up", "peer_error",
                    "resume_peer_failed"):
            sys.stderr.write(f"TrainingSupervisor: {kind}: {detail}\n")

    # -- surfaces --------------------------------------------------------
    def report(self) -> dict:
        return {
            "final_step": self._step,
            "final_loss": self.last_loss,
            "rollbacks": self.rollbacks,
            "anomalies": list(self.anomalies),
            "quarantined": sorted(self.cursor.quarantined),
        }

    def health(self) -> dict:
        """Structured snapshot (the ServingSupervisor.health() analogue)
        for probes/tests: progress, rollback ledger, detector stats,
        per-tier freshness, telemetry verdicts, and — when an elastic
        manager is attached — the membership self-report plus the
        world-size/re-mesh/reshard gauges. Wrapped in the shared
        :func:`obs.health_envelope` (HEALTH_COMMON_KEYS-conformant,
        like every other health() surface)."""
        tiers = {
            "ram": self._snapshots[-1][0] if self._snapshots else None,
            "peer": (self.peer.last_published_step
                     if self.peer is not None else None),
            "disk": (self.auto_checkpoint.latest_step()
                     if self.auto_checkpoint is not None else None),
        }
        tele = None
        if self.telemetry is not None:
            v = self.telemetry.last_verdict
            tele = {
                "stragglers": self.telemetry.stragglers(),
                "sdc_suspects": (v.sdc_suspects if v is not None else []),
                "published": self.telemetry.n_published,
            }
        elastic = None
        if self.elastic is not None:
            elastic = self.elastic.health()
            self._g_world.set(float(elastic.get("world_size") or 0))
            self._g_remesh.set(float(elastic.get("remesh_events") or 0))
        return _obs.health_envelope("training", {
            "step": self._step,
            "last_loss": self.last_loss,
            "rank": self.rank,
            "rollbacks": self.rollbacks,
            "rollback_budget": self.rollback_budget,
            "quarantined": sorted(self.cursor.quarantined),
            "detector": self.detector.snapshot(),
            "tiers": tiers,
            "telemetry": tele,
            "elastic": elastic,
            "reshard_resumes": self.reshard_resumes,
            "scaler_skips": (self.scaler.n_skipped_steps
                             if self.scaler is not None else None),
            "wall_seconds": {b: round(v, 6)
                             for b, v in sorted(self._wall.items())},
            "goodput_frac": self.goodput_frac(),
            "events": list(self.events[-20:]),
        })
