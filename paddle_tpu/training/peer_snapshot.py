"""Peer-replicated in-memory checkpoints over a KV store.

Gemini/CheckFreq shape: every ``snapshot_interval`` steps each rank
mirrors its (sharded) training state to a PEER rank's host RAM, so a
killed-and-relaunched rank restores at memory speed without touching
disk. Here "a peer's host RAM" is mediated by the shared KV store
(``distributed/store.py``): in TCP mode the payload physically lives in
the store server's RAM on another host; the ring assignment
``peer = (rank + 1) % world`` is recorded in the key namespace so a
future direct-transport backend can place the bytes on that exact host
without changing the protocol.

Publish protocol (crash-only, torn-publish-proof):

1. ``<tag>/snap/<rank>/data/<step>``  — an INNER whole-payload CRC32
   envelope around the serialized payload, shipped via ``put_bytes``
   (which adds the length-prefixed + CRC32 frame). Two CRCs on
   purpose, like the disagg handoff's part-frames + whole-payload
   commit: the outer frame catches corruption in the store/transport,
   the inner envelope — computed BEFORE the ``ckpt.peer`` chaos
   site — catches corruption on the way in, so a bit flip anywhere
   surfaces as a verification failure at fetch, never as garbage
   state;
2. ``<tag>/snap/<rank>/meta``         — JSON ``{step, payload_bytes,
   nonce}``, written LAST. A writer killed between (1) and (2) leaves
   the previous meta pointing at the previous (still present) data key
   — the reader can never observe a half-published snapshot;
3. the superseded data key is deleted after the meta flips.

``fetch()`` is verified-or-nothing: a missing/corrupt/short payload
returns the next-older intact publish (or None), so the recovery tier
comparison in the supervisor only ever sees restorable snapshots.

Chaos site ``ckpt.peer`` wraps every publish leg: ``corrupt`` flips a
payload bit (the CRC framing must catch it at fetch), ``drop`` loses
that leg (recovery falls back to an older tier).

Every blocking store leg threads a ``Deadline`` (DDL001/DDL002
discipline) — a slow store can delay a snapshot, never wedge training.
"""
from __future__ import annotations

import binascii
import json
import os
import struct
import threading
import time
from typing import Optional, Tuple

from ..distributed.store import CorruptBlobError, KVStore
from ..testing import chaos as _chaos
from ..utils.retries import Deadline, RetryPolicy

__all__ = ["PeerReplicator"]


class PeerReplicator:
    """Async snapshot mirroring for one rank.

    Parameters: ``store`` — any :class:`KVStore`; ``rank``/``world_size``
    — this rank's slot in the ring (``peer`` = the rank whose RAM holds
    our replica); ``tag`` — key namespace (one per job, so relaunched
    jobs don't read a previous job's snapshots); ``deadline_s`` — total
    budget per publish/fetch; ``keep`` — how many superseded data keys
    to retain (older ones are deleted; >=1 keeps a fallback for a
    corrupt newest payload).
    """

    def __init__(self, store: KVStore, rank: int, world_size: int, *,
                 tag: str = "trainsnap", deadline_s: float = 30.0,
                 keep: int = 1, retry: Optional[RetryPolicy] = None):
        if world_size < 1:
            raise ValueError("world_size must be >= 1")
        if not 0 <= rank < world_size:
            raise ValueError(f"rank {rank} outside [0, {world_size})")
        self.store = store
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.tag = tag
        self.deadline_s = float(deadline_s)
        self.keep = max(1, int(keep))
        self.retry = retry if retry is not None else RetryPolicy(
            max_attempts=4, base_delay=0.05, max_delay=1.0,
            transient=(OSError, ValueError))
        # per-incarnation nonce: a relaunched rank's publishes must be
        # distinguishable from its previous life's (meta carries it)
        self._nonce = f"{os.getpid()}-{int(time.time() * 1000) & 0xFFFFFF}"
        self._worker: Optional[threading.Thread] = None
        self._publish_error: Optional[BaseException] = None
        self.n_published = 0
        self.last_published_step: Optional[int] = None

    # -- key scheme ------------------------------------------------------
    @property
    def peer(self) -> int:
        """The rank whose host RAM holds THIS rank's replica."""
        return (self.rank + 1) % self.world_size

    def _meta_key(self, rank: Optional[int] = None) -> str:
        r = self.rank if rank is None else rank
        return f"{self.tag}/snap/{r}/meta"

    def _data_key(self, step: int, rank: Optional[int] = None) -> str:
        r = self.rank if rank is None else rank
        return f"{self.tag}/snap/{r}/data/{step}"

    # -- publish ---------------------------------------------------------
    def publish(self, step: int, payload, *, block: bool = False):
        """Mirror the serialized snapshot for ``step`` to the peer
        tier. ``payload`` is bytes OR a zero-arg callable returning
        bytes — the callable form defers serialization (device_get +
        pickle) onto the worker thread, so the train thread only hands
        over immutable references. Async by default; a previous
        in-flight publish is drained first (one at a time, newest
        wins). ``block=True`` publishes inline (tests, final snapshot
        before exit)."""
        self.drain()  # drain + surface a previous publish's error
        if block:
            self._publish(int(step), payload)
            self._raise_publish_error()
            return
        self._worker = threading.Thread(
            target=self._publish, args=(int(step), payload),
            name="paddle_tpu_peer_snapshot", daemon=True)
        self._worker.start()

    def _publish(self, step: int, payload):
        dl = Deadline(self.deadline_s)
        try:
            if callable(payload):
                payload = payload()
            # inner whole-payload CRC, sealed BEFORE the chaos site:
            # corruption between here and the store is provable at fetch
            envelope = struct.pack(
                "!I", binascii.crc32(payload) & 0xFFFFFFFF) + payload
            data = _chaos.inject_bytes("ckpt.peer", envelope)
            if data is None:
                return  # dropped leg: this interval's mirror is lost
            self.retry.call(
                lambda: self.store.put_bytes(self._data_key(step), data),
                deadline=dl, describe="peer snapshot data put")
            if not _chaos.inject("ckpt.peer"):
                return  # dropped meta: previous publish stays current
            meta = json.dumps({"step": step, "payload_bytes": len(payload),
                               "nonce": self._nonce})
            self.retry.call(
                lambda: self.store.set(self._meta_key(), meta),
                deadline=dl, describe="peer snapshot meta put")
            self.n_published += 1
            self.last_published_step = step
            self._prune(step, dl)
        except BaseException as e:  # noqa: BLE001 — reported on next publish
            self._publish_error = e

    def _prune(self, newest_step: int, dl: Deadline):
        """Delete superseded data keys beyond ``keep`` — the peer's RAM
        holds a bounded number of replicas, not the run's history."""
        try:
            prefix = f"{self.tag}/snap/{self.rank}/data/"
            steps = sorted(
                int(k[len(prefix):]) for k in self.store.keys(prefix)
                if k[len(prefix):].isdigit())
            live = [s for s in steps if s <= newest_step][:-1 - self.keep]
            for s in live:
                dl.check("peer snapshot prune")
                self.store.delete(self._data_key(s))
        except (OSError, ValueError, RuntimeError, TimeoutError):
            pass  # pruning is hygiene; never fail a publish over it

    def drain(self):
        """Drain the in-flight publish; raises if it failed (a final
        pre-exit mirror failing silently would strand the relaunch on a
        stale tier with no indication). The join is BOUNDED by the
        publish deadline (+ scheduling slack): every store leg inside
        the worker runs under ``Deadline(deadline_s)``, so a join that
        outlives it means a wedge worth surfacing, not waiting on."""
        if self._worker is not None:
            self._worker.join(self.deadline_s + 5.0)
            alive, self._worker = self._worker.is_alive(), None
            if alive:
                raise RuntimeError(
                    "peer snapshot publish wedged past its deadline "
                    f"({self.deadline_s}s) — abandoning the worker")
        self._raise_publish_error()

    # API symmetry with AutoCheckpoint.wait (same drain-the-async-save
    # contract); assignment, not a def, so callers can use either name
    wait = drain

    def _raise_publish_error(self):
        if self._publish_error is not None:
            err, self._publish_error = self._publish_error, None
            raise RuntimeError(f"peer snapshot publish failed: {err!r}") \
                from err

    # -- fetch -----------------------------------------------------------
    def latest_step(self, rank: Optional[int] = None) -> Optional[int]:
        """Step of the newest PUBLISHED snapshot for ``rank`` (default:
        self — the relaunched-rank read), or None. Reads only the meta
        record; the payload is verified at :meth:`fetch`."""
        dl = Deadline(self.deadline_s)
        try:
            raw = self.retry.call(
                lambda: self.store.get(self._meta_key(rank)),
                deadline=dl, describe="peer snapshot meta get")
        except (OSError, ValueError, RuntimeError, TimeoutError):
            return None
        if not raw:
            return None
        try:
            return int(json.loads(raw)["step"])
        except (ValueError, KeyError, TypeError):
            return None

    def ranks(self) -> list:
        """Every rank with a committed meta under this tag — the SAVED
        world, which after an elastic shrink can be LARGER than the
        current ``world_size`` (a relaunched smaller fleet still needs
        all the old ranks' shard payloads to assemble full state)."""
        out = set()
        try:
            for k in self.store.keys(f"{self.tag}/snap/"):
                parts = k.split("/")
                if parts and parts[-1] == "meta" and parts[-2].isdigit():
                    out.add(int(parts[-2]))
        except (OSError, ValueError, RuntimeError, TimeoutError):
            return []
        return sorted(out)

    def fetch_at(self, rank: int, step: int) -> Optional[bytes]:
        """The VERIFIED payload for EXACTLY ``step`` of ``rank``, or
        None. The sharded restore gathers a consistent cut — every
        rank at the same step — so unlike :meth:`fetch` there is no
        older-tier fallback: a missing/corrupt payload at the cut step
        means this cut is unusable, full stop."""
        dl = Deadline(self.deadline_s)
        meta_step = self.latest_step(rank)
        if meta_step is None or meta_step < step:
            return None  # not committed: a torn or missing publish
        try:
            envelope = self.retry.call(
                lambda: self.store.get_bytes(self._data_key(step, rank)),
                deadline=dl, describe="peer snapshot data get")
        except (CorruptBlobError, OSError, ValueError, RuntimeError,
                TimeoutError):
            return None
        if envelope is None or len(envelope) < 4:
            return None
        (want,) = struct.unpack("!I", envelope[:4])
        payload = envelope[4:]
        if binascii.crc32(payload) & 0xFFFFFFFF != want:
            return None
        return payload

    def fetch(self, rank: Optional[int] = None
              ) -> Optional[Tuple[int, bytes]]:
        """The newest VERIFIED (step, payload) for ``rank`` (default:
        self), or None. A corrupt/short/missing newest payload falls
        back to the next-older retained data key — verified-or-nothing,
        so the caller can trust any returned bytes survived the CRC
        frame and the meta's length record."""
        dl = Deadline(self.deadline_s)
        r = self.rank if rank is None else rank
        meta_step = self.latest_step(r)
        prefix = f"{self.tag}/snap/{r}/data/"
        try:
            steps = sorted(
                (int(k[len(prefix):]) for k in self.store.keys(prefix)
                 if k[len(prefix):].isdigit()), reverse=True)
        except (OSError, ValueError, RuntimeError):
            return None
        # only steps the meta has COMMITTED are restorable (a data key
        # newer than meta.step is a torn publish mid-flight)
        steps = [s for s in steps if meta_step is not None
                 and s <= meta_step]
        for s in steps:
            dl.check("peer snapshot fetch")
            try:
                envelope = self.retry.call(
                    lambda key=self._data_key(s, r): self.store.get_bytes(
                        key),
                    deadline=dl, describe="peer snapshot data get")
            except CorruptBlobError:
                continue  # outer frame proven corrupt: try next-older
            except (OSError, ValueError, RuntimeError, TimeoutError):
                return None
            if envelope is None:
                continue
            if len(envelope) < 4:
                continue
            (want,) = struct.unpack("!I", envelope[:4])
            payload = envelope[4:]
            if binascii.crc32(payload) & 0xFFFFFFFF != want:
                continue  # inner envelope proven corrupt: next-older
            return s, payload
        return None
