"""Fault-tolerant training: the supervision layer over a step function.

The serving side is crash-only end to end (ServingSupervisor, overload
control, journaled requeue, disagg handoff); this package gives the
TRAINING loop the same treatment — MegaScale-style anomaly detection
with per-rank diagnosis, Gemini/CheckFreq-style peer-replicated
in-memory checkpoints so recovery is RAM-speed rather than disk-speed,
and cross-rank straggler / silent-data-corruption detection:

- :class:`TrainingSupervisor` (``supervisor.py``) — wraps a step
  function; detects anomalies, rolls back to the last good snapshot,
  quarantines poison batches, escalates crash-only past a rollback
  budget, and keeps the two-tier (peer RAM / disk) checkpoint fabric
  fed.
- :class:`AnomalyDetector` (``anomaly.py``) — finite checks plus
  EWMA+MAD spike gates over loss and gradient norm; the AMP
  GradScaler's found_inf skips feed the same detector.
- :class:`PeerReplicator` (``peer_snapshot.py``) — async CRC-framed
  snapshot mirroring to a peer rank's host RAM over any KVStore.
- :class:`TrainTelemetry` (``telemetry.py``) — per-step (step-time,
  gradient-fingerprint) exchange; dp-replica fingerprint divergence
  flags suspected SDC, persistent step-time outliers name the
  straggling rank in the CommWatchdog hang dump.
- :class:`DataCursor` — deterministic step→batch mapping with batch
  quarantine and a checkpointable position.
"""
from .anomaly import (  # noqa: F401
    Anomaly,
    AnomalyDetector,
    pack_health,
    unpack_health,
)
from .peer_snapshot import PeerReplicator  # noqa: F401
from .supervisor import (  # noqa: F401
    DataCursor,
    TrainingGaveUp,
    TrainingSupervisor,
    TRAINFAULT_EXIT_CODE,
)
from .telemetry import TelemetryVerdict, TrainTelemetry  # noqa: F401

__all__ = [
    "Anomaly",
    "AnomalyDetector",
    "DataCursor",
    "PeerReplicator",
    "TelemetryVerdict",
    "TrainTelemetry",
    "TrainingGaveUp",
    "TrainingSupervisor",
    "TRAINFAULT_EXIT_CODE",
    "pack_health",
    "unpack_health",
]
