"""Anomaly detection for training steps — the health word and gates.

MegaScale-style in-band health checking: every step folds a CHEAP
on-device health word into its outputs (:func:`pack_health` — loss,
global grad norm, and their finite flags in one 4-element f32 array, so
the host pays exactly one tiny D2H per step), and a host-side
:class:`AnomalyDetector` triages it:

- **finite gates** — a non-finite loss or grad norm is an anomaly
  immediately (no statistics needed);
- **spike gates** — an EWMA tracks the running level of the loss (and
  grad norm) and a second EWMA tracks the mean absolute deviation
  around it (the MAD analogue that, unlike a variance EWMA, is not
  itself destroyed by the spike it is measuring). A value more than
  ``spike_k`` deviations ABOVE the level after ``warmup_steps``
  observations trips the gate — upward only, because a loss falling
  faster than usual is called training, not an anomaly;
- **scaler-skip gate** — the AMP GradScaler's found_inf skips are
  individually benign (that is the scaler working) but a RUN of them
  means the loss scale can no longer find a representable range:
  ``max_consecutive_scaler_skips`` in a row is an anomaly.

Anomalous values are NOT folded into the running statistics — a NaN
would destroy the EWMA it is being compared against, and a spike would
raise the level that must detect its own repetition.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

__all__ = ["Anomaly", "AnomalyDetector", "pack_health", "unpack_health"]


def pack_health(loss, grad_norm=None):
    """Fold a step's health into ONE small device array (inside jit):
    ``[loss, grad_norm, loss_finite, grad_finite, has_grad]`` as f32.
    Returning this from a compiled step costs a single 20-byte
    transfer; the supervisor unpacks it host-side with
    :func:`unpack_health`. The explicit ``has_grad`` lane keeps a
    loss-only pack distinguishable from a genuine zero gradient norm —
    without it the supervisor would fingerprint the constant 0.0 and
    silently disable SDC detection."""
    import jax.numpy as jnp

    loss = jnp.asarray(loss, jnp.float32).reshape(())
    if grad_norm is None:
        gn = jnp.asarray(0.0, jnp.float32)
        gfin = jnp.asarray(1.0, jnp.float32)
        has = jnp.asarray(0.0, jnp.float32)
    else:
        gn = jnp.asarray(grad_norm, jnp.float32).reshape(())
        gfin = jnp.isfinite(gn).astype(jnp.float32)
        has = jnp.asarray(1.0, jnp.float32)
    return jnp.stack(
        [loss, gn, jnp.isfinite(loss).astype(jnp.float32), gfin, has])


def unpack_health(word):
    """Host-side inverse of :func:`pack_health`:
    ``(loss, grad_norm, loss_finite, grad_finite)`` as Python scalars.
    The finite FLAGS are authoritative (computed on device before the
    f32 round trip); ``grad_norm`` is None when it was not packed
    (``has_grad`` lane 0; 4-lane words from older callers keep the
    packed value)."""
    import numpy as np

    arr = np.asarray(word, np.float32).reshape(-1)
    loss = float(arr[0])
    gn = float(arr[1]) if len(arr) > 1 else None
    lfin = bool(arr[2] >= 0.5) if len(arr) > 2 else math.isfinite(loss)
    gfin = bool(arr[3] >= 0.5) if len(arr) > 3 else True
    if len(arr) > 4 and arr[4] < 0.5:
        gn = None
    return loss, gn, lfin, gfin


@dataclass(frozen=True)
class Anomaly:
    """One detected anomaly: ``kind`` ∈ {loss_nonfinite, grad_nonfinite,
    loss_spike, grad_spike, scaler_skips, sdc} and a human detail."""

    kind: str
    detail: str = ""

    def __str__(self):
        return f"{self.kind}: {self.detail}" if self.detail else self.kind


class _SpikeGate:
    """EWMA level + EWMA absolute-deviation gate for one scalar.

    Two guards against the false positives a descending training loss
    manufactures: (a) the warmup phase averages uniformly (effective
    alpha = max(alpha, 1/n)) so the deviation scale reflects the whole
    early sample, not the first point; (b) a spike must ALSO clear a
    relative floor — ``min_rel`` × the level above the mean — because
    once the loss plateaus the MAD shrinks toward the noise floor and
    a benign uptick would otherwise read as many "deviations". A real
    anomaly spike (corrupted batch, diverging optimizer) is a multiple
    of the level, not a wiggle."""

    def __init__(self, alpha: float, spike_k: float, warmup: int,
                 min_rel: float):
        self.alpha = float(alpha)
        self.spike_k = float(spike_k)
        self.warmup = int(warmup)
        self.min_rel = float(min_rel)
        self.mean: Optional[float] = None
        self.mad: float = 0.0
        self.n = 0

    def observe(self, x: float) -> Optional[float]:
        """Returns the deviation ratio (|x-mean|/mad) when ``x`` spikes,
        else None after folding ``x`` into the statistics."""
        if self.mean is not None and self.n >= self.warmup:
            scale = max(self.mad, 1e-12 * max(abs(self.mean), 1.0), 1e-30)
            dev = (x - self.mean) / scale
            if (dev > self.spike_k
                    and x - self.mean > self.min_rel * max(
                        abs(self.mean), 1e-30)):
                return dev  # spike: NOT folded into the stats
        a = max(self.alpha, 1.0 / (self.n + 1))  # uniform during warmup
        if self.mean is None:
            self.mean = x
        else:
            self.mean += a * (x - self.mean)
            self.mad += a * (abs(x - self.mean) - self.mad)
        self.n += 1
        return None

    def snapshot(self) -> dict:
        return {"mean": self.mean, "mad": self.mad, "n": self.n}


class AnomalyDetector:
    """Host-side triage of per-step health words. Returns an
    :class:`Anomaly` (or None) per :meth:`observe`; never raises."""

    def __init__(self, *, ewma_alpha: float = 0.1, spike_k: float = 8.0,
                 grad_spike_k: Optional[float] = None, warmup_steps: int = 8,
                 min_rel_spike: float = 1.0,
                 max_consecutive_scaler_skips: int = 4):
        self.loss_gate = _SpikeGate(ewma_alpha, spike_k, warmup_steps,
                                    min_rel_spike)
        self.grad_gate = _SpikeGate(
            ewma_alpha,
            spike_k if grad_spike_k is None else grad_spike_k,
            warmup_steps, min_rel_spike)
        self.max_consecutive_scaler_skips = int(max_consecutive_scaler_skips)
        self._consecutive_skips = 0
        self.n_anomalies = 0
        self.last_anomaly: Optional[Anomaly] = None

    # -- scaler feed ----------------------------------------------------
    def notify_scaler_skip(self, step_ix: int) -> None:
        """Wired to ``GradScaler(on_skip=...)``: each found_inf skip
        bumps the consecutive counter :meth:`observe` gates on (a
        healthy observed step resets it)."""
        self._consecutive_skips += 1

    # -- main gate ------------------------------------------------------
    def observe(self, loss: float, grad_norm: Optional[float] = None,
                loss_finite: Optional[bool] = None,
                grad_finite: Optional[bool] = None) -> Optional[Anomaly]:
        if self._consecutive_skips > self.max_consecutive_scaler_skips:
            n = self._consecutive_skips
            # reset ON flag: the supervisor responds with a rollback
            # (restored scaler state, replayed steps) — a latched
            # counter would re-flag every replayed step and burn the
            # whole rollback budget on ONE transient skip-run
            self._consecutive_skips = 0
            return self._flag(Anomaly(
                "scaler_skips",
                f"{n} consecutive GradScaler found_inf skips "
                f"(> {self.max_consecutive_scaler_skips})"))
        if loss_finite is False or not math.isfinite(loss):
            return self._flag(Anomaly("loss_nonfinite", f"loss={loss}"))
        if grad_norm is not None and (
                grad_finite is False or not math.isfinite(grad_norm)):
            return self._flag(Anomaly(
                "grad_nonfinite", f"grad_norm={grad_norm}"))
        dev = self.loss_gate.observe(float(loss))
        if dev is not None:
            return self._flag(Anomaly(
                "loss_spike",
                f"loss={loss:.6g} is {dev:.1f} deviations above the "
                f"EWMA level {self.loss_gate.mean:.6g}"))
        if grad_norm is not None:
            dev = self.grad_gate.observe(float(grad_norm))
            if dev is not None:
                return self._flag(Anomaly(
                    "grad_spike",
                    f"grad_norm={grad_norm:.6g} is {dev:.1f} deviations "
                    f"above the EWMA level {self.grad_gate.mean:.6g}"))
        self._consecutive_skips = 0  # an observed healthy step
        return None

    def _flag(self, anomaly: Anomaly) -> Anomaly:
        self.n_anomalies += 1
        self.last_anomaly = anomaly
        return anomaly

    def snapshot(self) -> dict:
        return {
            "loss": self.loss_gate.snapshot(),
            "grad": self.grad_gate.snapshot(),
            "consecutive_scaler_skips": self._consecutive_skips,
            "n_anomalies": self.n_anomalies,
            "last_anomaly": (None if self.last_anomaly is None
                             else str(self.last_anomaly)),
        }
