"""paddle_tpu.hub — hubconf-based model loading (ref: python/paddle/
hub.py — list/help/load over github|gitee|local sources).

This environment has no network egress, so the remote sources raise
with guidance; the ``local`` source (a directory containing
``hubconf.py``) is fully functional — same entrypoint contract as the
reference: callables not prefixed with '_' are models, ``dependencies``
is an optional requirements list.
"""
from __future__ import annotations

import importlib.util
import os
import sys
from typing import List, Optional

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {_HUBCONF} found in {repo_dir}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    module = importlib.util.module_from_spec(spec)
    sys.modules["paddle_tpu_hubconf"] = module
    spec.loader.exec_module(module)
    return module


def _check_source(source: str):
    if source not in ("github", "gitee", "local"):
        raise ValueError("source must be github/gitee/local")
    if source != "local":
        raise RuntimeError(
            f"hub source {source!r} needs network egress, which this "
            "environment does not have; clone the repo and use "
            "source='local' with its directory path"
        )


def list(repo_dir: str, source: str = "github", force_reload: bool = False):  # noqa: A001
    """Entrypoint names exposed by the repo's hubconf (ref: hub.py list)."""
    _check_source(source)
    module = _load_hubconf(repo_dir)
    return [
        name
        for name, obj in vars(module).items()
        if callable(obj) and not name.startswith("_")
    ]


def help(repo_dir: str, model: str, source: str = "github",  # noqa: A001
         force_reload: bool = False):
    """Docstring of an entrypoint (ref: hub.py help)."""
    _check_source(source)
    module = _load_hubconf(repo_dir)
    fn = getattr(module, model, None)
    if fn is None or model.startswith("_"):
        raise ValueError(f"model {model!r} not found in {repo_dir}/{_HUBCONF}")
    return fn.__doc__


def load(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False, **kwargs):
    """Instantiate an entrypoint (ref: hub.py load)."""
    _check_source(source)
    module = _load_hubconf(repo_dir)
    fn = getattr(module, model, None)
    if fn is None or model.startswith("_"):
        raise ValueError(f"model {model!r} not found in {repo_dir}/{_HUBCONF}")
    return fn(**kwargs)
