"""paddle_tpu.metric — streaming metrics.

ref: python/paddle/metric/metrics.py (Metric base :46, Accuracy :175,
Precision :310, Recall :407, Auc :504). Same streaming contract:
``update`` consumes per-batch results, ``accumulate`` reports the
running value, ``reset`` clears state. Computation is host-side numpy —
metrics are consumed between steps, so keeping them off-device avoids
blocking the TPU pipeline on tiny reductions.
"""
from __future__ import annotations

import abc
from typing import List, Sequence, Union

import numpy as np

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc"]


def _to_np(x):
    from ..base.tensor import Tensor

    if isinstance(x, Tensor):
        return np.asarray(x.numpy())
    return np.asarray(x)


class Metric(abc.ABC):
    """Streaming metric base (ref: metrics.py:46)."""

    def __init__(self):
        pass

    @abc.abstractmethod
    def reset(self):
        raise NotImplementedError

    @abc.abstractmethod
    def update(self, *args):
        raise NotImplementedError

    @abc.abstractmethod
    def accumulate(self):
        raise NotImplementedError

    @abc.abstractmethod
    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        """Optional pre-processing done on device outputs; default
        passthrough (ref: metrics.py Metric.compute)."""
        return args


class Accuracy(Metric):
    """Top-k accuracy (ref: metrics.py:175)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred = _to_np(pred)
        label = _to_np(label)
        idx = np.argsort(-pred, axis=-1)[..., : self.maxk]
        if label.ndim == pred.ndim:
            if label.shape[-1] == 1:  # (N, 1) class-index column
                label = label[..., 0]
            else:  # one-hot / soft labels
                label = np.argmax(label, axis=-1)
        correct = idx == label[..., None]
        return correct

    def update(self, correct, *args):
        correct = _to_np(correct)
        num_samples = int(np.prod(correct.shape[:-1]))
        accs = []
        for k in self.topk:
            num_corrects = int(correct[..., :k].sum())
            accs.append(float(num_corrects) / max(num_samples, 1))
            self.total[self.topk.index(k)] += num_corrects
            self.count[self.topk.index(k)] += num_samples
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        out = [t / c if c > 0 else 0.0 for t, c in zip(self.total, self.count)]
        return out[0] if len(out) == 1 else out

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """Binary precision (ref: metrics.py:310)."""

    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds).flatten()
        labels = _to_np(labels).flatten()
        pred_pos = np.rint(preds).astype(np.int64) == 1
        self.tp += int(np.sum(pred_pos & (labels == 1)))
        self.fp += int(np.sum(pred_pos & (labels != 1)))

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall (ref: metrics.py:407)."""

    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds).flatten()
        labels = _to_np(labels).flatten()
        pred_pos = np.rint(preds).astype(np.int64) == 1
        self.tp += int(np.sum(pred_pos & (labels == 1)))
        self.fn += int(np.sum(~pred_pos & (labels == 1)))

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via thresholded confusion histogram (ref: metrics.py:504 —
    same num_thresholds bucketing algorithm)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self._curve = curve
        self._num_thresholds = int(num_thresholds)
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds)
        labels = _to_np(labels).flatten()
        if preds.ndim == 2 and preds.shape[1] >= 2:
            pos_prob = preds[:, 1]
        else:
            pos_prob = preds.flatten()
        buckets = np.minimum(
            (pos_prob * self._num_thresholds).astype(np.int64),
            self._num_thresholds,
        )
        pos = labels.astype(bool)
        self._stat_pos += np.bincount(
            buckets[pos], minlength=self._num_thresholds + 1
        )
        self._stat_neg += np.bincount(
            buckets[~pos], minlength=self._num_thresholds + 1
        )

    def reset(self):
        self._stat_pos = np.zeros(self._num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self._num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self._num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_neg - tot_neg) * (tot_pos + new_pos) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        denom = tot_pos * tot_neg
        return float(auc / denom) if denom else 0.0

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (ref: python/paddle/metric/metrics.py:
    accuracy functional)."""
    from .. import to_tensor

    pred = _to_np(input)
    lab = _to_np(label)
    idx = np.argsort(-pred, axis=-1)[..., :k]
    if lab.ndim == pred.ndim and lab.shape[-1] == 1:
        lab = lab[..., 0]
    hit = (idx == lab[..., None]).any(axis=-1)
    return to_tensor(np.asarray(hit.mean(), np.float32))
