"""paddle.audio.datasets (ref: python/paddle/audio/datasets/) — TESS and
ESC50. The download mirrors are unreachable (no egress); pass
archive_path= to a pre-downloaded copy, parsed with the reference's
layout (label from the directory / filename field)."""
from __future__ import annotations

import os

from ..io import Dataset

__all__ = ["TESS", "ESC50"]


class TESS(Dataset):
    """Toronto emotional speech set: <speaker>_<word>_<emotion>.wav
    files; label = emotion index (ref: datasets/tess.py)."""

    emotions = ["angry", "disgust", "fear", "happy", "neutral", "ps", "sad"]

    def __init__(self, mode: str = "train", n_folds: int = 5, split: int = 1,
                 feat_type: str = "raw", archive_path: str = None, **kwargs):
        if archive_path is None or not os.path.isdir(archive_path):
            raise RuntimeError(
                "TESS: automatic download is unavailable (no egress); pass "
                "archive_path=<dir containing the extracted TESS wav files>"
            )
        files = []
        for dirpath, _, names in sorted(os.walk(archive_path)):
            for f in sorted(names):
                if f.lower().endswith(".wav"):
                    emotion = f.rsplit(".", 1)[0].split("_")[-1].lower()
                    if emotion in self.emotions:
                        files.append((os.path.join(dirpath, f), self.emotions.index(emotion)))
        fold = lambda i: i % n_folds + 1
        self.files = [
            (p, y) for i, (p, y) in enumerate(files)
            if (fold(i) != split if mode == "train" else fold(i) == split)
        ]

    def __getitem__(self, idx):
        from . import load

        path, label = self.files[idx]
        wav, _sr = load(path)
        return wav, label

    def __len__(self):
        return len(self.files)


class ESC50(Dataset):
    """ESC-50 environmental sounds: <fold>-<id>-<take>-<target>.wav
    (ref: datasets/esc50.py)."""

    def __init__(self, mode: str = "train", split: int = 1,
                 feat_type: str = "raw", archive_path: str = None, **kwargs):
        if archive_path is None or not os.path.isdir(archive_path):
            raise RuntimeError(
                "ESC50: automatic download is unavailable (no egress); pass "
                "archive_path=<dir containing the extracted ESC-50 audio/>"
            )
        files = []
        for dirpath, _, names in sorted(os.walk(archive_path)):
            for f in sorted(names):
                if f.lower().endswith(".wav") and f.count("-") >= 3:
                    fold_s, _, _, target_s = f.rsplit(".", 1)[0].split("-")[:4]
                    files.append((os.path.join(dirpath, f), int(fold_s), int(target_s)))
        self.files = [
            (p, y) for p, fold, y in files
            if (fold != split if mode == "train" else fold == split)
        ]

    def __getitem__(self, idx):
        from . import load

        path, label = self.files[idx]
        wav, _sr = load(path)
        return wav, label

    def __len__(self):
        return len(self.files)
