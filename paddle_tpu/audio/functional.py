"""Audio functional ops (ref: python/paddle/audio/functional/
functional.py — same htk/slaney conventions)."""
from __future__ import annotations

import math
from typing import Optional, Union

import jax.numpy as jnp
import numpy as np

from ..base.tape import apply
from ..base.tensor import Tensor

__all__ = [
    "hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
    "compute_fbank_matrix", "power_to_db", "create_dct",
 "get_window",]


def hz_to_mel(freq, htk: bool = False):
    """ref: functional.py hz_to_mel."""
    scalar = not isinstance(freq, (Tensor, np.ndarray))
    f = np.asarray(freq.numpy() if isinstance(freq, Tensor) else freq, np.float64)
    if htk:
        mel = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mel = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mel = np.where(
            f >= min_log_hz, min_log_mel + np.log(np.maximum(f, 1e-10) / min_log_hz) / logstep, mel
        )
    return float(mel) if scalar else mel.astype(np.float32)


def mel_to_hz(mel, htk: bool = False):
    scalar = not isinstance(mel, (Tensor, np.ndarray))
    m = np.asarray(mel.numpy() if isinstance(mel, Tensor) else mel, np.float64)
    if htk:
        hz = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        hz = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        hz = np.where(m >= min_log_mel,
                      min_log_hz * np.exp(logstep * (m - min_log_mel)), hz)
    return float(hz) if scalar else hz.astype(np.float32)


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False,
                    dtype="float32"):
    low = hz_to_mel(f_min, htk)
    high = hz_to_mel(f_max, htk)
    mels = np.linspace(low, high, n_mels)
    return mel_to_hz(mels, htk).astype(dtype)


def fft_frequencies(sr: int, n_fft: int, dtype="float32"):
    return np.linspace(0, sr / 2, 1 + n_fft // 2).astype(dtype)


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max: Optional[float] = None,
                         htk: bool = False, norm: Union[str, float] = "slaney",
                         dtype="float32"):
    """Triangular mel filterbank [n_mels, 1 + n_fft//2]
    (ref: functional.py compute_fbank_matrix)."""
    f_max = f_max or sr / 2.0
    fftfreqs = fft_frequencies(sr, n_fft, np.float64)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk, np.float64)
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2 : n_mels + 2] - mel_f[:n_mels])
        weights *= enorm[:, None]
    elif isinstance(norm, (int, float)):
        weights /= np.maximum(
            np.linalg.norm(weights, ord=norm, axis=-1, keepdims=True), 1e-10
        )
    return weights.astype(dtype)


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: Optional[float] = 80.0, name=None):
    """10·log10 with floor + dynamic-range clip (ref: power_to_db)."""

    def f(s):
        log_spec = 10.0 * jnp.log10(jnp.maximum(s, amin))
        log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(jnp.asarray(ref_value), amin))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
        return log_spec

    return apply(f, spect, op_name="power_to_db")


def create_dct(n_mfcc: int, n_mels: int, norm: Optional[str] = "ortho",
               dtype="float32"):
    """DCT-II matrix [n_mels, n_mfcc] (ref: create_dct)."""
    n = np.arange(n_mels, dtype=np.float64)
    k = np.arange(n_mfcc, dtype=np.float64)[None, :]
    dct = np.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        dct[:, 0] *= 1.0 / math.sqrt(2.0)
        dct *= math.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return dct.astype(dtype)


def get_window(window, win_length, fftbins=True, dtype="float64"):
    """ref: audio/functional/window.py get_window — named window factory
    ('hann', ('gaussian', std), ...)."""
    import jax.numpy as jnp

    from ..base.dtype import canonical_dtype
    from ..base.tensor import Tensor

    if isinstance(window, tuple):
        name, *args = window
    else:
        name, args = window, []
    n = win_length
    periodic = fftbins
    m = n if periodic else n - 1
    if m <= 0:  # length-1 symmetric window: every formula below hits 0/0
        from ..base.tensor import Tensor as _T

        return _T(jnp.ones((n,), canonical_dtype(dtype)), _internal=True)
    i = jnp.arange(n, dtype=jnp.float64)
    if name in ("hann", "hanning"):
        w = 0.5 - 0.5 * jnp.cos(2 * jnp.pi * i / m)
    elif name == "hamming":
        w = 0.54 - 0.46 * jnp.cos(2 * jnp.pi * i / m)
    elif name == "blackman":
        w = (0.42 - 0.5 * jnp.cos(2 * jnp.pi * i / m)
             + 0.08 * jnp.cos(4 * jnp.pi * i / m))
    elif name == "bartlett":
        w = 1.0 - jnp.abs(2.0 * i / m - 1.0)
    elif name in ("rect", "boxcar", "ones"):
        w = jnp.ones((n,))
    elif name == "gaussian":
        std = args[0] if args else 7.0
        w = jnp.exp(-0.5 * ((i - m / 2.0) / std) ** 2)
    elif name == "triang":
        # periodic = symmetric window of n+1 truncated (scipy fftbins)
        L = n + 1 if periodic else n
        w = 1.0 - jnp.abs((i - (L - 1) / 2.0) / ((L + (L % 2)) / 2.0))
    elif name == "cosine":
        L = n + 1 if periodic else n
        w = jnp.sin(jnp.pi * (i + 0.5) / L)
    else:
        raise ValueError(f"unsupported window {name!r}")
    return Tensor(w.astype(canonical_dtype(dtype)), _internal=True)
