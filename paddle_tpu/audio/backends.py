"""paddle.audio.backends (ref: python/paddle/audio/backends/) — backend
registry. Only the stdlib-wave backend is bundled (the reference's
default wave_backend plays the same role); load/save/info live on the
parent package and are re-exported here like the reference."""
from __future__ import annotations

__all__ = ["list_available_backends", "get_current_backend", "set_backend"]


def list_available_backends():
    return ["wave"]


def get_current_backend() -> str:
    return "wave"


def set_backend(backend: str):
    if backend != "wave":
        raise ValueError(
            f"only the stdlib 'wave' backend is bundled, got {backend!r}"
        )
