"""Audio feature layers (ref: python/paddle/audio/features/layers.py —
Spectrogram :33, MelSpectrogram :123, LogMelSpectrogram :244,
MFCC :347). Window tensors and filterbanks are precomputed buffers;
compute runs through signal.stft, so features are jit-able and
differentiable (for e.g. vocoder losses).
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..base.tensor import Tensor
from ..nn.layer.layers import Layer
from . import functional as AF

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


def _window(name: str, n: int) -> np.ndarray:
    if name in ("hann", "hanning"):
        return np.hanning(n).astype(np.float32)
    if name in ("hamming",):
        return np.hamming(n).astype(np.float32)
    if name in ("blackman",):
        return np.blackman(n).astype(np.float32)
    if name in ("rect", "rectangular", "boxcar", "ones"):
        return np.ones(n, np.float32)
    raise ValueError(f"unsupported window {name!r}")


class Spectrogram(Layer):
    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", dtype: str = "float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.register_buffer(
            "window", Tensor(jnp.asarray(_window(window, self.win_length)), _internal=True)
        )

    def forward(self, x):
        from .. import signal

        spec = signal.stft(
            x, n_fft=self.n_fft, hop_length=self.hop_length,
            win_length=self.win_length, window=self.window,
            center=self.center, pad_mode=self.pad_mode,
        )
        mag = (spec.real() ** 2 + spec.imag() ** 2)
        if self.power == 2.0:
            return mag
        return mag ** (self.power / 2.0)


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm="slaney", dtype: str = "float32"):
        super().__init__()
        self._spectrogram = Spectrogram(
            n_fft, hop_length, win_length, window, power, center, pad_mode
        )
        fbank = AF.compute_fbank_matrix(
            sr=sr, n_fft=n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max,
            htk=htk, norm=norm,
        )
        self.register_buffer("fbank", Tensor(jnp.asarray(fbank), _internal=True))

    def forward(self, x):
        from .. import matmul

        spec = self._spectrogram(x)  # [..., freq, time]
        return matmul(self.fbank, spec)  # [..., n_mels, time]


class LogMelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, ref_value: float = 1.0,
                 amin: float = 1e-10, top_db: Optional[float] = None,
                 **mel_kwargs):
        super().__init__()
        self._melspectrogram = MelSpectrogram(sr=sr, **mel_kwargs)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return AF.power_to_db(mel, self.ref_value, self.amin, self.top_db)


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_mels: int = 64,
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None, **mel_kwargs):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(
            sr=sr, ref_value=ref_value, amin=amin, top_db=top_db,
            n_mels=n_mels, **mel_kwargs,
        )
        dct = AF.create_dct(n_mfcc, n_mels)
        self.register_buffer("dct", Tensor(jnp.asarray(dct), _internal=True))

    def forward(self, x):
        from .. import matmul
        from ..tensor.manipulation import transpose

        logmel = self._log_melspectrogram(x)  # [..., n_mels, time]
        ndim = len(logmel.shape)
        perm = list(range(ndim - 2)) + [ndim - 1, ndim - 2]
        swapped = transpose(logmel, perm)  # [..., time, n_mels]
        out = matmul(swapped, self.dct)  # [..., time, n_mfcc]
        return transpose(out, perm)  # [..., n_mfcc, time]
