"""paddle_tpu.audio — audio feature extraction.

ref: python/paddle/audio/ — functional/functional.py (hz_to_mel,
mel_to_hz, mel_frequencies, fft_frequencies, compute_fbank_matrix,
power_to_db, create_dct), features/layers.py (Spectrogram,
MelSpectrogram, LogMelSpectrogram, MFCC). Backends (file IO) are
omitted — no soundfile in this environment; features compute from
waveform Tensors via paddle_tpu.signal.stft.
"""
from . import functional  # noqa: F401
from .features import MFCC, LogMelSpectrogram, MelSpectrogram, Spectrogram  # noqa: F401

__all__ = ["functional", "Spectrogram", "MelSpectrogram",
           "LogMelSpectrogram", "MFCC"]
