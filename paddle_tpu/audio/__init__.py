"""paddle_tpu.audio — audio feature extraction.

ref: python/paddle/audio/ — functional/functional.py (hz_to_mel,
mel_to_hz, mel_frequencies, fft_frequencies, compute_fbank_matrix,
power_to_db, create_dct), features/layers.py (Spectrogram,
MelSpectrogram, LogMelSpectrogram, MFCC). Backends (file IO) are
omitted — no soundfile in this environment; features compute from
waveform Tensors via paddle_tpu.signal.stft.
"""
from . import functional  # noqa: F401
from .features import MFCC, LogMelSpectrogram, MelSpectrogram, Spectrogram  # noqa: F401

__all__ = ["functional", "Spectrogram", "MelSpectrogram",
           "LogMelSpectrogram", "MFCC", "datasets", "backends", "load", "info", "save",]


# -- backends + file I/O (ref: python/paddle/audio/backends/) ---------------
# The soundfile backend isn't bundled; the stdlib `wave` module gives a
# real PCM WAV path (the reference's default wave_backend does the same).


class AudioInfo:
    """ref: backends/backend.py AudioInfo."""

    def __init__(self, sample_rate, num_samples, num_channels, bits_per_sample, encoding="PCM_S"):
        self.sample_rate = sample_rate
        self.num_frames = num_samples
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def info(filepath: str) -> AudioInfo:
    """ref: backends/wave_backend.py info."""
    import wave as _wave

    with _wave.open(filepath, "rb") as w:
        return AudioInfo(
            w.getframerate(), w.getnframes(), w.getnchannels(),
            w.getsampwidth() * 8,
        )


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True):
    """ref: backends/wave_backend.py load — (Tensor [C, L] or [L, C],
    sample_rate)."""
    import wave as _wave

    import numpy as np

    from ..base.tensor import to_tensor

    with _wave.open(filepath, "rb") as w:
        sr = w.getframerate()
        w.setpos(frame_offset)
        n = num_frames if num_frames > 0 else w.getnframes() - frame_offset
        raw = w.readframes(n)
        width = w.getsampwidth()
        ch = w.getnchannels()
    dt = {1: np.uint8, 2: np.int16, 4: np.int32}[width]
    data = np.frombuffer(raw, dt).reshape(-1, ch)
    if width == 1:
        data = data.astype(np.int16) - 128  # 8-bit wav is unsigned
    if normalize:
        data = data.astype(np.float32) / float(2 ** (8 * width - 1))
    out = data.T if channels_first else data
    return to_tensor(np.ascontiguousarray(out)), sr


def save(filepath: str, src, sample_rate: int, channels_first: bool = True,
         encoding: str = "PCM_16", bits_per_sample: int = 16):
    """ref: backends/wave_backend.py save — float input in [-1, 1];
    8-bit WAV is unsigned, 16/32-bit are signed little-endian."""
    import wave as _wave

    import numpy as np

    if bits_per_sample not in (8, 16, 32):
        raise ValueError("bits_per_sample must be 8, 16 or 32")
    arr = np.asarray(src.numpy() if hasattr(src, "numpy") else src)
    if channels_first:
        arr = arr.T
    if arr.dtype.kind in "iu":
        # integer input: normalize by ITS OWN width so the float path
        # re-scales to bits_per_sample (avoids writing mismatched-width
        # frames under a header claiming another width)
        src_bits = arr.dtype.itemsize * 8
        arr = arr.astype(np.float64) / float(2 ** (src_bits - 1))
    if arr.dtype.kind == "f":
        arr = np.clip(arr, -1.0, 1.0)
        scaled = arr * (2 ** (bits_per_sample - 1) - 1)
        if bits_per_sample == 8:
            arr = (scaled + 128).astype("u1")  # unsigned per the WAV spec
        elif bits_per_sample == 16:
            arr = scaled.astype("<i2")
        else:
            arr = scaled.astype("<i4")
    with _wave.open(filepath, "wb") as w:
        w.setnchannels(arr.shape[1] if arr.ndim > 1 else 1)
        w.setsampwidth(bits_per_sample // 8)
        w.setframerate(sample_rate)
        w.writeframes(arr.tobytes())


from . import backends  # noqa: E402,F401
from . import datasets  # noqa: E402,F401
