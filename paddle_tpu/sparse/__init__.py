"""paddle_tpu.sparse — sparse tensors (COO / CSR).

ref: python/paddle/sparse/ — creation.py (sparse_coo_tensor :54,
sparse_csr_tensor :233), unary ops, matmul, nn.sparse layers (subset).

TPU-native design note: the TPU has no scatter-gather sparse units; XLA
lowers sparse work to dense-ish gathers. JAX's BCOO (jax.experimental.
sparse) is the native format — SparseCooTensor wraps it, so every op
here is jit-compatible and differentiates. CSR is stored as the
(crows, cols, values) triple for format parity; unary ops transform
values in place (traceable), matmul converts to BCOO (traceable), and
only CSR-output binary ops rebuild structure host-side.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..base.tensor import Tensor

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "is_same_shape", "matmul", "add", "multiply",
    "relu", "abs", "sin", "tanh", "coalesce",
]


def _unwrap(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x)


class SparseCooTensor:
    """COO sparse tensor over jax.experimental.sparse.BCOO."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    # -- paddle Tensor-like surface ------------------------------------
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    @property
    def nnz(self) -> int:
        return int(self._bcoo.nse)

    def indices(self) -> Tensor:
        return Tensor(self._bcoo.indices.T, _internal=True)

    def values(self) -> Tensor:
        return Tensor(self._bcoo.data, _internal=True)

    def to_dense(self) -> Tensor:
        return Tensor(self._bcoo.todense(), _internal=True)

    def to_sparse_csr(self) -> "SparseCsrTensor":
        if len(self._bcoo.shape) != 2:
            raise ValueError("CSR requires a 2-D tensor")
        dense = self._bcoo.todense()
        return _dense_to_csr(dense)

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(self._bcoo.sum_duplicates())

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def __repr__(self):
        return f"SparseCooTensor(shape={self.shape}, nnz={self.nnz})"


class SparseCsrTensor:
    """CSR triple (crows, cols, values); converts to BCOO for compute."""

    def __init__(self, crows, cols, values, shape):
        self.crows_arr = jnp.asarray(_unwrap(crows), jnp.int32)
        self.cols_arr = jnp.asarray(_unwrap(cols), jnp.int32)
        self.values_arr = _unwrap(values)
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self.values_arr.dtype

    @property
    def nnz(self) -> int:
        return int(self.values_arr.shape[0])

    def crows(self) -> Tensor:
        return Tensor(self.crows_arr, _internal=True)

    def cols(self) -> Tensor:
        return Tensor(self.cols_arr, _internal=True)

    def values(self) -> Tensor:
        return Tensor(self.values_arr, _internal=True)

    def _to_bcoo(self) -> jsparse.BCOO:
        counts = jnp.diff(self.crows_arr)
        rows = jnp.repeat(jnp.arange(self._shape[0]), counts,
                          total_repeat_length=self.nnz)
        idx = jnp.stack([rows, self.cols_arr], axis=1)
        return jsparse.BCOO((self.values_arr, idx), shape=self._shape)

    def to_sparse_coo(self, sparse_dim: Optional[int] = None) -> SparseCooTensor:
        return SparseCooTensor(self._to_bcoo())

    def to_dense(self) -> Tensor:
        return Tensor(self._to_bcoo().todense(), _internal=True)

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def __repr__(self):
        return f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz})"


def _dense_to_csr(dense) -> SparseCsrTensor:
    d = np.asarray(jax.device_get(dense))
    rows, cols = np.nonzero(d)
    values = d[rows, cols]
    crows = np.zeros(d.shape[0] + 1, np.int32)
    np.add.at(crows[1:], rows, 1)
    crows = np.cumsum(crows).astype(np.int32)
    return SparseCsrTensor(crows, cols.astype(np.int32), values, d.shape)


# ---------------------------------------------------------------------------
# creation (ref: sparse/creation.py)
# ---------------------------------------------------------------------------


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """ref: creation.py:54 — indices [ndim, nnz], values [nnz]."""
    idx = jnp.asarray(_unwrap(indices), jnp.int32)
    vals = _unwrap(values)
    if dtype is not None:
        from ..base.dtype import canonical_dtype

        vals = vals.astype(canonical_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in np.asarray(jax.device_get(idx)).max(1))
    return SparseCooTensor(jsparse.BCOO((vals, idx.T), shape=tuple(shape)))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    """ref: creation.py:233."""
    vals = _unwrap(values)
    if dtype is not None:
        from ..base.dtype import canonical_dtype

        vals = vals.astype(canonical_dtype(dtype))
    return SparseCsrTensor(crows, cols, vals, shape)


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


# ---------------------------------------------------------------------------
# ops (ref: sparse/binary.py, unary.py, matmul.py)
# ---------------------------------------------------------------------------


def _coo(x):
    if isinstance(x, SparseCsrTensor):
        return x._to_bcoo(), "csr"
    if isinstance(x, SparseCooTensor):
        return x._bcoo, "coo"
    raise TypeError(f"expected a sparse tensor, got {type(x)}")


def matmul(x, y, name=None):
    """sparse @ dense → dense (ref: sparse/matmul.py)."""
    b, _ = _coo(x)
    yd = _unwrap(y)
    return Tensor(b @ yd, _internal=True)


def add(x, y, name=None):
    # sparse+sparse via dense and re-sparsify with a static nse bound
    # (traceable); COO output. CSR inputs yield CSR via a host-side
    # conversion — re-sparsifying to CSR needs concrete row counts.
    bx, kind = _coo(x)
    by, _ = _coo(y)
    dense = bx.todense() + by.todense()
    out = jsparse.BCOO.fromdense(dense, nse=int(bx.nse) + int(by.nse))
    return _rewrap_dense_aware(out, kind, dense)


def multiply(x, y, name=None):
    bx, kind = _coo(x)
    by, _ = _coo(y)
    dense = bx.todense() * by.todense()
    out = jsparse.BCOO.fromdense(dense, nse=min(int(bx.nse), int(by.nse)))
    return _rewrap_dense_aware(out, kind, dense)


def _rewrap_dense_aware(bcoo, kind, dense):
    if kind == "csr":
        return _dense_to_csr(dense)  # host sync; CSR structure is host-built
    return SparseCooTensor(bcoo)


def _unary(fn):
    """Zero-preserving elementwise op: transforms values only, so both
    formats keep their structure with no densify/host sync (fully
    jit-compatible)."""

    def op(x, name=None):
        if isinstance(x, SparseCsrTensor):
            return SparseCsrTensor(
                x.crows_arr, x.cols_arr, fn(x.values_arr), x._shape
            )
        b, _ = _coo(x)
        return SparseCooTensor(jsparse.BCOO((fn(b.data), b.indices), shape=b.shape))

    return op


relu = _unary(lambda v: jnp.maximum(v, 0))
abs = _unary(jnp.abs)  # noqa: A001
sin = _unary(jnp.sin)
tanh = _unary(jnp.tanh)


def coalesce(x, name=None):
    return x.coalesce()
