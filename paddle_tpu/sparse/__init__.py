"""paddle_tpu.sparse — sparse tensors (COO / CSR).

ref: python/paddle/sparse/ — creation.py (sparse_coo_tensor :54,
sparse_csr_tensor :233), unary ops, matmul, nn.sparse layers (subset).

TPU-native design note: the TPU has no scatter-gather sparse units; XLA
lowers sparse work to dense-ish gathers. JAX's BCOO (jax.experimental.
sparse) is the native format — SparseCooTensor wraps it, so every op
here is jit-compatible and differentiates. CSR is stored as the
(crows, cols, values) triple for format parity; unary ops transform
values in place (traceable), matmul converts to BCOO (traceable), and
only CSR-output binary ops rebuild structure host-side.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..base.tensor import Tensor

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "is_same_shape", "matmul", "add", "multiply",
    "relu", "abs", "sin", "tanh", "coalesce",
    # value-map unaries (zero-preserving)
    "tan", "asin", "atan", "sinh", "asinh", "atanh", "sqrt", "square",
    "log1p", "pow", "neg", "deg2rad", "rad2deg", "expm1", "cast", "isnan",
    # binary / matmul family
    "subtract", "divide", "mv", "addmm", "masked_matmul", "mask_as",
    # structure ops
    "transpose", "reshape", "sum", "slice", "pca_lowrank",
]


def _unwrap(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x)


class SparseCooTensor:
    """COO sparse tensor over jax.experimental.sparse.BCOO.

    ``values_tensor`` (optional) is the LIVE tape Tensor the values came
    from: sparse.nn ops pass it so ``values()`` / ``to_dense()`` stay on
    the autograd tape (a fresh wrapper around the raw buffer would cut
    the gradient path at every sparse layer boundary)."""

    def __init__(self, bcoo: jsparse.BCOO, values_tensor: "Tensor" = None):
        self._bcoo = bcoo
        self._values_tensor = values_tensor

    # -- paddle Tensor-like surface ------------------------------------
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    @property
    def nnz(self) -> int:
        return int(self._bcoo.nse)

    def indices(self) -> Tensor:
        return Tensor(self._bcoo.indices.T, _internal=True)

    def values(self) -> Tensor:
        if self._values_tensor is not None:
            return self._values_tensor
        return Tensor(self._bcoo.data, _internal=True)

    def to_dense(self) -> Tensor:
        if self._values_tensor is not None:
            # differentiable scatter so grads flow back to the values
            from ..base.tape import apply as _apply

            idx = tuple(np.asarray(jax.device_get(self._bcoo.indices)).T)
            shape = self._bcoo.shape

            def scatter(v):
                return jnp.zeros(shape, v.dtype).at[idx].add(v)

            return _apply(scatter, self._values_tensor,
                          op_name="sparse_to_dense")
        if self._bcoo.data.dtype == jnp.bool_:
            # jax BCOO todense scatter-adds, which rejects bool (isnan
            # & friends): densify via int8 and cast back
            b8 = jsparse.BCOO(
                (self._bcoo.data.astype(jnp.int8), self._bcoo.indices),
                shape=self._bcoo.shape)
            return Tensor(b8.todense().astype(jnp.bool_), _internal=True)
        return Tensor(self._bcoo.todense(), _internal=True)

    def to_sparse_csr(self) -> "SparseCsrTensor":
        if len(self._bcoo.shape) != 2:
            raise ValueError("CSR requires a 2-D tensor")
        dense = self._bcoo.todense()
        return _dense_to_csr(dense)

    def coalesce(self) -> "SparseCooTensor":
        if self._values_tensor is None:
            return SparseCooTensor(self._bcoo.sum_duplicates())
        # keep the gradient path: group duplicate indices host-side and
        # scatter-add the LIVE values through the tape
        from ..base.tape import apply as _apply

        idx_np = np.asarray(jax.device_get(self._bcoo.indices))
        uniq, inv = np.unique(idx_np, axis=0, return_inverse=True)
        inv = jnp.asarray(inv.reshape(-1))
        n = uniq.shape[0]

        def f(v):
            return jnp.zeros((n,) + v.shape[1:], v.dtype).at[inv].add(v)

        nv = _apply(f, self._values_tensor, op_name="sparse_coalesce")
        return SparseCooTensor(
            jsparse.BCOO((nv._data, jnp.asarray(uniq, jnp.int32)),
                         shape=self._bcoo.shape),
            values_tensor=nv)

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def __repr__(self):
        return f"SparseCooTensor(shape={self.shape}, nnz={self.nnz})"


class SparseCsrTensor:
    """CSR triple (crows, cols, values); converts to BCOO for compute."""

    def __init__(self, crows, cols, values, shape):
        self.crows_arr = jnp.asarray(_unwrap(crows), jnp.int32)
        self.cols_arr = jnp.asarray(_unwrap(cols), jnp.int32)
        self.values_arr = _unwrap(values)
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self.values_arr.dtype

    @property
    def nnz(self) -> int:
        return int(self.values_arr.shape[0])

    def crows(self) -> Tensor:
        return Tensor(self.crows_arr, _internal=True)

    def cols(self) -> Tensor:
        return Tensor(self.cols_arr, _internal=True)

    def values(self) -> Tensor:
        return Tensor(self.values_arr, _internal=True)

    def _to_bcoo(self) -> jsparse.BCOO:
        counts = jnp.diff(self.crows_arr)
        rows = jnp.repeat(jnp.arange(self._shape[0]), counts,
                          total_repeat_length=self.nnz)
        idx = jnp.stack([rows, self.cols_arr], axis=1)
        return jsparse.BCOO((self.values_arr, idx), shape=self._shape)

    def to_sparse_coo(self, sparse_dim: Optional[int] = None) -> SparseCooTensor:
        return SparseCooTensor(self._to_bcoo())

    def to_dense(self) -> Tensor:
        return Tensor(self._to_bcoo().todense(), _internal=True)

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def __repr__(self):
        return f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz})"


def _dense_to_csr(dense) -> SparseCsrTensor:
    d = np.asarray(jax.device_get(dense))
    rows, cols = np.nonzero(d)
    values = d[rows, cols]
    crows = np.zeros(d.shape[0] + 1, np.int32)
    np.add.at(crows[1:], rows, 1)
    crows = np.cumsum(crows).astype(np.int32)
    return SparseCsrTensor(crows, cols.astype(np.int32), values, d.shape)


# ---------------------------------------------------------------------------
# creation (ref: sparse/creation.py)
# ---------------------------------------------------------------------------


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """ref: creation.py:54 — indices [ndim, nnz], values [nnz]."""
    idx = jnp.asarray(_unwrap(indices), jnp.int32)
    # the reference's default is stop_gradient=True: grads flow back to
    # the values only when the caller opts in (ref creation.py:54)
    vt = (values if isinstance(values, Tensor) and not stop_gradient
          and jnp.issubdtype(_unwrap(values).dtype, jnp.inexact) else None)
    vals = _unwrap(values)
    if dtype is not None:
        from ..base.dtype import canonical_dtype

        dt = canonical_dtype(dtype)
        if vt is not None and jnp.issubdtype(dt, jnp.inexact):
            from ..base.tape import apply as _apply

            vt = _apply(lambda v: v.astype(dt), vt, op_name="cast")
            vals = vt._data
        else:
            vals = vals.astype(dt)
            vt = None  # non-differentiable cast
    if shape is None:
        shape = tuple(int(m) + 1 for m in np.asarray(jax.device_get(idx)).max(1))
    # keep the LIVE tape Tensor so grads flow back through values()/
    # to_dense()/matmul/_unary (same contract sparse.nn relies on)
    return SparseCooTensor(jsparse.BCOO((vals, idx.T), shape=tuple(shape)),
                           values_tensor=vt)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    """ref: creation.py:233."""
    vals = _unwrap(values)
    if dtype is not None:
        from ..base.dtype import canonical_dtype

        vals = vals.astype(canonical_dtype(dtype))
    return SparseCsrTensor(crows, cols, vals, shape)


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


# ---------------------------------------------------------------------------
# ops (ref: sparse/binary.py, unary.py, matmul.py)
# ---------------------------------------------------------------------------


def _coo(x):
    if isinstance(x, SparseCsrTensor):
        return x._to_bcoo(), "csr"
    if isinstance(x, SparseCooTensor):
        return x._bcoo, "coo"
    raise TypeError(f"expected a sparse tensor, got {type(x)}")


def _spmm(b, x, y, op_name):
    """Differentiable sparse@dense core shared by matmul/mv/addmm:
    routes through tape.apply when either the COO tensor carries its
    live values Tensor or the dense operand is a live Tensor."""
    vt = getattr(x, "_values_tensor", None)
    if vt is None and not isinstance(y, Tensor):
        return Tensor(b @ _unwrap(y), _internal=True)
    from ..base.tape import apply as _apply

    indices, shape = b.indices, b.shape

    def f(v, yd):
        return jsparse.BCOO((v, indices), shape=shape) @ yd

    return _apply(
        f, vt if vt is not None else Tensor(b.data, _internal=True),
        y if isinstance(y, Tensor) else Tensor(_unwrap(y), _internal=True),
        op_name=op_name)


def matmul(x, y, name=None):
    """sparse @ dense → dense (ref: sparse/matmul.py). Differentiable
    w.r.t. BOTH the sparse values (when the COO tensor carries its live
    values Tensor) and the dense operand."""
    b, _ = _coo(x)
    return _spmm(b, x, y, "sparse_matmul")


def add(x, y, name=None):
    # sparse+sparse via dense and re-sparsify with a static nse bound
    # (traceable); COO output. CSR inputs yield CSR via a host-side
    # conversion — re-sparsifying to CSR needs concrete row counts.
    bx, kind = _coo(x)
    by, _ = _coo(y)
    dense = bx.todense() + by.todense()
    out = jsparse.BCOO.fromdense(dense, nse=int(bx.nse) + int(by.nse))
    return _rewrap_dense_aware(out, kind, dense)


def multiply(x, y, name=None):
    bx, kind = _coo(x)
    by, _ = _coo(y)
    dense = bx.todense() * by.todense()
    out = jsparse.BCOO.fromdense(dense, nse=min(int(bx.nse), int(by.nse)))
    return _rewrap_dense_aware(out, kind, dense)


def _rewrap_dense_aware(bcoo, kind, dense):
    if kind == "csr":
        return _dense_to_csr(dense)  # host sync; CSR structure is host-built
    return SparseCooTensor(bcoo)


def _unary(fn):
    """Zero-preserving elementwise op: transforms values only, so both
    formats keep their structure with no densify/host sync (fully
    jit-compatible)."""

    def op(x, name=None):
        if isinstance(x, SparseCsrTensor):
            return SparseCsrTensor(
                x.crows_arr, x.cols_arr, fn(x.values_arr), x._shape
            )
        b, _ = _coo(x)
        vt = getattr(x, "_values_tensor", None)
        if vt is not None:
            out_dtype = jax.eval_shape(fn, b.data).dtype  # zero FLOPs
            if jnp.issubdtype(out_dtype, jnp.inexact):
                from ..base.tape import apply as _apply

                new_vt = _apply(fn, vt, op_name="sparse_unary")
                return SparseCooTensor(
                    jsparse.BCOO((new_vt._data, b.indices), shape=b.shape),
                    values_tensor=new_vt)
            # bool/int results (isnan, ...) have no gradient path and
            # to_dense's scatter-add rejects them — drop the link
        return SparseCooTensor(jsparse.BCOO((fn(b.data), b.indices), shape=b.shape))

    return op


relu = _unary(lambda v: jnp.maximum(v, 0))
abs = _unary(jnp.abs)  # noqa: A001
sin = _unary(jnp.sin)
tanh = _unary(jnp.tanh)


def coalesce(x, name=None):
    return x.coalesce()


# ---------------------------------------------------------------------------
# parity sweep (ref: python/paddle/sparse/__init__.py full op list)
# ---------------------------------------------------------------------------

tan = _unary(jnp.tan)
asin = _unary(jnp.arcsin)
atan = _unary(jnp.arctan)
sinh = _unary(jnp.sinh)
asinh = _unary(jnp.arcsinh)
atanh = _unary(jnp.arctanh)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)
log1p = _unary(jnp.log1p)
neg = _unary(jnp.negative)
deg2rad = _unary(jnp.deg2rad)
rad2deg = _unary(jnp.rad2deg)
expm1 = _unary(jnp.expm1)
isnan = _unary(jnp.isnan)


def pow(x, factor, name=None):  # noqa: A001
    """Zero-preserving for factor > 0 (ref sparse/unary.py pow)."""
    return _unary(lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    """ref sparse/unary.py cast — changes value (and index) dtypes."""
    from ..base.dtype import canonical_dtype

    vd = canonical_dtype(value_dtype) if value_dtype is not None else None
    idt = jnp.int64 if index_dtype in ("int64",) else (jnp.int32 if index_dtype else None)
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(
            x.crows_arr.astype(idt) if idt else x.crows_arr,
            x.cols_arr.astype(idt) if idt else x.cols_arr,
            x.values_arr.astype(vd) if vd else x.values_arr,
            x._shape,
        )
    b, _ = _coo(x)
    idx = b.indices.astype(idt) if idt else b.indices
    vals = b.data.astype(vd) if vd else b.data
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=b.shape))


def subtract(x, y, name=None):
    bx, kind = _coo(x)
    by, _ = _coo(y)
    dense = bx.todense() - by.todense()
    out = jsparse.BCOO.fromdense(dense, nse=int(bx.nse) + int(by.nse))
    return _rewrap_dense_aware(out, kind, dense)


def divide(x, y, name=None):
    """Dense-semantics divide (0/0 -> nan), matching the reference.
    Every shared-zero position is NaN, so nse must cover the FULL
    shape — a tighter bound would silently truncate entries."""
    bx, kind = _coo(x)
    by, _ = _coo(y)
    dense = bx.todense() / by.todense()
    out = jsparse.BCOO.fromdense(dense, nse=int(np.prod(bx.shape)))
    return _rewrap_dense_aware(out, kind, dense)


def mv(x, vec, name=None):
    """sparse [M,N] @ dense [N] -> dense [M] (ref sparse/matmul.py mv);
    same autograd contract as matmul."""
    b, _ = _coo(x)
    return _spmm(b, x, vec, "sparse_mv")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x@y) (ref sparse/matmul.py addmm); same
    autograd contract as matmul."""
    b, _ = _coo(x)
    prod = _spmm(b, x, y, "sparse_addmm")
    inp = input if isinstance(input, Tensor) else Tensor(
        _unwrap(input), _internal=True)
    return inp * beta + prod * alpha


def masked_matmul(x, y, mask, name=None):
    """Dense@dense evaluated only at mask's sparsity (ref matmul.py
    masked_matmul; the cuSPARSE SDDMM analogue). Computes per-nonzero
    row·col dot products — never materializes the dense product."""
    xd, yd = _unwrap(x), _unwrap(y)

    def _sddmm(a, c, rows, cols):
        # per-nonzero row-col dot products (the cuSPARSE SDDMM shape)
        return jnp.einsum("nk,nk->n", a[rows, :], c[:, cols].T)

    if isinstance(mask, SparseCsrTensor):
        b = mask._to_bcoo()
        rows, cols = b.indices[:, 0], b.indices[:, 1]
        vals = _sddmm(xd, yd, rows, cols)
        dense = jnp.zeros(mask.shape, vals.dtype).at[rows, cols].set(vals)
        return _dense_to_csr(dense)
    b, _ = _coo(mask)
    rows, cols = b.indices[:, 0], b.indices[:, 1]
    if isinstance(x, Tensor) or isinstance(y, Tensor):
        # SDDMM differentiable w.r.t. both dense operands: the values
        # ride the tape, so downstream to_dense/matmul keep the path
        from ..base.tape import apply as _apply

        nv = _apply(
            lambda a, c: _sddmm(a, c, rows, cols),
            x if isinstance(x, Tensor) else Tensor(xd, _internal=True),
            y if isinstance(y, Tensor) else Tensor(yd, _internal=True),
            op_name="sparse_masked_matmul")
        return SparseCooTensor(
            jsparse.BCOO((nv._data, b.indices), shape=tuple(mask.shape)),
            values_tensor=nv)
    vals = _sddmm(xd, yd, rows, cols)
    return SparseCooTensor(jsparse.BCOO((vals, b.indices), shape=tuple(mask.shape)))


def mask_as(x, mask, name=None):
    """Take dense x's values at mask's nonzero positions (ref
    sparse/unary.py mask_as)."""
    xd = _unwrap(x)
    if isinstance(mask, SparseCsrTensor):
        b = mask._to_bcoo()
        dense = jnp.zeros(mask.shape, xd.dtype).at[b.indices[:, 0], b.indices[:, 1]].set(
            xd[b.indices[:, 0], b.indices[:, 1]]
        )
        return _dense_to_csr(dense)
    b, _ = _coo(mask)
    idx = tuple(b.indices[:, i] for i in range(b.indices.shape[1]))
    vals = xd[idx]
    return SparseCooTensor(jsparse.BCOO((vals, b.indices), shape=tuple(mask.shape)))


def _via_dense(x, fn, out_shape=None):
    """Structure-changing op through a dense round-trip (XLA fuses the
    densify/re-sparsify; nse bound = input nnz)."""
    b, kind = _coo(x)
    dense = fn(b.todense())
    out = jsparse.BCOO.fromdense(dense, nse=int(b.nse))
    return _rewrap_dense_aware(out, kind, dense)


def transpose(x, perm, name=None):
    return _via_dense(x, lambda d: jnp.transpose(d, perm))


def reshape(x, shape, name=None):
    return _via_dense(x, lambda d: jnp.reshape(d, shape))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    """Reduce over axis; returns sparse like the reference. A COO
    input carrying its live values Tensor keeps the gradient path for
    the full (axis=None) reduction — the sum of all nonzeros."""
    vt = getattr(x, "_values_tensor", None)
    if vt is not None and axis is None:
        # full reduction over live values: the gradient path survives
        # every variant (keepdim wraps the scalar back into a 1-element
        # COO with tape-linked values; dtype casts ride the tape)
        out = vt.sum()
        if dtype is not None:
            from ..base.dtype import canonical_dtype
            from ..base.tape import apply as _apply

            dt = canonical_dtype(dtype)
            out = _apply(lambda v: v.astype(dt), out, op_name="cast")
        if not keepdim:
            return out
        from ..tensor.manipulation import reshape as _reshape

        ndim = len(x.shape)
        nv = _reshape(out, [1])
        return SparseCooTensor(
            jsparse.BCOO(
                (nv._data, jnp.zeros((1, ndim), jnp.int32)),
                shape=(1,) * ndim),
            values_tensor=nv)
    b, kind = _coo(x)
    dense = b.todense().sum(axis=axis, keepdims=keepdim)
    if dtype is not None:
        from ..base.dtype import canonical_dtype

        dense = dense.astype(canonical_dtype(dtype))
    if dense.ndim == 0:
        return Tensor(dense, _internal=True)
    out = jsparse.BCOO.fromdense(dense, nse=min(int(b.nse), int(np.prod(dense.shape))))
    return _rewrap_dense_aware(out, kind, dense)


def slice(x, axes, starts, ends, name=None):  # noqa: A001
    import builtins as _b

    def _f(d):
        idx = [_b.slice(None)] * d.ndim
        for ax, st, en in zip(axes, starts, ends):
            idx[ax] = _b.slice(st, en)
        return d[tuple(idx)]

    return _via_dense(x, _f)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized PCA (ref sparse/multiary.py pca_lowrank): subspace
    iteration on the (centered) matrix; sparse matmuls stay sparse."""
    b, _ = _coo(x) if not isinstance(x, Tensor) else (None, None)
    d = _unwrap(x.to_dense() if hasattr(x, "to_dense") else x)
    m, n = d.shape
    if q is None:
        q = min(6, m, n)
    if center:
        d = d - d.mean(axis=0, keepdims=True)
    key = jax.random.PRNGKey(0)
    omega = jax.random.normal(key, (n, q), d.dtype)
    y = d @ omega
    for _ in range(niter):
        y = d @ (d.T @ y)
    qmat, _ = jnp.linalg.qr(y)
    bmat = qmat.T @ d
    u_hat, s, vt = jnp.linalg.svd(bmat, full_matrices=False)
    u = qmat @ u_hat
    return Tensor(u, _internal=True), Tensor(s, _internal=True), Tensor(vt.T, _internal=True)


# sparse.nn (layer stack) — imported last: it consumes the COO/CSR
# types defined above (ref: python/paddle/sparse/nn/)
from . import nn  # noqa: E402,F401
