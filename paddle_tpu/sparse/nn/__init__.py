"""paddle.sparse.nn parity: sparse conv / norm / activation / pooling
layers over SparseCooTensor (ref: python/paddle/sparse/nn/layer/
conv.py:304 Conv3D, :574 SubmConv3D; norm.py BatchNorm; activation.py
ReLU/ReLU6/LeakyReLU/Softmax; pooling.py MaxPool3D). See functional.py
for the gather-GEMM-scatter design notes."""
from __future__ import annotations

import numpy as np

from . import functional  # noqa: F401
from ...nn.layer.layers import Layer

__all__ = [
    "Conv2D", "SubmConv2D", "Conv3D", "SubmConv3D", "BatchNorm",
    "SyncBatchNorm", "ReLU", "ReLU6", "LeakyReLU", "Softmax", "MaxPool3D",
    "functional",
]


def _tup3(v):
    return functional._tup3(v)


class _Conv3DBase(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 key=None):
        super().__init__()
        if groups != 1:
            raise ValueError("sparse conv supports groups=1")
        if padding_mode != "zeros":
            raise ValueError("sparse conv supports padding_mode='zeros'")
        if data_format != "NDHWC":
            raise ValueError("sparse conv uses the NDHWC sparse layout")
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _tup3(kernel_size)
        self._stride = _tup3(stride)
        self._padding = _tup3(padding)
        self._dilation = _tup3(dilation)
        kd, kh, kw = self._kernel_size
        fan_in = in_channels * kd * kh * kw
        bound = 1.0 / np.sqrt(fan_in)
        from ...nn import initializer as I

        self.weight = self.create_parameter(
            shape=[kd, kh, kw, in_channels, out_channels],
            attr=weight_attr,
            default_initializer=I.Uniform(-bound, bound),
        )
        if bias_attr is not False:
            self.bias = self.create_parameter(
                shape=[out_channels], is_bias=True, attr=bias_attr,
                default_initializer=I.Uniform(-bound, bound),
            )
        else:
            self.bias = None


class Conv3D(_Conv3DBase):
    """Sparse 3-D conv (ref: sparse/nn/layer/conv.py:304)."""

    def forward(self, x):
        return functional.conv3d(
            x, self.weight, self.bias, self._stride, self._padding,
            self._dilation,
        )


class SubmConv3D(_Conv3DBase):
    """Submanifold sparse 3-D conv (ref: conv.py:574)."""

    def forward(self, x):
        return functional.subm_conv3d(
            x, self.weight, self.bias, self._stride, self._padding,
            self._dilation,
        )


class _Conv2DBase(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC",
                 key=None):
        super().__init__()
        if groups != 1:
            raise ValueError("sparse conv supports groups=1")
        if padding_mode != "zeros":
            raise ValueError("sparse conv supports padding_mode='zeros'")
        if data_format != "NHWC":
            raise ValueError("sparse conv2d uses the NHWC sparse layout")
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = functional._tup(kernel_size, 2)
        self._stride = functional._tup(stride, 2)
        self._padding = functional._tup(padding, 2)
        self._dilation = functional._tup(dilation, 2)
        kh, kw = self._kernel_size
        fan_in = in_channels * kh * kw
        bound = 1.0 / np.sqrt(fan_in)
        from ...nn import initializer as I

        self.weight = self.create_parameter(
            shape=[kh, kw, in_channels, out_channels],
            attr=weight_attr,
            default_initializer=I.Uniform(-bound, bound),
        )
        if bias_attr is not False:
            self.bias = self.create_parameter(
                shape=[out_channels], is_bias=True, attr=bias_attr,
                default_initializer=I.Uniform(-bound, bound),
            )
        else:
            self.bias = None


class Conv2D(_Conv2DBase):
    """Sparse 2-D conv (ref: sparse/nn/layer/conv.py Conv2D)."""

    def forward(self, x):
        return functional.conv2d(
            x, self.weight, self.bias, self._stride, self._padding,
            self._dilation,
        )


class SubmConv2D(_Conv2DBase):
    """Submanifold sparse 2-D conv (ref: conv.py SubmConv2D)."""

    def forward(self, x):
        return functional.subm_conv2d(
            x, self.weight, self.bias, self._stride, self._padding,
            self._dilation,
        )


class BatchNorm(Layer):
    """Sparse BatchNorm (ref: sparse/nn/layer/norm.py:24 — a BatchNorm1D
    over the nnz values, channelwise): normalizes values [nnz, C] with
    running statistics."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        if use_global_stats:
            raise NotImplementedError(
                "sparse BatchNorm(use_global_stats=True) is not supported"
            )
        import paddle_tpu.nn as nn

        self._bn = nn.BatchNorm1D(
            num_features, momentum=momentum, epsilon=epsilon,
            weight_attr=weight_attr, bias_attr=bias_attr,
        )

    def forward(self, x):
        import jax.experimental.sparse as jsparse

        from .. import SparseCooTensor
        from ...base.tensor import Tensor

        bcoo = x._bcoo
        out = self._bn(x.values())
        return SparseCooTensor(jsparse.BCOO(
            (out._data, bcoo.indices), shape=bcoo.shape,
            indices_sorted=bcoo.indices_sorted,
            unique_indices=bcoo.unique_indices,
        ), values_tensor=out)


class SyncBatchNorm(BatchNorm):
    """Sparse SyncBatchNorm (ref: norm.py SyncBatchNorm) — under GSPMD
    the batch statistics are computed on the global (replicated or
    sharded) values, so the dense BatchNorm semantics already match
    the synchronized behavior."""


class ReLU(Layer):
    def forward(self, x):
        return functional.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return functional.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return functional.leaky_relu(x, self._slope)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return functional.softmax(x, self._axis)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 ceil_mode=False, return_mask=False, data_format="NDHWC",
                 name=None):
        super().__init__()
        if ceil_mode or return_mask:
            raise NotImplementedError(
                "sparse MaxPool3D supports ceil_mode=False, "
                "return_mask=False"
            )
        if data_format != "NDHWC":
            raise ValueError("sparse MaxPool3D uses the NDHWC layout")
        self._kernel = kernel_size
        self._stride = stride
        self._padding = padding

    def forward(self, x):
        return functional.max_pool3d(
            x, self._kernel, self._stride, self._padding
        )
