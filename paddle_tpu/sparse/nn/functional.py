"""Sparse neural-network functional ops: submanifold / regular sparse
3-D convolution, activations, pooling, and block-sparse attention.

TPU-native redesign of the reference's sparse conv stack (ref:
python/paddle/sparse/nn/functional/conv.py:30 conv3d / :330
subm_conv3d; GPU kernels paddle/phi/kernels/sparse/gpu/conv_kernel.cu —
a hash-table "rulebook" of (kernel offset, in row, out row) pairs
driving per-offset GEMMs). The TPU design keeps exactly that
decomposition but splits it MXU-first:

- the RULEBOOK (which input row contributes to which output row under
  which kernel offset) depends only on the COO coordinates — host data
  for point-cloud workloads — so it is built ONCE on host with numpy
  dict lookups;
- the compute is K^3 dense [nnz_k, C_in] @ [C_in, C_out] GEMMs with
  gather/scatter-add glue, all inside ONE tape.apply: large batched
  matmuls on the MXU, static shapes, differentiable w.r.t. values AND
  weights through jax.vjp (the reference hand-writes conv_grad_kernel).

Submanifold convs (SubmConv3D) keep the output coordinate set equal to
the input's — the standard trick that stops sparsity dilation in deep
point-cloud nets; regular sparse conv produces the full reachable
output set.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...base.tape import apply
from ...base.tensor import Tensor
from .. import SparseCooTensor


def _tup3(v) -> Tuple[int, int, int]:
    return _tup(v, 3)


def _coords_values(x: SparseCooTensor):
    """Host coords [nnz, ndim_sparse] + device values [nnz, C]."""
    bcoo = x._bcoo
    coords = np.asarray(jax.device_get(bcoo.indices))  # [nnz, n_sparse]
    values = bcoo.data
    return coords, values


_rulebook_cache: dict = {}


def _cached_rulebook(coords, spatial, kernel, stride, padding, dilation,
                     subm: bool):
    """Rulebooks depend only on the coordinate pattern + geometry, so a
    SubmConv stack (same coords every layer) and a training loop (same
    clouds every step) reuse them instead of re-running the O(K^3*nnz)
    host loop per forward. Bounded LRU-ish cache."""
    key = (coords.tobytes(), coords.shape, tuple(spatial), tuple(kernel),
           tuple(stride), tuple(padding), tuple(dilation), subm)
    hit = _rulebook_cache.get(key)
    if hit is None:
        if len(_rulebook_cache) >= 256:
            _rulebook_cache.pop(next(iter(_rulebook_cache)))
        hit = _rulebook_cache[key] = _build_rulebook(
            coords, spatial, kernel, stride, padding, dilation, subm)
    return hit


def _build_rulebook(coords, spatial, kernel, stride, padding, dilation,
                    subm: bool):
    """(out_coords, per-offset (in_rows, out_rows)) — the sparse-conv
    rulebook (ref: conv_kernel.cu's hash-table product), on host.
    Dimension-generic: spatial/kernel/stride/... are length-nd tuples
    (nd=2 for conv2d, nd=3 for conv3d); coords rows are [n, *pos]."""
    import itertools

    nd = len(spatial)
    out_sizes = tuple(
        (spatial[d] + 2 * padding[d] - dilation[d] * (kernel[d] - 1) - 1)
        // stride[d] + 1
        for d in range(nd)
    )

    in_map = {tuple(c): i for i, c in enumerate(coords)}
    if subm:
        out_map = in_map
        out_coords = coords
    else:
        out_map = {}
        out_list = []

    pairs = {}
    for offs in itertools.product(*[range(k) for k in kernel]):
        k = 0
        for d in range(nd):
            k = k * kernel[d] + offs[d]
        ins, outs = [], []
        for i, row in enumerate(coords):
            n, pos = row[0], row[1:]
            # output position this input feeds through offset k
            t = []
            ok = True
            for d in range(nd):
                td = pos[d] + padding[d] - offs[d] * dilation[d]
                if td % stride[d]:
                    ok = False
                    break
                td //= stride[d]
                if not 0 <= td < out_sizes[d]:
                    ok = False
                    break
                t.append(td)
            if not ok:
                continue
            key = (n, *t)
            j = out_map.get(key)
            if j is None:
                if subm:
                    continue
                j = len(out_list)
                out_map[key] = j
                out_list.append(key)
            ins.append(i)
            outs.append(j)
        if ins:
            pairs[k] = (np.asarray(ins, np.int32),
                        np.asarray(outs, np.int32))
    if not subm:
        out_coords = np.asarray(out_list, np.int64).reshape(-1, nd + 1)
    return out_coords, pairs, out_sizes


def _tup(v, nd: int):
    if isinstance(v, (list, tuple)):
        if len(v) == nd:
            return tuple(int(x) for x in v)
        if len(v) == 1:
            return (int(v[0]),) * nd
        raise ValueError(f"need 1 or {nd} entries, got {v!r}")
    return (int(v),) * nd


def _sparse_conv(x: SparseCooTensor, weight, bias, stride, padding,
                 dilation, subm: bool, op_name: str,
                 nd: int = 3) -> SparseCooTensor:
    """Shared gather-GEMM-scatter body for conv2d/3d and their subm
    variants. x dense shape [N, *spatial, C_in] (the reference's
    NDHWC/NHWC sparse layouts); weight [*kernel, C_in, C_out]."""
    import jax.experimental.sparse as jsparse

    shape = x.shape
    if len(shape) != nd + 2:
        raise ValueError(
            f"sparse conv{nd}d expects a {nd + 2}-D [N, *spatial, C] "
            f"input, got {shape}"
        )
    wshape = tuple((weight._data if isinstance(weight, Tensor) else weight).shape)
    kernel = wshape[:nd]
    coords, values = _coords_values(x)
    out_coords, pairs, out_spatial = _cached_rulebook(
        coords, shape[1 : nd + 1], kernel, _tup(stride, nd),
        _tup(padding, nd), _tup(dilation, nd), subm,
    )
    n_out = len(out_coords)
    c_out = wshape[-1]

    vt = x.values()  # live tape Tensor when upstream was a sparse op
    args = [vt, weight] + ([bias] if bias is not None else [])

    def run(vals, w, *maybe_bias):
        w2 = w.reshape(-1, w.shape[-2], w.shape[-1])  # [prod(K), C_in, C_out]
        out = jnp.zeros((n_out, c_out), vals.dtype)
        for k, (ins, outs) in pairs.items():
            contrib = vals[ins] @ w2[k].astype(vals.dtype)  # MXU GEMM
            out = out.at[outs].add(contrib)
        if maybe_bias:
            out = out + maybe_bias[0].astype(vals.dtype)
        return out

    out_vals = apply(run, *args, op_name=op_name)
    idx = jnp.asarray(out_coords, jnp.int32)
    new_shape = (shape[0],) + tuple(out_spatial) + (c_out,)
    bcoo = jsparse.BCOO(
        (out_vals._data, idx), shape=new_shape,
        indices_sorted=subm and x._bcoo.indices_sorted,
        unique_indices=True,
    )
    return SparseCooTensor(bcoo, values_tensor=out_vals)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NDHWC", name=None):
    """Sparse 3-D convolution (ref: sparse/nn/functional/conv.py:30)."""
    if groups != 1:
        raise ValueError("sparse conv3d supports groups=1")
    if data_format != "NDHWC":
        raise ValueError("sparse conv3d uses the NDHWC sparse layout")
    return _sparse_conv(x, weight, bias, stride, padding, dilation,
                        subm=False, op_name="sparse_conv3d")


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold sparse conv (ref: conv.py:330): output coordinates ==
    input coordinates, so deep stacks don't dilate the active set."""
    if groups != 1:
        raise ValueError("sparse subm_conv3d supports groups=1")
    if data_format != "NDHWC":
        raise ValueError("sparse subm_conv3d uses the NDHWC sparse layout")
    return _sparse_conv(x, weight, bias, stride, padding, dilation,
                        subm=True, op_name="sparse_subm_conv3d")


def _values_map(x: SparseCooTensor, fn, op_name) -> SparseCooTensor:
    import jax.experimental.sparse as jsparse

    bcoo = x._bcoo
    vals = apply(fn, x.values(), op_name=op_name)
    return SparseCooTensor(jsparse.BCOO(
        (vals._data, bcoo.indices), shape=bcoo.shape,
        indices_sorted=bcoo.indices_sorted, unique_indices=bcoo.unique_indices,
    ), values_tensor=vals)


def relu(x, name=None):
    return _values_map(x, lambda v: jnp.maximum(v, 0), "sparse_relu")


def relu6(x, name=None):
    return _values_map(x, lambda v: jnp.clip(v, 0, 6), "sparse_relu6")


def leaky_relu(x, negative_slope=0.01, name=None):
    return _values_map(
        x, lambda v: jnp.where(v >= 0, v, negative_slope * v),
        "sparse_leaky_relu",
    )


def softmax(x, axis=-1, name=None):
    """Sparse softmax (ref: sparse/nn/functional/activation.py softmax):
    normalizes over the STORED entries of each row of the last sparse
    axis — scalar-valued COO tensors get a per-row segment softmax over
    their nnz pattern; tensors with a dense trailing dim (values
    [nnz, C]) normalize over that dense axis."""
    if axis != -1:
        raise ValueError("sparse softmax supports axis=-1")
    bcoo = x._bcoo
    if bcoo.data.ndim > 1:
        return _values_map(
            x, lambda v: jax.nn.softmax(v, axis=-1), "sparse_softmax"
        )
    # scalar values: group by leading (row) coordinates on host, then
    # a segment max/sum softmax on device
    coords = np.asarray(jax.device_get(bcoo.indices))
    row_keys, row_ids = np.unique(
        coords[:, :-1], axis=0, return_inverse=True
    )
    n_rows = len(row_keys)
    seg = jnp.asarray(row_ids, jnp.int32)

    def run(v):
        mx = jnp.full((n_rows,), -jnp.inf, v.dtype).at[seg].max(v)
        e = jnp.exp(v - mx[seg])
        denom = jnp.zeros((n_rows,), e.dtype).at[seg].add(e)
        return e / denom[seg]

    return _values_map(x, run, "sparse_softmax")


def max_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NDHWC", name=None):
    """Sparse max pooling (ref: sparse/nn/functional/pooling.py:24):
    output coords = reachable windows over the active set; each output
    is the max over its active inputs (segment max on device)."""
    import jax.experimental.sparse as jsparse

    kernel = _tup3(kernel_size)
    stride_t = _tup3(stride if stride is not None else kernel_size)
    pad = _tup3(padding)
    shape = x.shape
    coords, values = _coords_values(x)
    out_coords, pairs, out_spatial = _cached_rulebook(
        coords, shape[1:4], kernel, stride_t, pad, (1, 1, 1), False,
    )
    n_out = len(out_coords)
    c = shape[-1]
    if not pairs:  # empty active set / no reachable window
        all_ins = np.zeros((0,), np.int32)
        all_outs = np.zeros((0,), np.int32)
    else:
        all_ins = np.concatenate([p[0] for p in pairs.values()])
        all_outs = np.concatenate([p[1] for p in pairs.values()])

    def run(vals):
        out = jnp.full((n_out, c), -jnp.inf, vals.dtype)
        return out.at[all_outs].max(vals[all_ins])

    out_vals = apply(run, x.values(), op_name="sparse_max_pool3d")
    bcoo = jsparse.BCOO(
        (out_vals._data, jnp.asarray(out_coords, jnp.int32)),
        shape=(shape[0],) + tuple(out_spatial) + (c,), unique_indices=True,
    )
    return SparseCooTensor(bcoo, values_tensor=out_vals)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Block-sparse attention (ref: the sparse_attention op,
    incubate/nn/functional and phi sparse attention kernels: attention
    restricted to a CSR-described sparsity pattern over [S, S]).

    query/key/value: dense [B, H, S, D] Tensors; ``sparse_mask`` is a
    SparseCsrTensor (or SparseCooTensor) of shape [S, S] (or
    [B*H, S, S]) whose stored entries mark the ALLOWED positions. On
    TPU the win comes from the masked softmax never materializing
    disallowed logits' exponentials; XLA fuses mask+softmax+matmul
    (a hand-gathered CSR loop would defeat the MXU)."""
    from .. import SparseCsrTensor

    if isinstance(sparse_mask, SparseCsrTensor):
        mask_dense = sparse_mask.to_dense()
    elif isinstance(sparse_mask, SparseCooTensor):
        mask_dense = sparse_mask.to_dense()
    else:
        mask_dense = sparse_mask
    md = mask_dense._data if isinstance(mask_dense, Tensor) else jnp.asarray(mask_dense)
    allowed = md != 0

    def run(q, k, v, *extra):
        d = q.shape[-1]
        scores = jnp.einsum("bhsd,bhtd->bhst", q, k) / jnp.sqrt(
            jnp.asarray(d, jnp.float32)
        ).astype(q.dtype)
        m = allowed
        if m.ndim == 2:  # [S, S] shared across batch+heads
            m = m[None, None]
        elif m.ndim == 3:  # [B*H, S, S]
            m = m.reshape(q.shape[0], q.shape[1], m.shape[-2], m.shape[-1])
        m = jnp.broadcast_to(m, scores.shape)
        i = 0
        if key_padding_mask is not None:
            # ADDITIVE float mask [B, S] (0 keeps, -inf masks) — the
            # same convention as attn_mask below
            scores = scores + extra[i][:, None, None, :]
            i += 1
        if attn_mask is not None:
            scores = scores + extra[i][None, None]
        scores = jnp.where(m, scores, -jnp.inf)
        p = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        p = jnp.where(jnp.isnan(p), 0.0, p).astype(q.dtype)  # all-masked rows
        return jnp.einsum("bhst,bhtd->bhsd", p, v)

    extra = [t for t in (key_padding_mask, attn_mask) if t is not None]
    return apply(run, query, key, value, *extra, op_name="sparse_attention")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NHWC", name=None):
    """Sparse 2-D convolution (ref: sparse/nn/functional/conv.py conv2d;
    same gather-GEMM-scatter rulebook as conv3d with nd=2)."""
    if groups != 1:
        raise ValueError("sparse conv2d supports groups=1")
    if data_format != "NHWC":
        raise ValueError("sparse conv2d uses the NHWC sparse layout")
    return _sparse_conv(x, weight, bias, stride, padding, dilation,
                        subm=False, op_name="sparse_conv2d", nd=2)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    """Submanifold sparse 2-D conv (ref: conv.py subm_conv2d): output
    coordinates == input coordinates."""
    if groups != 1:
        raise ValueError("sparse subm_conv2d supports groups=1")
    if data_format != "NHWC":
        raise ValueError("sparse subm_conv2d uses the NHWC sparse layout")
    return _sparse_conv(x, weight, bias, stride, padding, dilation,
                        subm=True, op_name="sparse_subm_conv2d", nd=2)


def subm_conv2d_igemm(x, weight, bias=None, stride=1, padding=0, dilation=1,
                      groups=1, data_format="NHWC", key=None, name=None):
    """ref: conv.py subm_conv2d_igemm — the reference's implicit-GEMM
    kernel variant; here every rulebook offset already lowers to one
    dense GEMM on the MXU, so the igemm entry point IS the regular
    path."""
    return subm_conv2d(x, weight, bias, stride, padding, dilation, groups,
                       data_format, key, name)


def subm_conv3d_igemm(x, weight, bias=None, stride=1, padding=0, dilation=1,
                      groups=1, data_format="NDHWC", key=None, name=None):
    """ref: conv.py subm_conv3d_igemm — see subm_conv2d_igemm."""
    return subm_conv3d(x, weight, bias, stride, padding, dilation, groups,
                       data_format, key, name)
