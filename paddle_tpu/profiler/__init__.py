"""paddle_tpu.profiler — performance tracing.

ref: python/paddle/profiler/ — profiler.py:346 (Profiler with
ProfilerTarget/scheduler/on_trace_ready), utils.py (RecordEvent),
timer.py:394 (benchmark ips tracking).

TPU-native redesign: the device-side tracer is jax.profiler (XLA/TPU
trace via TensorBoard's profile plugin — the role kineto/CUPTI plays in
the reference); RecordEvent lowers to jax.profiler.TraceAnnotation so
user spans show up inside the device trace. The chrome-trace exporter
writes the TensorBoard profile directory; ``make_scheduler`` reproduces
the reference's CLOSED/READY/RECORD state machine.
"""
from .profiler import (
    SortedKeys,
    SummaryView,
    export_protobuf,
    load_profiler_result,
)  # noqa: F401
from .profiler import (  # noqa: F401
    Profiler,
    ProfilerState,
    ProfilerTarget,
    RecordEvent,
    export_chrome_tracing,
    make_scheduler,
)
from .timer import benchmark  # noqa: F401

__all__ = [
    "Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
    "make_scheduler", "export_chrome_tracing", "benchmark",
]
