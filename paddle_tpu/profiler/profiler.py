"""Profiler core (ref: python/paddle/profiler/profiler.py:346).

Since ISSUE 12 this is a thin adapter over :mod:`paddle_tpu.obs`: every
:class:`RecordEvent` doubles as an obs span (so user annotations land
on the same Perfetto timeline as the serving/request spans) and step /
event durations feed registry histograms readable via
``python -m paddle_tpu.obs dump``. The jax.profiler device trace
integration is unchanged.
"""
from __future__ import annotations

import enum
import os
import time
from typing import Callable, Iterable, Optional, Union

from .. import obs as _obs
from ..obs.metrics import registry as _obs_registry

__all__ = [
    "Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
    "make_scheduler", "export_chrome_tracing",
]


class ProfilerState(enum.Enum):
    """ref: profiler.py ProfilerState."""

    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(enum.Enum):
    """ref: profiler.py ProfilerTarget — GPU/XPU become the TPU target."""

    CPU = 0
    GPU = 1
    TPU = 1  # alias: the device tracer is one XLA trace


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """ref: profiler.py make_scheduler — same state machine."""

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        period = closed + ready + record
        if repeat > 0 and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def _default_state_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD


# the innermost active Profiler; RecordEvent spans report here so
# summary() can print the user-annotation table (ref:
# profiler_statistic.py UserDefined view)
_active_profiler = None


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """ref: profiler.py export_chrome_tracing — returns an
    on_trace_ready callback; the jax trace directory is TensorBoard's
    profile format (open via tensorboard --logdir or Perfetto)."""

    def handler(prof: "Profiler"):
        prof._exported_dir = dir_name

    handler._dir = dir_name
    return handler


class RecordEvent:
    """User span annotation (ref: profiler/utils.py RecordEvent) —
    shows up in the XLA device trace via TraceAnnotation AND as an obs
    span named ``profiler:<name>`` on the host trace timeline, with the
    duration folded into the ``profiler_event_seconds`` histogram."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ctx = None
        self._sp = None
        self.begin_ns = None
        self.end_ns = None

    def begin(self):
        import jax.profiler

        self.begin_ns = time.perf_counter_ns()
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()
        if _obs.enabled():
            self._sp = _obs.start_span(f"profiler:{self.name}",
                                       tid="profiler")

    def end(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None
            self.end_ns = time.perf_counter_ns()
            _obs.finish_span(self._sp)
            self._sp = None
            _obs_registry().histogram(
                "profiler_event_seconds", {"name": self.name},
                help="RecordEvent span durations").observe(
                    (self.end_ns - self.begin_ns) * 1e-9)
            if _active_profiler is not None:
                _active_profiler._events.append(
                    (self.name, self.end_ns - self.begin_ns))

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class Profiler:
    """ref: profiler.py:346 Profiler — start/stop/step/export surface.

    The XLA trace captures device + host activity between start and
    stop; scheduler transitions drive jax.profiler.start_trace /
    stop_trace so only RECORD windows hit the (expensive) tracer.
    """

    def __init__(self, *, targets: Optional[Iterable] = None,
                 scheduler=None, on_trace_ready=None,
                 record_shapes: bool = False, profile_memory: bool = False,
                 timer_only: bool = False, emit_nvtx: bool = False,
                 custom_device_types=None, with_flops: bool = False):
        if scheduler is None:
            self._scheduler = _default_state_scheduler
        elif isinstance(scheduler, (tuple, list)):
            start, end = scheduler
            self._scheduler = make_scheduler(
                closed=max(start, 0), ready=0, record=end - start, repeat=1
            )
        else:
            self._scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._dir = getattr(on_trace_ready, "_dir", None) or "./profiler_log"
        self.step_num = 0
        self._state = ProfilerState.CLOSED
        self._tracing = False
        self._exported_dir = None
        self._step_times = []
        self._last_step_t = None
        self._events = []  # completed RecordEvent spans (name, dur_ns)

    # -- lifecycle -----------------------------------------------------
    def start(self):
        global _active_profiler
        self._prev_active = _active_profiler  # stack discipline: an
        # inner profiler must not deregister the outer one on stop
        _active_profiler = self
        self._state = self._scheduler(self.step_num)
        self._transition()
        self._last_step_t = time.perf_counter()

    def stop(self):
        global _active_profiler
        if _active_profiler is self:
            _active_profiler = getattr(self, "_prev_active", None)
        if self._tracing:
            self._stop_trace()
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)
        self._state = ProfilerState.CLOSED

    def step(self, num_steps: int = 1):
        now = time.perf_counter()
        if self._last_step_t is not None:
            per = (now - self._last_step_t) / num_steps
            self._step_times.append(per)
            _obs_registry().histogram(
                "profiler_step_seconds",
                help="Profiler.step() inter-step wall time").observe(per)
        self._last_step_t = now
        self.step_num += num_steps
        new_state = self._scheduler(self.step_num)
        if new_state != self._state:
            self._state = new_state
            self._transition()

    def _transition(self):
        should_trace = self._state in (
            ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN
        ) and not self._timer_only
        if should_trace and not self._tracing:
            self._start_trace()
        elif not should_trace and self._tracing:
            self._stop_trace()

    def _start_trace(self):
        import jax.profiler

        os.makedirs(self._dir, exist_ok=True)
        try:
            jax.profiler.start_trace(self._dir)
            self._tracing = True
        except RuntimeError:
            # tracer already active (nested profilers) — skip
            self._tracing = False

    def _stop_trace(self):
        import jax.profiler

        try:
            jax.profiler.stop_trace()
        finally:
            self._tracing = False
            self._exported_dir = self._dir

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- reporting -----------------------------------------------------
    def _collect_trace_ops(self):
        """Aggregate the captured XLA trace's complete events into
        per-op statistics, grouped by execution lane.

        The jax tracer writes the TensorBoard profile format; the
        chrome-trace file inside it carries one complete ('ph':'X')
        event per executed op/kernel with its duration, and 'M'
        metadata events naming each pid's lane ('/device:TPU:0 ...',
        host threads, ...). This is the device-event source the
        reference aggregates in profiler_statistic.py.

        Returns {lane_label: {op_name: [count, total_us, max_us]}}.
        """
        import glob
        import gzip
        import json as _json

        trace_dir = self._exported_dir or self._dir
        paths = sorted(
            glob.glob(os.path.join(
                trace_dir, "plugins", "profile", "*", "*.trace.json.gz")),
            key=os.path.getmtime)
        if not paths:
            return {}
        with gzip.open(paths[-1], "rt") as f:
            events = _json.load(f).get("traceEvents", [])
        pid_label = {}
        for e in events:
            if e.get("ph") == "M" and e.get("name") == "process_name":
                pid_label[e.get("pid")] = e.get("args", {}).get("name", "?")
        lanes = {}
        for e in events:
            if e.get("ph") != "X" or "dur" not in e:
                continue
            name = e.get("name", "?")
            if name.startswith(("$", "<")):
                # raw python source frames ("$file.py:123 fn") — the
                # table shows logical ops/kernels, like the reference's
                continue
            label = pid_label.get(e.get("pid"), "?")
            ops = lanes.setdefault(label, {})
            st = ops.setdefault(name, [0, 0.0, 0.0])
            st[0] += 1
            st[1] += float(e["dur"])
            st[2] = max(st[2], float(e["dur"]))
        return lanes

    @staticmethod
    def _print_table(title, rows, total_us, top_k):
        """rows: [(name, count, total_us, max_us)] — the reference's
        op-summary table shape (profiler_statistic.py _build_table)."""
        print(f"\n{'-' * 78}\n{title}\n{'-' * 78}")
        print(f"{'Name':<40} {'Calls':>6} {'Total(ms)':>10} "
              f"{'Avg(ms)':>9} {'Max(ms)':>9} {'Ratio':>6}")
        for name, count, tot, mx in rows[:top_k]:
            ratio = tot / total_us if total_us else 0.0
            shown = name if len(name) <= 40 else name[:37] + "..."
            print(f"{shown:<40} {count:>6} {tot / 1000:>10.3f} "
                  f"{tot / 1000 / max(count, 1):>9.3f} {mx / 1000:>9.3f} "
                  f"{ratio:>6.1%}")

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms", top_k: int = 20):
        """Step-time overview + per-op device/host tables aggregated
        from the captured trace + user RecordEvent spans + a device
        memory view (ref: profiler/profiler_statistic.py — overview,
        op summary, UserDefined and memory views)."""
        import numpy as np

        if self._step_times:
            ts = np.asarray(self._step_times) * 1000.0
            print(
                f"Profiler summary over {len(ts)} steps: "
                f"mean {ts.mean():.3f} ms, p50 {np.percentile(ts, 50):.3f} ms, "
                f"p99 {np.percentile(ts, 99):.3f} ms"
                + (f"; trace exported to {self._exported_dir}"
                   if self._exported_dir else "")
            )
        else:
            print("Profiler: no steps recorded")

        if op_detail:
            lanes = self._collect_trace_ops()
            order = sorted_by or SortedKeys.GPUTotal
            key = {
                SortedKeys.GPUMax: lambda r: r[3],
                SortedKeys.CPUMax: lambda r: r[3],
                SortedKeys.GPUAvg: lambda r: r[2] / max(r[1], 1),
                SortedKeys.CPUAvg: lambda r: r[2] / max(r[1], 1),
            }.get(order, lambda r: r[2])
            # device lanes first (the tables that matter), then host
            def lane_rank(label):
                return (0 if "device" in label.lower()
                        or "tpu" in label.lower() else 1, label)

            for label in sorted(lanes, key=lane_rank):
                rows = sorted(
                    ((n, c, t, m) for n, (c, t, m) in lanes[label].items()),
                    key=key, reverse=True)
                total = sum(r[2] for r in rows)
                self._print_table(f"Op summary — {label}", rows, total,
                                  top_k)

        if self._events:
            agg = {}
            for name, dur_ns in self._events:
                st = agg.setdefault(name, [0, 0.0, 0.0])
                st[0] += 1
                st[1] += dur_ns / 1000.0
                st[2] = max(st[2], dur_ns / 1000.0)
            rows = sorted(((n, c, t, m) for n, (c, t, m) in agg.items()),
                          key=lambda r: r[2], reverse=True)
            self._print_table("UserDefined summary (RecordEvent)", rows,
                              sum(r[2] for r in rows), top_k)

        # memory view: live device telemetry (ref MemorySummary)
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats() or {}
        except Exception:
            stats = {}
        if stats:
            used = stats.get("bytes_in_use", 0)
            peak = stats.get("peak_bytes_in_use", 0)
            limit = stats.get("bytes_limit", 0)
            print(f"\nDevice memory: in use {used / 2**20:.1f} MiB, "
                  f"peak {peak / 2**20:.1f} MiB"
                  + (f", limit {limit / 2**20:.1f} MiB" if limit else ""))

    def export(self, path: Optional[str] = None, format: str = "json"):
        return self._exported_dir


class SortedKeys(enum.Enum):
    """ref: profiler/profiler_statistic.py SortedKeys — summary sort
    orders."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView(enum.Enum):
    """ref: profiler/profiler.py SummaryView — which summary tables to
    print."""

    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def export_protobuf(dir_name: str, worker_name: Optional[str] = None):
    """ref: profiler.py export_protobuf — on-trace-ready handler writing
    the profile under ``dir_name``. jax.profiler already emits xplane
    protobufs, so this is the same handler as export_chrome_tracing with
    the protobuf layout kept."""
    return export_chrome_tracing(dir_name, worker_name)


def load_profiler_result(filename: str):
    """ref: profiler.py load_profiler_result — load an exported trace.
    Returns the raw bytes of the xplane/trace file (the reference
    returns a ProfilerResult handle; the TPU trace is consumed by
    TensorBoard/Perfetto rather than an in-process reader)."""
    with open(filename, "rb") as f:
        return f.read()
