"""Profiler core (ref: python/paddle/profiler/profiler.py:346)."""
from __future__ import annotations

import enum
import os
import time
from typing import Callable, Iterable, Optional, Union

__all__ = [
    "Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
    "make_scheduler", "export_chrome_tracing",
]


class ProfilerState(enum.Enum):
    """ref: profiler.py ProfilerState."""

    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(enum.Enum):
    """ref: profiler.py ProfilerTarget — GPU/XPU become the TPU target."""

    CPU = 0
    GPU = 1
    TPU = 1  # alias: the device tracer is one XLA trace


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """ref: profiler.py make_scheduler — same state machine."""

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        period = closed + ready + record
        if repeat > 0 and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def _default_state_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """ref: profiler.py export_chrome_tracing — returns an
    on_trace_ready callback; the jax trace directory is TensorBoard's
    profile format (open via tensorboard --logdir or Perfetto)."""

    def handler(prof: "Profiler"):
        prof._exported_dir = dir_name

    handler._dir = dir_name
    return handler


class RecordEvent:
    """User span annotation (ref: profiler/utils.py RecordEvent) —
    shows up in the XLA device trace via TraceAnnotation."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ctx = None
        self.begin_ns = None
        self.end_ns = None

    def begin(self):
        import jax.profiler

        self.begin_ns = time.perf_counter_ns()
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()

    def end(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None
            self.end_ns = time.perf_counter_ns()

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class Profiler:
    """ref: profiler.py:346 Profiler — start/stop/step/export surface.

    The XLA trace captures device + host activity between start and
    stop; scheduler transitions drive jax.profiler.start_trace /
    stop_trace so only RECORD windows hit the (expensive) tracer.
    """

    def __init__(self, *, targets: Optional[Iterable] = None,
                 scheduler=None, on_trace_ready=None,
                 record_shapes: bool = False, profile_memory: bool = False,
                 timer_only: bool = False, emit_nvtx: bool = False,
                 custom_device_types=None, with_flops: bool = False):
        if scheduler is None:
            self._scheduler = _default_state_scheduler
        elif isinstance(scheduler, (tuple, list)):
            start, end = scheduler
            self._scheduler = make_scheduler(
                closed=max(start, 0), ready=0, record=end - start, repeat=1
            )
        else:
            self._scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._dir = getattr(on_trace_ready, "_dir", None) or "./profiler_log"
        self.step_num = 0
        self._state = ProfilerState.CLOSED
        self._tracing = False
        self._exported_dir = None
        self._step_times = []
        self._last_step_t = None

    # -- lifecycle -----------------------------------------------------
    def start(self):
        self._state = self._scheduler(self.step_num)
        self._transition()
        self._last_step_t = time.perf_counter()

    def stop(self):
        if self._tracing:
            self._stop_trace()
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)
        self._state = ProfilerState.CLOSED

    def step(self, num_steps: int = 1):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append((now - self._last_step_t) / num_steps)
        self._last_step_t = now
        self.step_num += num_steps
        new_state = self._scheduler(self.step_num)
        if new_state != self._state:
            self._state = new_state
            self._transition()

    def _transition(self):
        should_trace = self._state in (
            ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN
        ) and not self._timer_only
        if should_trace and not self._tracing:
            self._start_trace()
        elif not should_trace and self._tracing:
            self._stop_trace()

    def _start_trace(self):
        import jax.profiler

        os.makedirs(self._dir, exist_ok=True)
        try:
            jax.profiler.start_trace(self._dir)
            self._tracing = True
        except RuntimeError:
            # tracer already active (nested profilers) — skip
            self._tracing = False

    def _stop_trace(self):
        import jax.profiler

        try:
            jax.profiler.stop_trace()
        finally:
            self._tracing = False
            self._exported_dir = self._dir

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- reporting -----------------------------------------------------
    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """Host-side step-time summary; the op-level breakdown lives in
        the exported XLA trace (TensorBoard), which supersedes the
        reference's table printer."""
        if not self._step_times:
            print("Profiler: no steps recorded")
            return
        import numpy as np

        ts = np.asarray(self._step_times) * 1000.0
        print(
            f"Profiler summary over {len(ts)} steps: "
            f"mean {ts.mean():.3f} ms, p50 {np.percentile(ts, 50):.3f} ms, "
            f"p99 {np.percentile(ts, 99):.3f} ms"
            + (f"; trace exported to {self._exported_dir}" if self._exported_dir else "")
        )

    def export(self, path: Optional[str] = None, format: str = "json"):
        return self._exported_dir


class SortedKeys(enum.Enum):
    """ref: profiler/profiler_statistic.py SortedKeys — summary sort
    orders."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView(enum.Enum):
    """ref: profiler/profiler.py SummaryView — which summary tables to
    print."""

    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def export_protobuf(dir_name: str, worker_name: Optional[str] = None):
    """ref: profiler.py export_protobuf — on-trace-ready handler writing
    the profile under ``dir_name``. jax.profiler already emits xplane
    protobufs, so this is the same handler as export_chrome_tracing with
    the protobuf layout kept."""
    return export_chrome_tracing(dir_name, worker_name)


def load_profiler_result(filename: str):
    """ref: profiler.py load_profiler_result — load an exported trace.
    Returns the raw bytes of the xplane/trace file (the reference
    returns a ProfilerResult handle; the TPU trace is consumed by
    TensorBoard/Perfetto rather than an in-process reader)."""
    with open(filename, "rb") as f:
        return f.read()
