"""Benchmark timer (ref: python/paddle/profiler/timer.py:394 —
paddle.profiler.benchmark() singleton with step()/ips semantics, used
by hapi and launch to report throughput)."""
from __future__ import annotations

import time
from typing import Optional

from ..obs.metrics import registry as _obs_registry

__all__ = ["benchmark", "Benchmark"]


class _Event:
    def __init__(self):
        self.reader_cost_avg = 0.0
        self.batch_cost_avg = 0.0
        self.ips_avg = 0.0
        self.steps = 0


class Benchmark:
    """Throughput tracker: call before_reader/after_reader around data
    loading and step(batch_size) per iteration."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._event = _Event()
        self._reader_t0 = None
        self._step_t0 = None
        self._reader_cost = 0.0
        self._warmup = 2

    def before_reader(self):
        self._reader_t0 = time.perf_counter()

    def after_reader(self):
        if self._reader_t0 is not None:
            self._reader_cost = time.perf_counter() - self._reader_t0

    def step(self, batch_size: Optional[int] = None):
        now = time.perf_counter()
        e = self._event
        if self._step_t0 is not None:
            cost = now - self._step_t0
            e.steps += 1
            if e.steps > self._warmup:
                n = e.steps - self._warmup
                e.batch_cost_avg += (cost - e.batch_cost_avg) / n
                e.reader_cost_avg += (self._reader_cost - e.reader_cost_avg) / n
                if batch_size and e.batch_cost_avg > 0:
                    e.ips_avg = batch_size / e.batch_cost_avg
                # obs registry mirror (ISSUE 12): the live averages as
                # gauges, so the throughput line shows up in obs dumps
                reg = _obs_registry()
                reg.gauge("benchmark_ips",
                          help="benchmark() samples/s").set(e.ips_avg)
                reg.gauge("benchmark_batch_cost_seconds",
                          help="benchmark() batch cost avg").set(
                              e.batch_cost_avg)
        self._step_t0 = now

    def step_info(self, unit: str = "samples") -> str:
        e = self._event
        return (
            f"reader_cost: {e.reader_cost_avg:.5f} s, "
            f"batch_cost: {e.batch_cost_avg:.5f} s, "
            f"ips: {e.ips_avg:.2f} {unit}/s"
        )

    @property
    def ips(self) -> float:
        return self._event.ips_avg


_instance: Optional[Benchmark] = None


def benchmark() -> Benchmark:
    """ref: timer.py benchmark() — process singleton."""
    global _instance
    if _instance is None:
        _instance = Benchmark()
    return _instance
