"""paddle_tpu.geometric — graph message-passing primitives.

ref: python/paddle/geometric/ — message_passing/send_recv.py
(send_u_recv :33, send_ue_recv :142, send_uv :312), math.py
(segment_sum/mean/min/max), sampling/.

TPU-native: gather/segment-reduce lower to jax.ops.segment_sum-style
primitives with a **static** ``out_size`` (pass it for jit; defaults to
the data-dependent max+1 eagerly, matching the reference's dynamic
shape behavior in dygraph).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..base.tape import apply
from ..base.tensor import Tensor

__all__ = [
    "segment_sum", "segment_mean", "segment_min", "segment_max",
    "send_u_recv", "send_ue_recv", "send_uv",
 "reindex_graph", "reindex_heter_graph", "sample_neighbors", "weighted_sample_neighbors",]


def _num_segments(ids, out_size):
    if out_size is not None:
        return int(out_size)
    arr = np.asarray(jax.device_get(ids._data if isinstance(ids, Tensor) else ids))
    return int(arr.max()) + 1 if arr.size else 0


def _segment(op_name, reducer_fill):
    jax_op = {
        "sum": jax.ops.segment_sum,
        "min": jax.ops.segment_min,
        "max": jax.ops.segment_max,
    }

    def op(data, segment_ids, name=None, out_size=None):
        n = _num_segments(segment_ids, out_size)

        def f(d, ids):
            if op_name == "mean":
                s = jax.ops.segment_sum(d, ids, num_segments=n)
                cnt = jax.ops.segment_sum(jnp.ones_like(ids, d.dtype), ids,
                                          num_segments=n)
                shape = (n,) + (1,) * (d.ndim - 1)
                return s / jnp.maximum(cnt.reshape(shape), 1)
            out = jax_op[op_name](d, ids, num_segments=n)
            if reducer_fill is not None:
                # empty segments: the reference yields 0, jax yields ±inf
                cnt = jax.ops.segment_sum(jnp.ones_like(ids, jnp.int32), ids,
                                          num_segments=n)
                shape = (n,) + (1,) * (d.ndim - 1)
                out = jnp.where(cnt.reshape(shape) > 0, out, 0)
            return out

        return apply(f, data, segment_ids, op_name=f"segment_{op_name}")

    return op


segment_sum = _segment("sum", None)
segment_mean = _segment("mean", None)
segment_min = _segment("min", 0)
segment_max = _segment("max", 0)


def send_u_recv(x, src_index, dst_index, reduce_op: str = "sum",
                out_size: Optional[int] = None, name=None):
    """Gather x at src, reduce onto dst (ref: send_recv.py:33)."""
    reduce_op = reduce_op.lower()
    seg = {"sum": segment_sum, "mean": segment_mean,
           "min": segment_min, "max": segment_max}[reduce_op]
    n = out_size if out_size is not None else int(x.shape[0])

    def gather(a, idx):
        return a[idx]

    msgs = apply(gather, x, src_index, op_name="gather")
    return seg(msgs, dst_index, out_size=n)


def send_ue_recv(x, y, src_index, dst_index, message_op: str = "add",
                 reduce_op: str = "sum", out_size: Optional[int] = None,
                 name=None):
    """Gather x at src, combine with edge feature y, reduce onto dst
    (ref: send_recv.py:142)."""
    ops = {
        "add": lambda a, b: a + b,
        "sub": lambda a, b: a - b,
        "mul": lambda a, b: a * b,
        "div": lambda a, b: a / b,
    }
    combine = ops[message_op.lower()]
    n = out_size if out_size is not None else int(x.shape[0])

    def f(a, e, idx):
        m = a[idx]
        if e.ndim < m.ndim:
            e = e.reshape(e.shape + (1,) * (m.ndim - e.ndim))
        return combine(m, e)

    msgs = apply(f, x, y, src_index, op_name="send_ue")
    seg = {"sum": segment_sum, "mean": segment_mean,
           "min": segment_min, "max": segment_max}[reduce_op.lower()]
    return seg(msgs, dst_index, out_size=n)


def send_uv(x, y, src_index, dst_index, message_op: str = "add", name=None):
    """Per-edge message from both endpoints (ref: send_recv.py:312)."""
    ops = {
        "add": lambda a, b: a + b,
        "sub": lambda a, b: a - b,
        "mul": lambda a, b: a * b,
        "div": lambda a, b: a / b,
    }
    combine = ops[message_op.lower()]

    def f(a, b, si, di):
        return combine(a[si], b[di])

    return apply(f, x, y, src_index, dst_index, op_name="send_uv")


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None, name=None):
    """ref: geometric/reindex.py reindex_graph."""
    from ..incubate import graph_reindex

    return graph_reindex(x, neighbors, count, value_buffer, index_buffer)


def reindex_heter_graph(x, neighbors, count, value_buffer=None, index_buffer=None, name=None):
    """ref: geometric/reindex.py reindex_heter_graph — per-edge-type
    neighbor lists reindexed against one shared node mapping."""
    import numpy as np

    from ..incubate import graph_reindex

    nbs = [n for n in neighbors]
    cnts = [c for c in count]
    from ..base.tensor import to_tensor

    nb_cat = np.concatenate([np.asarray(n.numpy()).reshape(-1) for n in nbs])
    cnt_cat = np.concatenate([np.asarray(c.numpy()).reshape(-1) for c in cnts])
    # centers repeat once per edge type
    xs = np.asarray(x.numpy()).reshape(-1)
    ctr = np.tile(xs, len(nbs))
    return graph_reindex(to_tensor(ctr), to_tensor(nb_cat.astype(np.int64)),
                         to_tensor(cnt_cat.astype(np.int64)))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """ref: geometric/sampling/neighbors.py sample_neighbors."""
    from ..incubate import graph_sample_neighbors

    return graph_sample_neighbors(row, colptr, input_nodes, sample_size,
                                  eids, return_eids, perm_buffer)


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weighted variant (ref: neighbors.py weighted_sample_neighbors):
    neighbors drawn without replacement proportionally to edge weight
    (zero-weight edges excluded). Shares graph_sample_neighbors' body."""
    from ..incubate import graph_sample_neighbors

    return graph_sample_neighbors(row, colptr, input_nodes, sample_size,
                                  eids, return_eids, edge_weight=edge_weight)
