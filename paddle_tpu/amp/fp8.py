"""fp8 delayed-scaling GEMMs — training + prefill (ISSUE 17 lever (b)).

Generalizes the Int8InferenceLinear pattern (quantization/__init__.py)
from inference-only int8 to TRAINING: ``fp8_linear`` runs the matmul on
fp8 operands with a custom VJP, following the delayed-scaling recipe of
Micikevicius et al., *FP8 Formats for Deep Learning* (2022):

- forward: x and w cast to **e4m3** (max 448; 3 mantissa bits — the
  activations/weights format) with per-tensor scales derived from an
  amax HISTORY recorded on previous steps, accumulation in >= bf16
  (``preferred_element_type=f32`` — the MXU's fp8 path accumulates
  wide natively; off-TPU XLA computes the same f32 accumulation).
- backward: dy cast to **e5m2** (max 57344; wider exponent — gradient
  magnitudes swing orders across layers) with a just-in-time scale
  (grad statistics move too fast step-to-step for a useful history);
  dgrad/wgrad run fp8 x fp8 against the saved e4m3 operands.
- scales: ``scale = E4M3_MAX / max(amax_history)`` — the cast uses the
  scale derived BEFORE this step's amax is recorded (delayed scaling:
  no serializing amax round-trip inside the step). An empty history
  (fresh layer, or eval/prefill without a warmup) falls back to the
  current tensor's amax just-in-time.

``Fp8Linear`` wraps an existing ``nn.Linear`` keeping the SAME weight/
bias parameters (drop-in for training — the optimizer keeps driving
the master weights; only the GEMM operands are fp8), with the amax
histories as ``register_buffer`` entries so ``paddle_tpu.jit`` threads
them through compiled train steps. ``convert_to_fp8`` swaps every
Linear in a model (the convert_to_weight_only pattern).

Quality contract (tests/test_fp8.py): per-tensor rel-err of the fp8
linear vs the float linear stays within the gate (int8's rel-err test
style, 0.031-class), and an fp8-converted tiny model's N-step loss
curve tracks the bf16 run within tolerance.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..base.tape import apply, no_grad
from ..base.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["Fp8Linear", "convert_to_fp8", "fp8_linear",
           "E4M3_MAX", "E5M2_MAX"]

E4M3_MAX = 448.0    # jnp.finfo(float8_e4m3fn).max
E5M2_MAX = 57344.0  # jnp.finfo(float8_e5m2).max


def _cast_fp8(x, scale, dtype, fmax):
    return jnp.clip(x.astype(jnp.float32) * scale, -fmax, fmax).astype(dtype)


def _jit_scale(t, fmax):
    """Just-in-time per-tensor scale: fmax / amax (1.0 for an all-zero
    tensor)."""
    amax = jnp.max(jnp.abs(t)).astype(jnp.float32)
    return jnp.where(amax > 0, fmax / amax, 1.0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _fp8_dot(x_dtype, w_dtype, x, w, x_scale, w_scale):
    y, _ = _fp8_dot_fwd(x_dtype, w_dtype, x, w, x_scale, w_scale)
    return y


def _fp8_dot_fwd(x_dtype, w_dtype, x, w, x_scale, w_scale):
    qx = _cast_fp8(x, x_scale, jnp.float8_e4m3fn, E4M3_MAX)
    qw = _cast_fp8(w, w_scale, jnp.float8_e4m3fn, E4M3_MAX)
    acc = jax.lax.dot_general(
        qx, qw, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    y = (acc / (x_scale * w_scale)).astype(x_dtype)
    # residuals are the fp8 images: backward re-uses them as the e4m3
    # operands of dgrad/wgrad — half the residual HBM of a bf16 save
    return y, (qx, qw, x_scale, w_scale)


def _fp8_dot_bwd(x_dtype, w_dtype, res, dy):
    qx, qw, x_scale, w_scale = res
    dy_scale = _jit_scale(dy, E5M2_MAX)
    qdy = _cast_fp8(dy, dy_scale, jnp.float8_e5m2, E5M2_MAX)
    # dx = dy @ w.T
    dx = jax.lax.dot_general(
        qdy, qw, (((dy.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dx = (dx / (dy_scale * w_scale)).astype(x_dtype)
    # dw = x.T @ dy (contract every leading dim)
    lead = tuple(range(qx.ndim - 1))
    dw = jax.lax.dot_general(
        qx, qdy, ((lead, lead), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dw = (dw / (x_scale * dy_scale)).astype(w_dtype)
    # scales are amax-derived controls, not trainable signal
    return dx, dw, jnp.zeros_like(x_scale), jnp.zeros_like(w_scale)


_fp8_dot.defvjp(_fp8_dot_fwd, _fp8_dot_bwd)


def fp8_linear(x, weight, bias=None, x_scale=None, w_scale=None):
    """y = fp8_dot(x, w) + bias at the tape level. ``x_scale``/
    ``w_scale`` are per-tensor f32 cast scales (Tensors; from an
    Fp8Linear's delayed-scaling histories) — omitted, each is computed
    just-in-time from the tensor's current amax."""
    has_xs = x_scale is not None
    has_ws = w_scale is not None
    has_b = bias is not None

    def _f(a, w, *rest):
        i = 0
        if has_xs:
            xs = rest[i]; i += 1  # noqa: E702
        else:
            xs = _jit_scale(a, E4M3_MAX)
        if has_ws:
            ws = rest[i]; i += 1  # noqa: E702
        else:
            ws = _jit_scale(w, E4M3_MAX)
        out = _fp8_dot(str(a.dtype), str(w.dtype), a, w, xs, ws)
        if has_b:
            out = out + rest[i]
        return out

    args = [x, weight]
    if has_xs:
        args.append(x_scale)
    if has_ws:
        args.append(w_scale)
    if has_b:
        args.append(bias)
    return apply(_f, *args, op_name="fp8_linear")


class Fp8Linear(Layer):
    """Drop-in fp8 training Linear: wraps an existing ``nn.Linear``
    KEEPING its weight/bias parameters (the optimizer state, master
    weights and sharding placement survive the conversion untouched —
    only the GEMM runs on fp8 operands). Amax histories live as
    buffers, so ``to_static`` threads them and a compiled train step
    carries the delayed-scaling state on device."""

    def __init__(self, linear, history_len: int = 16):
        super().__init__()
        self.weight = linear.weight
        self.bias = linear.bias
        self.history_len = int(history_len)
        # two DISTINCT zero arrays: buffers thread through to_static
        # with donate_state, and one shared buffer would be donated
        # twice in a single compiled call
        self.register_buffer("amax_history_x", Tensor(
            jnp.zeros((self.history_len,), jnp.float32), _internal=True))
        self.register_buffer("amax_history_w", Tensor(
            jnp.zeros((self.history_len,), jnp.float32), _internal=True))

    def _scale_from(self, hist, cur):
        def _f(h, c):
            hmax = jnp.max(h)
            amax = jnp.where(hmax > 0, hmax, c)
            return jnp.where(amax > 0, E4M3_MAX / amax, 1.0).astype(
                jnp.float32)

        return apply(_f, hist, cur, op_name="fp8_scale")

    def forward(self, x):
        with no_grad():
            amax = lambda a: jnp.max(jnp.abs(a)).astype(jnp.float32)  # noqa: E731
            cur_x = apply(amax, x, op_name="fp8_amax")
            cur_w = apply(amax, self.weight, op_name="fp8_amax")
            # delayed scaling: cast with the scale the HISTORY implies,
            # THEN record this step's amax for future steps
            xs = self._scale_from(self.amax_history_x, cur_x)
            ws = self._scale_from(self.amax_history_w, cur_w)
            if self.training:
                roll = lambda h, c: jnp.concatenate([h[1:], c.reshape(1)])  # noqa: E731,E501
                self.amax_history_x.set_value(apply(
                    roll, self.amax_history_x, cur_x,
                    op_name="fp8_amax_roll")._data)
                self.amax_history_w.set_value(apply(
                    roll, self.amax_history_w, cur_w,
                    op_name="fp8_amax_roll")._data)
        return fp8_linear(x, self.weight, self.bias,
                          x_scale=xs, w_scale=ws)

    def extra_repr(self):
        return (f"in_features={self.weight.shape[0]}, "
                f"out_features={self.weight.shape[1]}, fp8=e4m3/e5m2, "
                f"history_len={self.history_len}")


def convert_to_fp8(model, exclude=lambda name: False,
                   history_len: int = 16) -> int:
    """Swap every ``nn.Linear`` in ``model`` for an :class:`Fp8Linear`
    sharing the same parameters (the convert_to_weight_only pattern).
    Returns the number of layers converted. Typical exclusions: the
    lm_head (its logits feed the loss — fp8 there costs measurable
    perplexity for one GEMM of savings)."""
    from ..nn.layer.common import Linear

    n = 0
    for name, sub in list(model.named_sublayers(include_self=False)):
        if not isinstance(sub, Linear) or exclude(name):
            continue
        parent = model
        parts = name.split(".")
        for p in parts[:-1]:
            parent = getattr(parent, p)
        setattr(parent, parts[-1], Fp8Linear(sub, history_len=history_len))
        n += 1
    return n
