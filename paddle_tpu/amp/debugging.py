"""AMP debugging tools: per-op tensor checking, operator stats, and
cross-dtype accuracy comparison.

TPU-native redesign of the reference's amp debugging stack (ref:
python/paddle/amp/debugging.py:156 TensorCheckerConfig, :455
enable_operator_stats_collection, :534 collect_operator_stats, :569
compare_accuracy, :628 enable_tensor_checker). The reference instruments
its generated ad_func layer and GPU kernel logs; here every op already
flows through ONE dispatch point (base.tape.apply/_wrap_outputs), so the
collector and checker are tape observers:

- observers see each op's RAW output arrays right after execution and
  compute nan/inf counts, absmax/absmin/mean on host (a device sync per
  op — this is a debugging tool, not a fast path);
- collection is EAGER-mode: under a jit trace outputs are abstract
  tracers and are skipped (run the step un-jitted to inspect it — the
  same code runs in both regimes by tape design);
- training-step tracking for ``debug_step`` ranges ticks on each
  ``run_backward`` entry (the reference ticks its iter_id in the
  optimizer hook).

``compare_accuracy`` keeps the reference's dump-file signature
(dump_path, another_dump_path, output_filename) over JSONL stats dumps
written by ``collect_operator_stats(output_dir=...)``, writing a CSV
(not xlsx — no openpyxl dependency) — and additionally accepts a
callable first argument to run a function under two dtypes back-to-back
and diff the per-op stats directly.
"""
from __future__ import annotations

import contextlib
import json
import os
import traceback
from enum import Enum
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "DebugMode",
    "TensorCheckerConfig",
    "check_numerics",
    "enable_operator_stats_collection",
    "disable_operator_stats_collection",
    "collect_operator_stats",
    "enable_tensor_checker",
    "disable_tensor_checker",
    "compare_accuracy",
    "check_layer_numerics",
]

_FP16_MAX = 65504.0
_FP16_TINY = 6.103515625e-05  # smallest normal float16


class DebugMode(Enum):
    """Checker behavior (ref: debugging.py:41).

    - CHECK_NAN_INF_AND_ABORT: raise on NaN/Inf outputs.
    - CHECK_NAN_INF: report NaN/Inf outputs, keep running.
    - CHECK_ALL_FOR_OVERFLOW: report fp32 outputs outside the float16
      representable range (overflow/underflow candidates for O1).
    - CHECK_ALL: report key stats for every checked op.
    """

    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL_FOR_OVERFLOW = 2
    CHECK_ALL = 3


def _leaf_stats(arr) -> Optional[dict]:
    """Host-side stats for one raw output array; None for non-float or
    abstract (traced) values."""
    import jax.core as jcore

    if isinstance(arr, jcore.Tracer):
        return None
    try:
        a = np.asarray(arr)
    except Exception:  # pragma: no cover — non-array leaf
        return None
    dtype_str = str(a.dtype)
    if a.dtype.kind not in "fcV" or a.size == 0:
        return None
    if a.dtype.kind == "V":
        # ml_dtypes (bfloat16, float8_*) register as numpy void kinds;
        # they're exactly the dtypes AMP debugging exists for — widen to
        # float32 for the stats math (NaN/Inf preserved)
        try:
            a = a.astype(np.float32)
        except Exception:
            return None  # a genuine void/struct dtype
    af = np.abs(a).astype(np.float64)  # complex -> magnitude
    finite = np.isfinite(a)
    num_nan = int(np.isnan(a).sum())
    num_inf = int(np.isinf(a).sum())
    if finite.any():
        fin = af[finite]
        absmax = float(fin.max())
        nonzero = fin[fin > 0]
        absmin = float(nonzero.min()) if nonzero.size else 0.0
        mean = float(fin.mean())
    else:
        absmax = absmin = mean = float("nan")
    return {
        "dtype": dtype_str,
        "numel": int(a.size),
        "num_nan": num_nan,
        "num_inf": num_inf,
        "absmax": absmax,
        "absmin": absmin,
        "mean": mean,
    }


# ---------------------------------------------------------------------------
# Operator stats collection (ref: debugging.py:455-568)
# ---------------------------------------------------------------------------


class _StatsCollector:
    """Aggregates per-(op, dtype) stats from the tape observer."""

    def __init__(self):
        # (op, dtype) -> {calls, num_nan, num_inf, absmax, absmin, mean_sum}
        self.stats: Dict[Tuple[str, str], dict] = {}

    def __call__(self, op_name: str, leaves: Sequence):
        op = op_name or "op"  # backward ops arrive as "grad_<op>"
        seen_dtypes = set()  # "calls" = op INVOCATIONS per dtype
        for leaf in leaves:
            st = _leaf_stats(leaf)
            if st is None:
                continue
            key = (op, st["dtype"])
            ent = self.stats.setdefault(
                key,
                {"calls": 0, "leaves": 0, "num_nan": 0, "num_inf": 0,
                 "absmax": 0.0, "absmin": float("inf"),
                 "_mean_sum": 0.0, "_mean_count": 0},
            )
            if st["dtype"] not in seen_dtypes:
                seen_dtypes.add(st["dtype"])
                ent["calls"] += 1
            ent["leaves"] += 1
            ent["num_nan"] += st["num_nan"]
            ent["num_inf"] += st["num_inf"]
            if not np.isnan(st["absmax"]):
                ent["absmax"] = max(ent["absmax"], st["absmax"])
                if st["absmin"] > 0:
                    ent["absmin"] = min(ent["absmin"], st["absmin"])
                ent["_mean_sum"] += st["mean"]
                ent["_mean_count"] += 1

    def rows(self) -> List[dict]:
        out = []
        for (op, dt), ent in sorted(self.stats.items()):
            out.append({
                "op": op, "dtype": dt, "calls": ent["calls"],
                "num_nan": ent["num_nan"], "num_inf": ent["num_inf"],
                "absmax": ent["absmax"],
                "absmin": 0.0 if ent["absmin"] == float("inf") else ent["absmin"],
                "mean": ent["_mean_sum"] / max(ent["_mean_count"], 1),
            })
        return out

    def summary_table(self) -> str:
        """Printable table in the spirit of the reference's
        _print_operator_stats (ref: debugging.py:411): op, dtype call
        counts, nan/inf totals, absmax."""
        rows = self.rows()
        if not rows:
            return "<op stats: no float operator outputs observed>"
        header = (
            f"{'op':<28}{'dtype':<12}{'calls':>7}{'num_nan':>9}"
            f"{'num_inf':>9}{'absmax':>13}{'absmin':>13}{'mean':>13}"
        )
        lines = [header, "-" * len(header)]
        for r in rows:
            lines.append(
                f"{r['op']:<28}{r['dtype']:<12}{r['calls']:>7}"
                f"{r['num_nan']:>9}{r['num_inf']:>9}{r['absmax']:>13.4e}"
                f"{r['absmin']:>13.4e}{r['mean']:>13.4e}"
            )
        return "\n".join(lines)

    def dump(self, path: str) -> str:
        with open(path, "w") as f:
            for r in self.rows():
                f.write(json.dumps(r) + "\n")
        return path


_active_collector: Optional[_StatsCollector] = None


def enable_operator_stats_collection():
    """Start collecting per-op output stats at the tape dispatch point
    (ref: debugging.py:455). Eager-mode only; traced ops are skipped."""
    global _active_collector
    from ..base import tape

    if _active_collector is not None:
        return
    _active_collector = _StatsCollector()
    tape._op_observers.append(_active_collector)


def disable_operator_stats_collection():
    """Stop collecting and print the summary table (ref: debugging.py:493).
    Returns the list of per-(op, dtype) stat rows."""
    global _active_collector
    from ..base import tape

    if _active_collector is None:
        return []
    col = _active_collector
    _active_collector = None
    try:
        tape._op_observers.remove(col)
    except ValueError:
        pass
    print(col.summary_table())
    return col.rows()


@contextlib.contextmanager
def collect_operator_stats(output_dir: Optional[str] = None,
                           print_summary: bool = True):
    """Context manager: collect per-op stats inside the block (ref:
    debugging.py:534). Yields the collector; on exit prints the summary
    and, with ``output_dir``, writes ``op_stats.jsonl`` there (the dump
    ``compare_accuracy`` consumes)."""
    from ..base import tape

    col = _StatsCollector()
    tape._op_observers.append(col)
    try:
        yield col
    finally:
        try:
            tape._op_observers.remove(col)
        except ValueError:
            pass
        if print_summary:
            print(col.summary_table())
        if output_dir:
            os.makedirs(output_dir, exist_ok=True)
            col.dump(os.path.join(output_dir, "op_stats.jsonl"))


# ---------------------------------------------------------------------------
# Tensor checker (ref: debugging.py:156, 628, 669)
# ---------------------------------------------------------------------------


class TensorCheckerConfig:
    """Per-op numeric checking config (ref: debugging.py:156).

    Args mirror the reference: ``enable``, ``debug_mode``, ``output_dir``
    (report lines are appended to ``<output_dir>/tensor_check.log``
    instead of printed), ``checked_op_list`` / ``skipped_op_list`` (exact
    op names), ``debug_step`` ((start, end) training-step window, ticked
    per backward pass), ``stack_height_limit`` (Python stack frames
    reported on a hit)."""

    def __init__(
        self,
        enable: bool,
        debug_mode: DebugMode = DebugMode.CHECK_NAN_INF_AND_ABORT,
        output_dir: Optional[str] = None,
        checked_op_list: Optional[Sequence[str]] = None,
        skipped_op_list: Optional[Sequence[str]] = None,
        debug_step: Optional[Tuple[int, int]] = None,
        stack_height_limit: int = 1,
    ):
        self.enable = bool(enable)
        if not isinstance(debug_mode, DebugMode):
            raise TypeError(
                f"debug_mode must be a DebugMode, got {type(debug_mode)}"
            )
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = set(checked_op_list or [])
        self.skipped_op_list = set(skipped_op_list or [])
        if debug_step is not None:
            start, end = debug_step
            if start < 0 or end <= start:
                raise ValueError(
                    f"debug_step must be (start, end) with 0 <= start < "
                    f"end, got {debug_step}"
                )
            self.start_step, self.end_step = int(start), int(end)
        else:
            self.start_step = self.end_step = None
        self.stack_height_limit = int(stack_height_limit)
        self._step = 0

    # -- step window ----------------------------------------------------
    def update_and_check_step_id(self) -> bool:
        """Tick the training step (called per backward pass); returns
        whether checking is active for the current step."""
        self._step += 1
        return self._step_active()

    def _step_active(self) -> bool:
        if self.start_step is None:
            return True
        return self.start_step <= self._step < self.end_step

    def _op_selected(self, op: str) -> bool:
        if op in self.skipped_op_list:
            return False
        if self.checked_op_list:
            return op in self.checked_op_list
        return True

    # -- reporting ------------------------------------------------------
    def _report(self, msg: str):
        if self.output_dir:
            os.makedirs(self.output_dir, exist_ok=True)
            with open(os.path.join(self.output_dir, "tensor_check.log"), "a") as f:
                f.write(msg + "\n")
        else:
            print(msg)

    def _stack_suffix(self) -> str:
        if self.stack_height_limit <= 0:
            return ""
        frames = traceback.extract_stack()
        # prefer user frames (outside the framework); if the hit came
        # entirely from framework-internal code (hapi fit loop etc.),
        # report the innermost non-observer framework frames instead
        user = [f for f in frames if "paddle_tpu" not in f.filename]
        if not user:
            user = [f for f in frames
                    if not f.filename.endswith(("tape.py", "debugging.py"))]
        user = user[-self.stack_height_limit:]
        return "".join(
            f"\n  at {f.filename}:{f.lineno} in {f.name}" for f in user
        )

    # -- the observer ---------------------------------------------------
    def __call__(self, op_name: str, leaves: Sequence):
        if not self.enable or not self._step_active():
            return
        op = op_name or "op"
        if not self._op_selected(op):
            return
        for leaf in leaves:
            st = _leaf_stats(leaf)
            if st is None:
                continue
            bad = st["num_nan"] + st["num_inf"]
            mode = self.debug_mode
            if mode in (DebugMode.CHECK_NAN_INF_AND_ABORT,
                        DebugMode.CHECK_NAN_INF):
                if bad:
                    msg = (
                        f"[tensor checker] op '{op}' output has "
                        f"{st['num_nan']} NaN / {st['num_inf']} Inf of "
                        f"{st['numel']} ({st['dtype']}), finite absmax="
                        f"{st['absmax']:.4e}{self._stack_suffix()}"
                    )
                    if mode is DebugMode.CHECK_NAN_INF_AND_ABORT:
                        raise FloatingPointError(msg)
                    self._report(msg)
            elif mode is DebugMode.CHECK_ALL_FOR_OVERFLOW:
                if st["dtype"] == "float32" and (
                    bad
                    or st["absmax"] > _FP16_MAX
                    or (0 < st["absmin"] < _FP16_TINY)
                ):
                    self._report(
                        f"[tensor checker] op '{op}' float32 output "
                        f"outside float16 range: absmax={st['absmax']:.4e} "
                        f"absmin={st['absmin']:.4e} nan={st['num_nan']} "
                        f"inf={st['num_inf']}{self._stack_suffix()}"
                    )
            elif mode is DebugMode.CHECK_ALL:
                self._report(
                    f"[tensor checker] op '{op}' {st['dtype']} "
                    f"numel={st['numel']} absmax={st['absmax']:.4e} "
                    f"absmin={st['absmin']:.4e} mean={st['mean']:.4e} "
                    f"nan={st['num_nan']} inf={st['num_inf']}"
                )


_active_checker: Optional[TensorCheckerConfig] = None


def enable_tensor_checker(checker_config: TensorCheckerConfig):
    """Install the checker at the tape dispatch point (ref:
    debugging.py:628)."""
    global _active_checker
    from ..base import tape

    disable_tensor_checker()
    _active_checker = checker_config
    tape._op_observers.append(checker_config)
    tape._backward_tick_callbacks.append(
        checker_config.update_and_check_step_id
    )


def disable_tensor_checker():
    """Remove the active checker (ref: debugging.py:669)."""
    global _active_checker
    from ..base import tape

    if _active_checker is None:
        return
    for lst in (tape._op_observers, tape._backward_tick_callbacks):
        for item in list(lst):
            if item is _active_checker or (
                getattr(item, "__self__", None) is _active_checker
            ):
                lst.remove(item)
    _active_checker = None


def check_numerics(tensor, op_type: str = "", var_name: str = "",
                   debug_mode: DebugMode = DebugMode.CHECK_NAN_INF_AND_ABORT,
                   stack_height_limit: int = 1,
                   output_dir: Optional[str] = None):
    """Check one tensor immediately (ref: debugging.py:338). Returns
    (num_nan, num_inf, numel) as ints."""
    data = getattr(tensor, "_data", tensor)
    st = _leaf_stats(data)
    if st is None:
        # traced or non-float value: skipped (module contract) — size
        # from the aval shape, never materializing a tracer
        shape = getattr(data, "shape", None)
        return 0, 0, int(np.prod(shape)) if shape is not None else 0
    cfg = TensorCheckerConfig(
        True, debug_mode=debug_mode, output_dir=output_dir,
        stack_height_limit=stack_height_limit,
    )
    cfg(f"{op_type or 'check_numerics'}:{var_name}", [data])
    return st["num_nan"], st["num_inf"], st["numel"]


def check_layer_numerics(func: Callable) -> Callable:
    """Decorator: check a layer forward's tensor inputs and outputs for
    NaN/Inf (ref: debugging.py:63). Raises FloatingPointError on a hit."""
    import functools

    def check_tree(tree, what, layer_name):
        # every Tensor leaf in any nesting (tuples, dicts, kwargs)
        from jax import tree_util

        from ..base.tensor import Tensor

        leaves = tree_util.tree_leaves(tree, is_leaf=lambda x: isinstance(x, Tensor))
        for i, leaf in enumerate(leaves):
            data = getattr(leaf, "_data", None)
            if data is None:
                continue
            st = _leaf_stats(data)
            if st and (st["num_nan"] or st["num_inf"]):
                raise FloatingPointError(
                    f"{what} {i} of {layer_name}.forward has "
                    f"{st['num_nan']} NaN / {st['num_inf']} Inf"
                )

    @functools.wraps(func)
    def wrapper(self, *args, **kwargs):
        check_tree((args, kwargs), "input", type(self).__name__)
        out = func(self, *args, **kwargs)
        check_tree(out, "output", type(self).__name__)
        return out

    return wrapper


# ---------------------------------------------------------------------------
# Cross-dtype accuracy comparison (ref: debugging.py:569)
# ---------------------------------------------------------------------------


def _run_fn_with_stats(fn, args, kwargs, dtype: str):
    """Run fn with float Tensor args cast to ``dtype``, collecting stats."""
    from ..base import dtype as dtypes
    from ..base.tensor import Tensor

    def cast(x):
        if isinstance(x, Tensor) and dtypes.is_floating_point(x.dtype):
            return x.astype(dtype)
        return x

    cargs = [cast(a) for a in args]
    ckw = {k: cast(v) for k, v in (kwargs or {}).items()}
    with collect_operator_stats(print_summary=False) as col:
        fn(*cargs, **ckw)
    return col.rows()


def _rows_by_op(rows: List[dict]) -> Dict[str, dict]:
    """Merge rows over dtypes per op (an op may emit several dtypes)."""
    out: Dict[str, dict] = {}
    for r in rows:
        ent = out.setdefault(
            r["op"],
            {"calls": 0, "num_nan": 0, "num_inf": 0, "absmax": 0.0,
             "dtypes": set()},
        )
        ent["calls"] += r["calls"]
        ent["num_nan"] += r["num_nan"]
        ent["num_inf"] += r["num_inf"]
        ent["absmax"] = max(ent["absmax"], r["absmax"])
        ent["dtypes"].add(r["dtype"])
    return out


def _compare_tables(rows_a, rows_b, label_a, label_b,
                    output_filename=None) -> List[dict]:
    a, b = _rows_by_op(rows_a), _rows_by_op(rows_b)
    report = []
    for op in sorted(set(a) | set(b)):
        ea = a.get(op)
        eb = b.get(op)
        flag = ""
        if ea and eb:
            if (eb["num_nan"] + eb["num_inf"]) > (ea["num_nan"] + ea["num_inf"]):
                flag = "OVERFLOW_IN_" + label_b.upper()
            elif (ea["num_nan"] + ea["num_inf"]) > (eb["num_nan"] + eb["num_inf"]):
                flag = "OVERFLOW_IN_" + label_a.upper()
            elif ea["absmax"] > 0 and (
                abs(ea["absmax"] - eb["absmax"]) / ea["absmax"] > 0.05
            ):
                flag = "ABSMAX_DIVERGED"
        report.append({
            "op": op,
            f"{label_a}_dtypes": ",".join(sorted(ea["dtypes"])) if ea else "",
            f"{label_a}_nan_inf": (ea["num_nan"] + ea["num_inf"]) if ea else "",
            f"{label_a}_absmax": ea["absmax"] if ea else "",
            f"{label_b}_dtypes": ",".join(sorted(eb["dtypes"])) if eb else "",
            f"{label_b}_nan_inf": (eb["num_nan"] + eb["num_inf"]) if eb else "",
            f"{label_b}_absmax": eb["absmax"] if eb else "",
            "flag": flag,
        })
    if output_filename:
        import csv

        with open(output_filename, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=list(report[0].keys()) if report
                               else ["op"])
            w.writeheader()
            w.writerows(report)
    flagged = [r for r in report if r["flag"]]
    print(
        f"compare_accuracy: {len(report)} ops compared "
        f"({label_a} vs {label_b}), {len(flagged)} flagged"
    )
    for r in flagged:
        print(f"  {r['op']:<28} {r['flag']}")
    return report


def compare_accuracy(
    dump_path,
    another_dump_path=None,
    output_filename: Optional[str] = None,
    loss_scale: float = 1,
    dump_all_tensors: bool = False,
    *,
    args: Sequence = (),
    kwargs: Optional[dict] = None,
    dtypes: Tuple[str, str] = ("float32", "bfloat16"),
):
    """Cross-dtype accuracy comparison (ref: debugging.py:569).

    Two call forms:

    - ``compare_accuracy(dump_a, dump_b, out_csv)``: compare two
      ``op_stats.jsonl`` dumps written by
      ``collect_operator_stats(output_dir=...)`` (a path to the file or
      its directory); writes a CSV report.
    - ``compare_accuracy(fn, args=..., dtypes=("float32","bfloat16"))``:
      run ``fn`` twice with its float tensor args cast to each dtype,
      collecting per-op stats for both runs and diffing them — flags
      ops that produce NaN/Inf only in the lower precision or whose
      absmax diverges >5%.

    Returns the list of per-op comparison rows."""
    if callable(dump_path):
        fn = dump_path
        lo, hi = dtypes[0], dtypes[1]
        rows_a = _run_fn_with_stats(fn, args, kwargs, lo)
        rows_b = _run_fn_with_stats(fn, args, kwargs, hi)
        return _compare_tables(rows_a, rows_b, lo, hi, output_filename)

    if another_dump_path is None:
        raise ValueError(
            "compare_accuracy dump mode needs two dump paths "
            "(dump_path, another_dump_path); to compare a function "
            "under two dtypes pass a callable first argument instead"
        )

    def load(path):
        if os.path.isdir(path):
            path = os.path.join(path, "op_stats.jsonl")
        with open(path) as f:
            return [json.loads(line) for line in f if line.strip()]

    if dump_all_tensors:
        print("compare_accuracy: dump_all_tensors is not supported "
              "(per-op stats only)")
    return _compare_tables(
        load(dump_path), load(another_dump_path), "run_a", "run_b",
        output_filename,
    )
