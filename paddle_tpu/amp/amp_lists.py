"""AMP op lists (ref: python/paddle/amp/amp_lists.py:105).

Names match THIS framework's op_name vocabulary (the ``op_name=`` strings
passed to tape.apply by the tensor/nn.functional wrappers), mapped from
the reference's kernel names (matmul_v2 -> matmul, lookup_table_v2 ->
embedding, softmax_with_cross_entropy -> cross_entropy, ...).
"""
from __future__ import annotations

# Numerically safe, MXU-bound ops: always run in fp16/bf16 under amp.
WHITE_LIST = {
    "matmul",
    "linear",
    "einsum",
    "conv1d",
    "conv2d",
    "conv3d",
    "conv1d_transpose",
    "conv2d_transpose",
    "conv3d_transpose",
    "bmm",
    "mm",
    "addmm",
    "dot",
    "flash_attention",
    "scaled_dot_product_attention",
    "max_pool2d_indices",
}

# fp16-only extras (bf16 unsupported in the reference; kept for parity).
ONLY_FP16_WHITE_LIST = {
    "fused_attention",
    "fused_feedforward",
}

FP16_WHITE_LIST = WHITE_LIST | ONLY_FP16_WHITE_LIST

# Numerically dangerous in low precision: always promoted to fp32.
FP16_BLACK_LIST = {
    "tan",
    "acos",
    "asin",
    "sinh",
    "cosh",
    "atanh",
    "tanhshrink",
    "erfinv",
    "exp",
    "expm1",
    "log",
    "log10",
    "log2",
    "log1p",
    "reciprocal",
    "rsqrt",
    "pow",
    "square",
    "sum",
    "mean",
    "prod",
    "cumprod",
    "cumsum",
    "dist",
    "p_norm",
    "norm",
    "frobenius_norm",
    "renorm",
    "group_norm",
    "layer_norm",
    "softmax",
    "softmin",
    "softplus",
    "log_softmax",
    "logsumexp",
    "cross_entropy",
    "binary_cross_entropy",
    "bce_with_logits",
    "nll_loss",
    "huber_loss",
    "triplet_margin_loss",
    "log_loss",
    "hsigmoid_loss",
    "margin_cross_entropy",
    "sigmoid_focal_loss",
}

# Grad perf worse than fp32 in the reference; fp32 by default (O1 and O2).
EXTRA_BLACK_LIST = {
    "interpolate",
    "embedding",
    "scatter",
}

BF16_WHITE_LIST = WHITE_LIST
BF16_BLACK_LIST = FP16_BLACK_LIST


def white_list(dtype: str, level: str):
    if dtype == "float16":
        return set(FP16_WHITE_LIST)
    return set(BF16_WHITE_LIST)


def black_list(dtype: str, level: str):
    base = FP16_BLACK_LIST if dtype == "float16" else BF16_BLACK_LIST
    if level == "OD":
        return set()
    if level == "O2":
        return set(EXTRA_BLACK_LIST)
    return set(base) | set(EXTRA_BLACK_LIST)


class AutoCastLists:
    """User-extendable white/black lists (ref: AutoMixedPrecisionLists)."""

    def __init__(
        self,
        custom_white_list=None,
        custom_black_list=None,
        dtype: str = "float16",
        level: str = "O1",
    ):
        self.white_list = white_list(dtype, level)
        self.black_list = black_list(dtype, level)
        if custom_white_list:
            for op in custom_white_list:
                self.white_list.add(op)
                self.black_list.discard(op)
        if custom_black_list:
            for op in custom_black_list:
                self.black_list.add(op)
                self.white_list.discard(op)
        overlap = (set(custom_white_list or ()) & set(custom_black_list or ()))
        if overlap:
            raise ValueError(
                f"custom_white_list and custom_black_list overlap: {sorted(overlap)}"
            )


AutoMixedPrecisionLists = AutoCastLists
