"""auto_cast / decorate (ref: python/paddle/amp/auto_cast.py:899, :983).

bf16-first policy for TPU: the MXU computes natively in bf16, so
``dtype='bfloat16'`` is the default (the reference defaults to float16
for CUDA). Casting happens at the tape dispatch point
(base/tape.py apply -> base/amp_state.cast_target), mirroring the
reference's generated-ad_func AMP block.
"""
from __future__ import annotations

import contextlib
from typing import Optional, Sequence

import numpy as np

from ..base import amp_state, dtype as _dtypes
from .amp_lists import AutoCastLists

__all__ = ["auto_cast", "amp_guard", "decorate", "amp_decorate", "is_bfloat16_supported", "is_float16_supported"]

_SUPPORTED_LEVELS = ("O0", "OD", "O1", "O2")


def is_float16_supported(device=None) -> bool:
    """fp16 compute is supported through XLA on every backend we target."""
    return True


def is_bfloat16_supported(device=None) -> bool:
    """bf16 is the TPU-native precision (MXU accumulates fp32)."""
    return True


@contextlib.contextmanager
def amp_guard(
    enable: bool = True,
    custom_white_list: Optional[Sequence[str]] = None,
    custom_black_list: Optional[Sequence[str]] = None,
    level: str = "O1",
    dtype: str = "bfloat16",
    use_promote: bool = True,
):
    """Context manager enabling per-op auto-casting (ref: auto_cast.py:899)."""
    if level not in _SUPPORTED_LEVELS:
        raise ValueError(f"level should be one of {_SUPPORTED_LEVELS}, got {level}")
    if dtype not in ("float16", "bfloat16"):
        raise ValueError(f"dtype should be float16 or bfloat16, got {dtype}")
    if level == "O0":
        enable = False

    tls = amp_state.amp_attrs()
    prev = (tls.enable, tls.dtype, tls.level, tls.white, tls.black)
    if enable:
        lists = AutoCastLists(custom_white_list, custom_black_list, dtype, level)
        tls.enable = True
        tls.dtype = np.dtype(_dtypes.canonical_dtype(dtype))
        tls.level = level
        tls.white = lists.white_list
        tls.black = lists.black_list
    else:
        tls.enable = False
    try:
        yield
    finally:
        tls.enable, tls.dtype, tls.level, tls.white, tls.black = prev


# public name (paddle.amp.auto_cast); amp_guard is the legacy alias
auto_cast = amp_guard


def decorate(
    models,
    optimizers=None,
    level: str = "O1",
    dtype: str = "bfloat16",
    master_weight: Optional[bool] = None,
    save_dtype: Optional[str] = None,
    master_grad: bool = False,
    excluded_layers=None,
):
    """Cast models for pure-low-precision training (ref: auto_cast.py:983).

    O1: no-op on the model (casting is per-op in auto_cast).
    O2: parameters/buffers cast to ``dtype`` (floating only, excluding
    normalization layers' params kept fp32 like the reference), and
    optimizers get fp32 master weights.
    """
    from ..nn.layer.layers import Layer
    from ..nn.layer import norm as _norm

    if level not in _SUPPORTED_LEVELS:
        raise ValueError(f"level should be one of {_SUPPORTED_LEVELS}, got {level}")

    models_in = models
    if isinstance(models, Layer):
        models = [models]
    opts_in = optimizers
    if optimizers is None:
        optimizers = []
    elif not isinstance(optimizers, (list, tuple)):
        optimizers = [optimizers]

    if level == "O2":
        excluded_types = tuple(
            t for t in (
                getattr(_norm, "BatchNorm", None),
                getattr(_norm, "BatchNorm1D", None),
                getattr(_norm, "BatchNorm2D", None),
                getattr(_norm, "BatchNorm3D", None),
                getattr(_norm, "LayerNorm", None),
                getattr(_norm, "InstanceNorm1D", None),
                getattr(_norm, "InstanceNorm2D", None),
                getattr(_norm, "InstanceNorm3D", None),
                getattr(_norm, "GroupNorm", None),
                getattr(_norm, "SyncBatchNorm", None),
            ) if t is not None
        )
        if excluded_layers:
            extra = tuple(excluded_layers) if isinstance(excluded_layers, (list, tuple)) else (excluded_layers,)
            excluded_types = excluded_types + tuple(t for t in extra if isinstance(t, type))
        dt = _dtypes.canonical_dtype(dtype)
        for model in models:
            for sub in model.sublayers(include_self=True):
                if isinstance(sub, excluded_types):
                    continue
                for t in list(sub._parameters.values()) + list(sub._buffers.values()):
                    if t is not None and _dtypes.is_floating_point(t.dtype):
                        t._data = t._data.astype(dt)
                sub._dtype = dt
        use_master = master_weight if master_weight is not None else True
        for opt in optimizers:
            opt._multi_precision = bool(use_master)

    if save_dtype is not None:
        for model in models:
            model._save_dtype = _dtypes.canonical_dtype(save_dtype)

    if opts_in is None:
        return models_in
    return models_in, opts_in


amp_decorate = decorate
