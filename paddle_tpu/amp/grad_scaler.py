"""GradScaler — dynamic loss scaling (ref: python/paddle/amp/grad_scaler.py:617).

TPU-native design: the found-inf check and the step skip are expressed as
``jnp.where`` selects instead of host control flow, so a scaler-wrapped
train step traces cleanly under ``paddle_tpu.jit.to_static`` (the
reference reads ``found_inf`` back to the host via the
check_finite_and_unscale op; that D2H sync would stall the TPU pipeline).
Skipping a step = snapshotting params + accumulators before
``optimizer.step()`` and selecting the old values when inf was found —
XLA turns the selects into a predicated update with no extra traffic.
"""
from __future__ import annotations

import warnings
from enum import Enum
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..base import dtype as _dtypes
from ..base.tape import no_grad
from ..base.tensor import Tensor

__all__ = ["AmpScaler", "GradScaler", "OptimizerState"]


class OptimizerState(Enum):
    INIT = 0
    UNSCALED = 1
    STEPPED = 2


class AmpScaler:
    """ref: python/paddle/amp/grad_scaler.py AmpScaler (base of GradScaler)."""

    def __init__(
        self,
        enable: bool = True,
        init_loss_scaling: float = 2.0**15,
        incr_ratio: float = 2.0,
        decr_ratio: float = 0.5,
        incr_every_n_steps: int = 1000,
        decr_every_n_nan_or_inf: int = 2,
        use_dynamic_loss_scaling: bool = True,
        on_skip=None,
    ):
        if incr_ratio <= 1.0:
            raise ValueError("incr_ratio should be > 1")
        if not 0.0 < decr_ratio < 1.0:
            raise ValueError("decr_ratio should be in (0, 1)")
        self._enable = bool(enable)
        self._use_dynamic_loss_scaling = bool(use_dynamic_loss_scaling) and self._enable
        self._init_loss_scaling = float(init_loss_scaling)
        self._incr_ratio = float(incr_ratio)
        self._decr_ratio = float(decr_ratio)
        self._incr_every_n_steps = int(incr_every_n_steps)
        self._decr_every_n_nan_or_inf = int(decr_every_n_nan_or_inf)
        self._scale = jnp.asarray(self._init_loss_scaling, jnp.float32)
        self._good_steps = jnp.asarray(0, jnp.int32)
        self._bad_steps = jnp.asarray(0, jnp.int32)
        self._found_inf = jnp.asarray(False)
        self._opt_states: Dict[int, OptimizerState] = {}
        # found_inf skip observability: host-side counters advanced at
        # update() time, where the skip decision is settled. Counted
        # only when found_inf is CONCRETE — inside a to_static trace it
        # is a tracer and the threaded device state owns the semantics;
        # callers on that path read _found_inf after the compiled step
        # (jit restores a concrete value) instead of these counters.
        self._n_skipped_steps = 0
        self._last_skip_step = -1
        self._n_updates = 0
        self._on_skip = on_skip
        # fused-interleaved support: scale()-time snapshots of each
        # attached optimizer's params+accums, keyed by id(optimizer)
        # — the rollback target for layers whose fused update landed
        # BEFORE a later layer's grad revealed the inf (layers after
        # detection are vetoed in-kernel and never written at all)
        self._interleave_snaps: Dict[int, tuple] = {}
        self._interleaved_opts: Dict[int, object] = {}

    # ------------------------------------------------------------------
    def is_enable(self) -> bool:
        return self._enable

    is_enabled = is_enable

    def is_use_dynamic_loss_scaling(self) -> bool:
        return self._use_dynamic_loss_scaling

    def _attach_or_refuse_interleaved(self, when: str):
        """Interleaved optimizers apply updates DURING backward — on
        grads that are still scaled. By the time step() could object,
        params and Adam moments are already corrupted, so this must
        fire BEFORE backward ever runs: here, on the pre-backward
        surfaces (scale / unscale_). The check is deliberately
        PROCESS-GLOBAL (scale() cannot see which params the loss
        reaches).

        FUSED interleaved optimizers (AdamW(fused=True)) are the
        exception: the single-pass kernel takes a found-inf veto that
        is read in SMEM before any tile is written, so the scaler can
        drive them safely — each finalized grad is unscaled per-layer
        (_interleave_unscale) and the running found flag vetoes every
        fused update from the first bad layer on; layers updated
        before detection roll back at step() against the snapshot
        taken here. Everything else still refuses: a non-fused
        interleaved update has no pre-write veto point."""
        from ..base import tape as _tape

        if not _tape._interleave_registry:
            return
        opts = {}
        for pref, oref in list(_tape._interleave_registry.values()):
            o = oref()
            if o is not None:
                opts[id(o)] = o
        for opt in opts.values():
            if not getattr(opt, "_fused", False):
                raise ValueError(
                    "GradScaler cannot drive an interleave_updates "
                    f"optimizer ({when}): interleaved updates would fire "
                    "during backward on SCALED grads, before unscale_/"
                    "inf-skip can run — construct the optimizer without "
                    "interleave_updates when using a GradScaler, or "
                    "with fused=True (the fused kernel takes a "
                    "found-inf veto, which makes scaling safe)")
        for opt in opts.values():
            # attachment lasts one scale()→update() cycle: update()
            # detaches, so a later scaler-less backward runs the plain
            # interleaved path instead of unscaling unscaled grads
            opt._interleave_scaler = self
            self._interleaved_opts[id(opt)] = opt
            if id(opt) not in self._interleave_snaps:
                self._interleave_snaps[id(opt)] = self._snapshot(opt)
                opt._accum_creation_log = {}

    # ------------------------------------------------------------------
    def scale(self, var):
        """Multiply the loss by the current scale (ref: grad_scaler.py scale)."""
        if not self._enable:
            return var
        self._attach_or_refuse_interleaved(
            "refused at scale(), before backward")
        return var * Tensor(self._scale.astype(var._data.dtype), _internal=True)

    @no_grad()
    def _interleave_unscale(self, g):
        """Per-layer unscale for the fused interleaved path: called by
        Optimizer._interleave_apply the moment a grad finalizes during
        backward. ORs this grad's finiteness into the running
        found_inf and returns (unscaled grad, veto flag) — the flag
        covers every layer finalized SO FAR, so the fused kernel skips
        all writes from the first bad layer onward."""
        if np.dtype(g.dtype).kind in "fc":
            self._found_inf = self._found_inf | ~jnp.all(jnp.isfinite(g))
            inv_scale = 1.0 / self._scale
            g = (g.astype(jnp.float32) * inv_scale).astype(g.dtype)
        return g, self._found_inf

    # ------------------------------------------------------------------
    def _params_with_grads(self, optimizer):
        return [
            p for p in optimizer._parameter_list
            if not p.stop_gradient and p._grad is not None
        ]

    @no_grad()
    def unscale_(self, optimizer):
        """Divide grads by the scale and detect non-finite values
        (check_finite_and_unscale semantics, traceable)."""
        if not self._enable:
            return
        if (getattr(optimizer, "_interleave", False)
                and getattr(optimizer, "_interleave_scaler", None) is not self):
            self._attach_or_refuse_interleaved("refused at unscale_()")
        state = self._opt_states.get(id(optimizer), OptimizerState.INIT)
        if state is OptimizerState.UNSCALED:
            raise RuntimeError("unscale_() has already been called on this optimizer since the last update()")
        if state is OptimizerState.STEPPED:
            raise RuntimeError("unscale_() is being called after step()")

        params = self._params_with_grads(optimizer)
        inv_scale = (1.0 / self._scale)
        found = jnp.asarray(False)
        for p in params:
            g = p._grad._data
            if np.dtype(g.dtype).kind in "fc":
                found = found | ~jnp.all(jnp.isfinite(g))
                p._grad._data = (g.astype(jnp.float32) * inv_scale).astype(g.dtype)
        # OR, not overwrite: the fused interleaved path may already
        # have accumulated found-inf from per-layer unscales during
        # backward (and a second optimizer's unscale_ must not erase
        # the first's verdict)
        self._found_inf = self._found_inf | found
        self._opt_states[id(optimizer)] = OptimizerState.UNSCALED

    # ------------------------------------------------------------------
    def _snapshot(self, optimizer):
        params = [p for p in optimizer._parameter_list if not p.stop_gradient]
        old_params = [p._data for p in params]
        old_accums = jax.tree_util.tree_map(lambda a: a, optimizer._accumulators)
        return params, old_params, old_accums

    def _rollback_where_inf(self, optimizer, params, old_params, old_accums, creation_log):
        found = self._found_inf
        for p, old in zip(params, old_params):
            if p._data is not old:
                p._data = jnp.where(found, old, p._data)
        for name, store in optimizer._accumulators.items():
            old_store = old_accums.get(name, {})
            for pname, arr in store.items():
                # accumulators created DURING the (possibly skipped) step
                # roll back to their creation-time init value
                old = old_store.get(pname, creation_log.get((name, pname)))
                if old is not None and old is not arr:
                    store[pname] = jnp.where(found, old, arr)

    def step(self, optimizer):
        """Unscale (if needed) then step, skipping the update when inf/nan
        grads were found (ref: grad_scaler.py step)."""
        if getattr(optimizer, "_interleave", False):
            if getattr(optimizer, "_interleave_scaler", None) is self:
                return self._step_interleaved(optimizer)
            raise ValueError(
                "GradScaler cannot drive an interleave_updates "
                "optimizer: updates fire during backward with SCALED "
                "grads, before unscale_/inf-skip can run — construct "
                "it with fused=True to enable the kernel-level "
                "found-inf veto")
        if not self._enable:
            optimizer.step()
            return
        state = self._opt_states.get(id(optimizer), OptimizerState.INIT)
        if state is OptimizerState.STEPPED:
            raise RuntimeError("step() has already been called since the last update()")
        if state is OptimizerState.INIT:
            self.unscale_(optimizer)

        snap = self._snapshot(optimizer)
        optimizer._accum_creation_log = {}
        try:
            optimizer.step()
            self._rollback_where_inf(optimizer, *snap, optimizer._accum_creation_log)
        finally:
            optimizer._accum_creation_log = None
        self._opt_states[id(optimizer)] = OptimizerState.STEPPED

    def _step_interleaved(self, optimizer):
        """step() for a fused interleaved optimizer the scaler attached
        at scale() time. Most params were already updated during
        backward (per-layer unscale + in-kernel veto from the first
        bad layer on); here: unscale any leftover grads (params whose
        grad never finalized interleaved), run the residual step, then
        roll back everything the GLOBAL found_inf invalidates against
        the scale()-time snapshot — layers updated before the inf was
        detected come back bitwise."""
        if not self._enable:
            optimizer.step()
            return
        state = self._opt_states.get(id(optimizer), OptimizerState.INIT)
        if state is OptimizerState.STEPPED:
            raise RuntimeError("step() has already been called since the last update()")
        if state is OptimizerState.INIT:
            self.unscale_(optimizer)
        snap = self._interleave_snaps.pop(id(optimizer), None)
        if snap is None:  # scale() never saw this optimizer attached
            snap = self._snapshot(optimizer)
            optimizer._accum_creation_log = optimizer._accum_creation_log or {}
        creation_log = optimizer._accum_creation_log
        try:
            optimizer.step()
            self._rollback_where_inf(optimizer, *snap, creation_log or {})
        finally:
            optimizer._accum_creation_log = None
        self._opt_states[id(optimizer)] = OptimizerState.STEPPED

    def update(self):
        """Advance the dynamic loss scale (ref: grad_scaler.py update)."""
        if not self._enable:
            return
        if not isinstance(self._found_inf, jax.core.Tracer):
            # observable skips: a silently-dropped step is an anomaly
            # signal (the training supervisor's detector subscribes via
            # on_skip); counters only advance on concrete values so a
            # trace never leaks a tracer into host state
            step_ix = self._n_updates
            self._n_updates += 1
            if bool(np.asarray(self._found_inf)):
                self._n_skipped_steps += 1
                self._last_skip_step = step_ix
                if self._on_skip is not None:
                    self._on_skip(step_ix)
        if self._use_dynamic_loss_scaling:
            found = self._found_inf
            # consecutive counters: a good step resets bad and vice versa
            # (reference update_loss_scaling kernel semantics)
            bad = jnp.where(found, self._bad_steps + 1, 0)
            good = jnp.where(found, 0, self._good_steps + 1)
            # decrease after N consecutive bad steps
            shrink = bad >= self._decr_every_n_nan_or_inf
            scale = jnp.where(shrink, self._scale * self._decr_ratio, self._scale)
            bad = jnp.where(shrink, 0, bad)
            # increase after N consecutive good steps
            grow = good >= self._incr_every_n_steps
            scale = jnp.where(grow, scale * self._incr_ratio, scale)
            good = jnp.where(grow, 0, good)
            self._scale = jnp.maximum(scale, jnp.asarray(1.0, jnp.float32))
            self._good_steps = good
            self._bad_steps = bad
        self._found_inf = jnp.asarray(False)
        self._opt_states.clear()
        self._interleave_snaps.clear()
        for opt in self._interleaved_opts.values():
            if getattr(opt, "_interleave_scaler", None) is self:
                opt._interleave_scaler = None
        self._interleaved_opts.clear()

    def minimize(self, optimizer, *args, **kwargs):
        """step + update in one call (ref: AmpScaler.minimize)."""
        if not self._enable:
            return optimizer.step()
        self.step(optimizer)
        self.update()

    # ------------------------------------------------------------------
    @property
    def n_skipped_steps(self) -> int:
        """How many update() cycles found inf/nan grads and skipped the
        optimizer step (eager path; see update() for the jit caveat)."""
        return self._n_skipped_steps

    @property
    def last_skip_step(self) -> int:
        """0-based update() index of the most recent skipped step, or
        -1 when no step has been skipped."""
        return self._last_skip_step

    def set_on_skip(self, callback) -> None:
        """Install/replace the on-skip observer: ``callback(step_ix)``
        fires at update() time for every skipped step."""
        self._on_skip = callback

    # ------------------------------------------------------------------
    def get_scale_value(self) -> float:
        return float(np.asarray(self._scale))

    def set_scale_value(self, value: float):
        self._scale = jnp.asarray(float(value), jnp.float32)

    # GradScaler-compat accessor names (ref: grad_scaler.py:617 section)
    def get_init_loss_scaling(self):
        return self._init_loss_scaling

    def set_init_loss_scaling(self, v):
        self._init_loss_scaling = float(v)
        self._scale = jnp.asarray(self._init_loss_scaling, jnp.float32)

    def get_incr_ratio(self):
        return self._incr_ratio

    def set_incr_ratio(self, v):
        if v <= 1.0:
            raise ValueError("incr_ratio should be > 1")
        self._incr_ratio = float(v)

    def get_decr_ratio(self):
        return self._decr_ratio

    def set_decr_ratio(self, v):
        if not 0.0 < v < 1.0:
            raise ValueError("decr_ratio should be in (0, 1)")
        self._decr_ratio = float(v)

    def get_incr_every_n_steps(self):
        return self._incr_every_n_steps

    def set_incr_every_n_steps(self, v):
        self._incr_every_n_steps = int(v)

    def get_decr_every_n_nan_or_inf(self):
        return self._decr_every_n_nan_or_inf

    def set_decr_every_n_nan_or_inf(self, v):
        self._decr_every_n_nan_or_inf = int(v)

    def state_dict(self):
        if not self._enable:
            return {}
        return {
            "scale": np.asarray(self._scale),
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every_n_steps,
            "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
            "incr_count": int(np.asarray(self._good_steps)),
            "decr_count": int(np.asarray(self._bad_steps)),
            "use_dynamic_loss_scaling": self._use_dynamic_loss_scaling,
        }

    def load_state_dict(self, state_dict):
        if not self._enable:
            if state_dict:
                warnings.warn("Load state_dict on a disabled GradScaler: ignored")
            return
        self._scale = jnp.asarray(np.asarray(state_dict["scale"]).reshape(()), jnp.float32)
        self._incr_ratio = float(state_dict["incr_ratio"])
        self._decr_ratio = float(state_dict["decr_ratio"])
        self._incr_every_n_steps = int(state_dict["incr_every_n_steps"])
        self._decr_every_n_nan_or_inf = int(state_dict["decr_every_n_nan_or_inf"])
        self._good_steps = jnp.asarray(int(state_dict.get("incr_count", 0)), jnp.int32)
        self._bad_steps = jnp.asarray(int(state_dict.get("decr_count", 0)), jnp.int32)


class GradScaler(AmpScaler):
    """Public API name (ref: paddle.amp.GradScaler, grad_scaler.py:617)."""
