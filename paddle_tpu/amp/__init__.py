"""paddle_tpu.amp — automatic mixed precision (ref: python/paddle/amp/).

bf16-first for TPU: ``auto_cast`` defaults to dtype='bfloat16' (the MXU's
native input precision; fp32 accumulation is implicit), while float16 is
supported for reference parity. See auto_cast.py / grad_scaler.py.
"""
from . import amp_lists  # noqa: F401
from .amp_lists import AutoCastLists, AutoMixedPrecisionLists  # noqa: F401
from .auto_cast import (  # noqa: F401
    amp_decorate,
    amp_guard,
    auto_cast,
    decorate,
    is_bfloat16_supported,
    is_float16_supported,
)
from .fp8 import E4M3_MAX, E5M2_MAX, Fp8Linear, convert_to_fp8, fp8_linear  # noqa: F401,E501
from .grad_scaler import AmpScaler, GradScaler, OptimizerState  # noqa: F401

__all__ = [
    "auto_cast",
    "Fp8Linear",
    "convert_to_fp8",
    "fp8_linear",
    "E4M3_MAX",
    "E5M2_MAX",
    "amp_guard",
    "decorate",
    "amp_decorate",
    "GradScaler",
    "AmpScaler",
    "OptimizerState",
    "AutoCastLists",
    "AutoMixedPrecisionLists",
    "is_float16_supported",
    "is_bfloat16_supported",
    "debugging",
    "DebugMode",
    "TensorCheckerConfig",
    "collect_operator_stats",
    "compare_accuracy",
    "disable_operator_stats_collection",
    "disable_tensor_checker",
    "enable_operator_stats_collection",
    "enable_tensor_checker",
]

# debugging tools (ref: python/paddle/amp/debugging.py) — real per-op
# stats collection / tensor checking / cross-dtype comparison, hooked
# into the tape's single dispatch point. See debugging.py.
from . import debugging  # noqa: E402,F401
from .debugging import (  # noqa: E402,F401
    DebugMode,
    TensorCheckerConfig,
    collect_operator_stats,
    compare_accuracy,
    disable_operator_stats_collection,
    disable_tensor_checker,
    enable_operator_stats_collection,
    enable_tensor_checker,
)

# legacy alias kept from the round-2 shim era
debugging_enable_operator_stats_collection = enable_operator_stats_collection
