"""Tensor creation ops (ref: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..base import dtype as dtypes
from ..base.tape import apply
from ..base.tensor import Tensor, to_tensor  # noqa: F401


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._data if isinstance(s, Tensor) else s) for s in shape)


def _dt(dtype, default=None):
    if dtype is None:
        return dtypes.canonical_dtype(default or dtypes.get_default_dtype())
    return dtypes.canonical_dtype(dtype)


def zeros(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)), _internal=True)


def ones(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)), _internal=True)


def full(shape, fill_value, dtype=None, name=None) -> Tensor:
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        dtype = (
            dtypes.get_default_dtype()
            if isinstance(fill_value, float)
            else (dtypes.bool_ if isinstance(fill_value, bool) else dtypes.canonical_int())
        )
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)), _internal=True)


def empty(shape, dtype=None, name=None) -> Tensor:
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None) -> Tensor:
    return apply(lambda a: jnp.zeros_like(a, dtype=_dt(dtype, np.dtype(a.dtype))), x.detach() if isinstance(x, Tensor) else x, op_name="zeros_like")


def ones_like(x, dtype=None, name=None) -> Tensor:
    return apply(lambda a: jnp.ones_like(a, dtype=_dt(dtype, np.dtype(a.dtype))), x.detach() if isinstance(x, Tensor) else x, op_name="ones_like")


def full_like(x, fill_value, dtype=None, name=None) -> Tensor:
    return apply(
        lambda a: jnp.full_like(a, fill_value, dtype=_dt(dtype, np.dtype(a.dtype))),
        x.detach() if isinstance(x, Tensor) else x,
        op_name="full_like",
    )


def empty_like(x, dtype=None, name=None) -> Tensor:
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None) -> Tensor:
    def _val(v):
        return v.item() if isinstance(v, Tensor) else v

    start, end, step = _val(start), _val(end), _val(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = (
            dtypes.get_default_dtype()
            if any(isinstance(v, float) for v in (start, end, step))
            else dtypes.canonical_int()
        )
    return Tensor(jnp.arange(start, end, step, _dt(dtype)), _internal=True)


def linspace(start, stop, num, dtype=None, name=None) -> Tensor:
    def _val(v):
        return v.item() if isinstance(v, Tensor) else v

    return Tensor(
        jnp.linspace(_val(start), _val(stop), int(_val(num)), dtype=_dt(dtype)),
        _internal=True,
    )


def logspace(start, stop, num, base=10.0, dtype=None, name=None) -> Tensor:
    def _val(v):
        return v.item() if isinstance(v, Tensor) else v

    return Tensor(
        jnp.logspace(_val(start), _val(stop), int(_val(num)), base=_val(base), dtype=_dt(dtype)),
        _internal=True,
    )


def eye(num_rows, num_columns=None, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)), _internal=True)


def diag(x, offset=0, padding_value=0, name=None) -> Tensor:
    def _diag(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.eye(out.shape[0], out.shape[1], k=offset, dtype=bool)
                out = jnp.where(mask, out, jnp.asarray(padding_value, out.dtype))
            return out
        return jnp.diagonal(a, offset=offset)

    return apply(_diag, x, op_name="diag")


def diagflat(x, offset=0, name=None) -> Tensor:
    return apply(lambda a: jnp.diagflat(a, k=offset), x, op_name="diagflat")


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None) -> Tensor:
    def _f(a):
        out = jnp.zeros((*a.shape, a.shape[-1] + abs(offset)), a.dtype)
        idx = jnp.arange(a.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = out.at[..., r, c].set(a)
        ndim = out.ndim
        d1, d2 = dim1 % ndim, dim2 % ndim
        perm = [i for i in range(ndim) if i not in (ndim - 2, ndim - 1)]
        # place last two axes at dim1/dim2
        order = []
        src = iter(perm)
        for i in range(ndim):
            if i == d1:
                order.append(ndim - 2)
            elif i == d2:
                order.append(ndim - 1)
            else:
                order.append(next(src))
        return jnp.transpose(out, order)

    return apply(_f, x, op_name="diag_embed")


def tril(x, diagonal=0, name=None) -> Tensor:
    return apply(lambda a: jnp.tril(a, k=diagonal), x, op_name="tril")


def triu(x, diagonal=0, name=None) -> Tensor:
    return apply(lambda a: jnp.triu(a, k=diagonal), x, op_name="triu")


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), _dt(dtype)), _internal=True)


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.asarray(np.stack([r, c]), _dt(dtype)), _internal=True)


def meshgrid(*args, name=None):
    args = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    outs = apply(lambda *xs: tuple(jnp.meshgrid(*xs, indexing="ij")), *args, op_name="meshgrid")
    return list(outs)


def assign(x, output=None) -> Tensor:
    src = x if isinstance(x, Tensor) else Tensor(np.asarray(x))
    out = apply(lambda a: a + 0 if np.issubdtype(np.result_type(a), np.number) else a, src, op_name="assign")
    if output is not None:
        output._inplace_from(out)
        return output
    return out


def clone(x, name=None) -> Tensor:
    return x.clone()


def complex(real, imag, name=None) -> Tensor:
    return apply(lambda r, i: jax.lax.complex(r, i), real, imag, op_name="complex")


import jax  # noqa: E402  (used by complex above)


def polar(abs_, angle, name=None) -> Tensor:
    return apply(
        lambda a, t: jax.lax.complex(a * jnp.cos(t), a * jnp.sin(t)),
        abs_,
        angle,
        op_name="polar",
    )


def one_hot(x, num_classes, name=None) -> Tensor:
    import jax.nn as jnn

    return apply(
        lambda a: jnn.one_hot(a, num_classes, dtype=dtypes.get_default_dtype()),
        x,
        op_name="one_hot",
    )


def create_tensor(dtype, name=None, persistable=False):
    """ref: tensor/creation.py create_tensor — an empty typed tensor to
    be filled by assign/set_value."""
    from ..base.dtype import canonical_dtype

    t = Tensor(jnp.zeros((0,), canonical_dtype(dtype)), _internal=True)
    t.persistable = persistable
    return t
