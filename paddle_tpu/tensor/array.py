"""TensorArray ops (ref: python/paddle/tensor/array.py — create_array,
array_write, array_read, array_length; backed in the reference by the
LoDTensorArray specialized tensor, SURVEY §2.1).

TPU-native: in eager/dygraph the array is a plain Python list of
Tensors (exactly what the reference does in dynamic mode,
array.py in_dygraph_mode branches); inside jit-traced code a Python
list of traced Tensors composes fine because indices there must be
static anyway — dynamic-index accumulation is what lax.scan is for,
which paddle_tpu.jit users reach via multi_step/scan directly.
"""
from __future__ import annotations

from typing import List, Optional

from ..base.tensor import Tensor

__all__ = ["create_array", "array_write", "array_read", "array_length"]


def create_array(dtype: str = "float32", initialized_list=None):
    """ref: array.py create_array."""
    arr: List[Tensor] = []
    if initialized_list is not None:
        for t in initialized_list:
            if not isinstance(t, Tensor):
                raise TypeError(
                    f"initialized_list items must be Tensors, got {type(t)}"
                )
            arr.append(t)
    return arr


def _index(i) -> int:
    if isinstance(i, Tensor):
        return int(i.numpy())
    return int(i)


def array_write(x, i, array: Optional[list] = None):
    """Write x at index i, growing the array (ref: array.py array_write)."""
    if array is None:
        array = create_array()
    idx = _index(i)
    if idx < len(array):
        array[idx] = x
    elif idx == len(array):
        array.append(x)
    else:
        raise IndexError(
            f"array_write index {idx} beyond array length {len(array)}"
        )
    return array


def array_read(array: list, i):
    """ref: array.py array_read."""
    idx = _index(i)
    if not 0 <= idx < len(array):
        raise IndexError(f"array_read index {idx} out of range [0, {len(array)})")
    return array[idx]


def array_length(array: list):
    """ref: array.py array_length."""
    from .. import to_tensor

    return to_tensor(len(array))
