"""Comparison / logical / bitwise ops (ref: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..base.tape import apply
from ..base.tensor import Tensor


def _cmp(jfn, opname):
    def op(x, y, name=None):
        return apply(jfn, x, y, op_name=opname)

    op.__name__ = opname
    return op


equal = _cmp(jnp.equal, "equal")
not_equal = _cmp(jnp.not_equal, "not_equal")
less_than = _cmp(jnp.less, "less_than")
less_equal = _cmp(jnp.less_equal, "less_equal")
greater_than = _cmp(jnp.greater, "greater_than")
greater_equal = _cmp(jnp.greater_equal, "greater_equal")
logical_and = _cmp(jnp.logical_and, "logical_and")
logical_or = _cmp(jnp.logical_or, "logical_or")
logical_xor = _cmp(jnp.logical_xor, "logical_xor")
bitwise_and = _cmp(jnp.bitwise_and, "bitwise_and")
bitwise_or = _cmp(jnp.bitwise_or, "bitwise_or")
bitwise_xor = _cmp(jnp.bitwise_xor, "bitwise_xor")
bitwise_left_shift = _cmp(jnp.left_shift, "bitwise_left_shift")
bitwise_right_shift = _cmp(jnp.right_shift, "bitwise_right_shift")


def logical_not(x, out=None, name=None):
    return apply(jnp.logical_not, x, op_name="logical_not")


def bitwise_not(x, out=None, name=None):
    return apply(jnp.bitwise_not, x, op_name="bitwise_not")


def equal_all(x, y, name=None):
    return apply(lambda a, b: jnp.array_equal(a, b), x, y, op_name="equal_all")


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(
        lambda a, b: jnp.allclose(a, b, rtol=float(rtol), atol=float(atol), equal_nan=equal_nan),
        x,
        y,
        op_name="allclose",
    )


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(
        lambda a, b: jnp.isclose(a, b, rtol=float(rtol), atol=float(atol), equal_nan=equal_nan),
        x,
        y,
        op_name="isclose",
    )


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0), _internal=True)


def is_tensor(x):
    return isinstance(x, Tensor)


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return apply(
        lambda a, t: jnp.isin(a, t, assume_unique=assume_unique, invert=invert),
        x,
        test_x,
        op_name="isin",
    )


def is_complex(x):
    """ref: python/paddle/tensor/attribute.py is_complex."""
    return np.issubdtype(np.dtype(x.dtype), np.complexfloating)


def is_integer(x):
    """ref: attribute.py is_integer."""
    return np.issubdtype(np.dtype(x.dtype), np.integer)


def is_floating_point(x):
    """ref: attribute.py is_floating_point."""
    d = np.dtype(x.dtype)
    import ml_dtypes

    return np.issubdtype(d, np.floating) or d == np.dtype(ml_dtypes.bfloat16) or d == np.dtype(ml_dtypes.float8_e4m3fn)
