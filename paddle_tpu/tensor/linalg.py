"""Linear algebra ops (ref: python/paddle/tensor/linalg.py).

matmul & friends are the MXU path: keep operands batched and let XLA tile
them onto the systolic array. bf16 accumulation uses f32 by default via
``precision``/preferred_element_type.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import dtype as dtypes
from ..base.tape import apply
from ..base.tensor import Tensor


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def _f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        pref = None
        precision = None
        if np.result_type(a) in (dtypes.bfloat16, dtypes.float16):
            # low-precision inputs: MXU-native, accumulate in f32
            pref = jnp.float32
        elif np.result_type(a) == dtypes.float32:
            # f32 inputs: full precision (TPU default truncates to bf16;
            # the reference's cuBLAS fp32 path does not — parity)
            precision = jax.lax.Precision.HIGHEST
        out = jnp.matmul(a, b, preferred_element_type=pref, precision=precision)
        if pref is not None:
            out = out.astype(np.result_type(a))
        return out

    return apply(_f, x, y, op_name="matmul")


mm = matmul


def bmm(x, y, name=None):
    return matmul(x, y)


def dot(x, y, name=None):
    def _f(a, b):
        if a.ndim == 1:
            return jnp.dot(a, b)
        return jnp.sum(a * b, axis=-1)

    return apply(_f, x, y, op_name="dot")


def mv(x, vec, name=None):
    return apply(lambda a, v: a @ v, x, vec, op_name="mv")


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def _f(a):
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if p is None or p == "fro":
            if ax is None:
                return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(a))))
            return jnp.linalg.norm(a, ord=None, axis=ax, keepdims=keepdim)
        if p == "nuc":
            return jnp.linalg.norm(a, ord="nuc", axis=ax, keepdims=keepdim)
        if p == float("inf"):
            if ax is None:
                return jnp.max(jnp.abs(a))
            return jnp.linalg.norm(a, ord=np.inf, axis=ax, keepdims=keepdim)
        if p == float("-inf"):
            if ax is None:
                return jnp.min(jnp.abs(a))
            return jnp.linalg.norm(a, ord=-np.inf, axis=ax, keepdims=keepdim)
        if ax is None:
            return jnp.sum(jnp.abs(a) ** p) ** (1.0 / p)
        if isinstance(ax, tuple) and len(ax) > 1:
            return jnp.linalg.norm(a, ord=p, axis=ax, keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)

    return apply(_f, x, op_name="norm")


vector_norm = norm


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return apply(
        lambda a: jnp.linalg.norm(a, ord=p if p != "fro" else None, axis=tuple(axis), keepdims=keepdim),
        x,
        op_name="matrix_norm",
    )


def dist(x, y, p=2, name=None):
    return norm(x - y if isinstance(x, Tensor) else Tensor(x) - y, p=p)


def cond(x, p=None, name=None):
    return apply(lambda a: jnp.linalg.cond(a, p=p), x, op_name="cond")


def cross(x, y, axis=9, name=None):
    def _f(a, b):
        ax = axis
        if ax == 9:  # paddle default: first axis with dim 3
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)

    return apply(_f, x, y, op_name="cross")


def cholesky(x, upper=False, name=None):
    def _f(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2).conj() if upper else L

    return apply(_f, x, op_name="cholesky")


def cholesky_solve(x, y, upper=False, name=None):
    def _f(b, L):
        return jax.scipy.linalg.cho_solve((L, not upper), b)

    return apply(_f, x, y, op_name="cholesky_solve")


def qr(x, mode="reduced", name=None):
    outs = apply(lambda a: tuple(jnp.linalg.qr(a, mode=mode)) if mode != "r" else (jnp.linalg.qr(a, mode="r"),), x, op_name="qr")
    return outs if mode != "r" else outs[0]


def svd(x, full_matrices=False, name=None):
    return apply(
        lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)),
        x,
        op_name="svd",
    )


def svdvals(x, name=None):
    return apply(lambda a: jnp.linalg.svd(a, compute_uv=False), x, op_name="svdvals")


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    def _f(a):
        if center:
            a = a - jnp.mean(a, axis=-2, keepdims=True)
        u, s, vh = jnp.linalg.svd(a, full_matrices=False)
        k = q or min(6, a.shape[-1])
        return u[..., :k], s[..., :k], jnp.swapaxes(vh, -1, -2)[..., :k]

    return apply(_f, x, op_name="pca_lowrank")


def eig(x, name=None):
    """General eig: CPU-only in XLA; falls back to numpy eagerly."""
    from .manipulation import _require_eager

    _require_eager("eig", x)
    a = np.asarray(x._data if isinstance(x, Tensor) else x)
    w, v = np.linalg.eig(a)
    return Tensor(jnp.asarray(w), _internal=True), Tensor(jnp.asarray(v), _internal=True)


def eigvals(x, name=None):
    from .manipulation import _require_eager

    _require_eager("eigvals", x)
    a = np.asarray(x._data if isinstance(x, Tensor) else x)
    return Tensor(jnp.asarray(np.linalg.eigvals(a)), _internal=True)


def eigh(x, UPLO="L", name=None):
    return apply(lambda a: tuple(jnp.linalg.eigh(a, symmetrize_input=True)), x, op_name="eigh")


def eigvalsh(x, UPLO="L", name=None):
    return apply(lambda a: jnp.linalg.eigvalsh(a), x, op_name="eigvalsh")


def inv(x, name=None):
    return apply(jnp.linalg.inv, x, op_name="inv")


inverse = inv


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply(lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian), x, op_name="pinv")


def solve(x, y, name=None):
    return apply(jnp.linalg.solve, x, y, op_name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def _f(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular
        )

    return apply(_f, x, y, op_name="triangular_solve")


def lstsq(x, y, rcond=None, driver=None, name=None):
    outs = apply(
        lambda a, b: tuple(jnp.linalg.lstsq(a, b, rcond=rcond)), x, y, op_name="lstsq"
    )
    return outs


def lu(x, pivot=True, get_infos=False, name=None):
    def _f(a):
        lu_, piv = jax.scipy.linalg.lu_factor(a)
        return lu_, piv.astype(jnp.int32) + 1  # paddle pivots are 1-based

    lu_t, piv = apply(_f, x, op_name="lu")
    if get_infos:
        from .creation import zeros

        return lu_t, piv, zeros([1], dtype="int32")
    return lu_t, piv


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True, name=None):
    def _f(a, piv):
        m = a.shape[-2]
        L = jnp.tril(a, -1) + jnp.eye(m, a.shape[-1], dtype=a.dtype)
        U = jnp.triu(a)
        # build permutation matrix from 1-based pivots
        perm = jnp.arange(m)
        piv0 = piv - 1

        def body(i, p):
            pi = piv0[i]
            a_, b_ = p[i], p[pi]
            p = p.at[i].set(b_)
            return p.at[pi].set(a_)

        perm = jax.lax.fori_loop(0, piv.shape[-1], body, perm)
        P = jnp.eye(m, dtype=a.dtype)[perm].T
        return P, L[..., : min(a.shape[-2:]), :][..., : a.shape[-2], : min(a.shape[-2:])], U

    P, L, U = apply(_f, lu_data, lu_pivots, op_name="lu_unpack")
    return P, L, U


def matrix_power(x, n, name=None):
    return apply(lambda a: jnp.linalg.matrix_power(a, n), x, op_name="matrix_power")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply(lambda a: jnp.linalg.matrix_rank(a, rtol=tol), x, op_name="matrix_rank")


def det(x, name=None):
    return apply(jnp.linalg.det, x, op_name="det")


def slogdet(x, name=None):
    def _f(a):
        sign, logabs = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logabs])

    return apply(_f, x, op_name="slogdet")


def multi_dot(x, name=None):
    return apply(lambda *arrs: jnp.linalg.multi_dot(arrs), *x, op_name="multi_dot")


def histogram(input, bins=100, min=0, max=0, weight=None, density=False, name=None):  # noqa: A002
    def _f(a, *w):
        lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
        h, _ = jnp.histogram(a, bins=bins, range=(lo, hi), weights=w[0] if w else None, density=density)
        return h

    args = (input, weight) if weight is not None else (input,)
    return apply(_f, *args, op_name="histogram")


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    a = np.asarray(x._data if isinstance(x, Tensor) else x)
    w = np.asarray(weights._data) if isinstance(weights, Tensor) else weights
    h, edges = np.histogramdd(a, bins=bins, range=ranges, density=density, weights=w)
    return Tensor(jnp.asarray(h), _internal=True), [Tensor(jnp.asarray(e), _internal=True) for e in edges]


def bincount(x, weights=None, minlength=0, name=None):
    from .manipulation import _require_eager

    _require_eager("bincount", x)
    a = np.asarray(x._data if isinstance(x, Tensor) else x)
    length = max(minlength, int(a.max()) + 1 if a.size else 0)

    def _f(xx, *w):
        return jnp.bincount(xx, weights=w[0] if w else None, length=length)

    args = (x, weights) if weights is not None else (x,)
    return apply(_f, *args, op_name="bincount")


def corrcoef(x, rowvar=True, name=None):
    return apply(lambda a: jnp.corrcoef(a, rowvar=rowvar), x, op_name="corrcoef")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply(
        lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0),
        x,
        op_name="cov",
    )


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary", name=None):
    def _f(a, b):
        d = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(d * d, axis=-1) + 1e-30)
        return jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)

    return apply(_f, x, y, op_name="cdist")


def householder_product(x, tau, name=None):
    def _f(a, t):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)
        Q = jnp.broadcast_to(eye, a.shape[:-2] + (m, m)).copy() if a.ndim > 2 else eye

        def body(i, Q):
            v = jnp.where(jnp.arange(m) < i, 0.0, a[..., :, i].at[..., i].set(1.0))
            H = jnp.eye(m, dtype=a.dtype) - t[..., i] * jnp.outer(v, v)
            return Q @ H

        for i in range(n):
            v = a[..., :, i]
            v = jnp.where(jnp.arange(m) < i, jnp.zeros_like(v), v)
            v = v.at[i].set(1.0)
            H = jnp.eye(m, dtype=a.dtype) - t[..., i] * jnp.outer(v, v)
            Q = Q @ H
        return Q[..., :, :n]

    return apply(_f, x, tau, op_name="householder_product")


# -- parity sweep (ref: python/paddle/linalg.py remaining entries) ----------


def cholesky_inverse(x, upper=False, name=None):
    """Inverse from a Cholesky factor (ref tensor/linalg.py
    cholesky_inverse): A^-1 where A = LL^T (or U^T U)."""

    def _f(a):
        eye = jnp.eye(a.shape[-1], dtype=a.dtype)
        return jax.scipy.linalg.cho_solve((a, not upper), eye)

    return apply(_f, x, op_name="cholesky_inverse")


def matrix_exp(x, name=None):
    """Matrix exponential (ref tensor/linalg.py matrix_exp)."""
    return apply(jax.scipy.linalg.expm, x, op_name="matrix_exp")


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD (ref tensor/linalg.py svd_lowrank):
    subspace iteration, returns (U, S, V) with q columns."""

    def _f(a, *m):
        d = a - m[0] if m else a
        n = d.shape[-1]
        key = jax.random.PRNGKey(0)
        omega = jax.random.normal(key, d.shape[:-2] + (n, q), d.dtype)
        y = d @ omega
        for _ in range(niter):
            y = d @ (jnp.swapaxes(d, -1, -2) @ y)
        qmat, _ = jnp.linalg.qr(y)
        b = jnp.swapaxes(qmat, -1, -2) @ d
        u_hat, s, vt = jnp.linalg.svd(b, full_matrices=False)
        return qmat @ u_hat, s, jnp.swapaxes(vt, -1, -2)

    args = (x,) + ((M,) if M is not None else ())
    return apply(_f, *args, op_name="svd_lowrank")


def ormqr(x, tau, y, left=True, transpose=False, name=None):
    """Multiply y by Q from a householder factorization (ref
    tensor/linalg.py ormqr)."""

    def _f(a, t, other):
        qmat = jax.lax.linalg.householder_product(a, t)
        qm = jnp.swapaxes(qmat, -1, -2) if transpose else qmat
        return qm @ other if left else other @ qm

    return apply(_f, x, tau, y, op_name="ormqr")


def fp8_fp8_half_gemm_fused(x, y, bias=None, transpose_x=False, transpose_y=False,
                            scale=1.0, output_dtype="float16", act="identity",
                            name=None):
    """fp8 x fp8 -> half GEMM (ref: tensor/linalg.py:327
    fp8_fp8_half_gemm_fused, cutlass fp8 kernels). On TPU this is a
    dot_general with fp8 inputs and a wider accumulator — the MXU path
    XLA emits for float8_e4m3fn operands. ``act`` fuses the epilogue
    activation like the reference (identity | relu | gelu)."""
    import ml_dtypes

    if act not in ("identity", "relu", "gelu"):
        raise ValueError(f"fp8_fp8_half_gemm_fused: unsupported act {act!r}")
    out_dt = jnp.bfloat16 if output_dtype in ("bfloat16",) else jnp.float16

    def _f(a, b, *mb):
        a = a.astype(ml_dtypes.float8_e4m3fn)
        b = b.astype(ml_dtypes.float8_e4m3fn)
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = jax.lax.dot_general(
            a, b, (((a.ndim - 1,), (b.ndim - 2,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if mb:
            out = out + mb[0]
        if act == "relu":
            out = jnp.maximum(out, 0.0)
        elif act == "gelu":
            out = jax.nn.gelu(out)
        return out.astype(out_dt)

    args = (x, y) + ((bias,) if bias is not None else ())
    return apply(_f, *args, op_name="fp8_fp8_half_gemm_fused")
