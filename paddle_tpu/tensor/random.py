"""Random sampling ops (ref: python/paddle/tensor/random.py).

All draws pull subkeys from the default Generator (base/random.py), so
``paddle_tpu.seed`` controls everything and the functionalized train step
can thread RNG state through jit.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import dtype as dtypes
from ..base import random as _random
from ..base.tape import apply


def _cint():
    from ..base.dtype import canonical_int

    return canonical_int()
from ..base.tensor import Tensor
from .creation import _dt, _shape


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):  # noqa: A002
    key = _random.next_key() if not seed else jax.random.key(seed)
    return Tensor(
        jax.random.uniform(key, _shape(shape), _dt(dtype), minval=min, maxval=max),
        _internal=True,
    )


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):  # noqa: A002
    return x.set_value(uniform(tuple(x.shape), x.dtype, min, max, seed)._data)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        def _f(m, s):
            shp = np.broadcast_shapes(np.shape(m), np.shape(s))
            return m + s * jax.random.normal(_random.next_key(), shp, dtypes.get_default_dtype())

        return apply(_f, mean, std, op_name="normal")
    key = _random.next_key()
    return Tensor(
        mean + std * jax.random.normal(key, _shape(shape), _dt(None)), _internal=True
    )


def normal_(x, mean=0.0, std=1.0, name=None):
    return x.set_value(normal(mean, std, tuple(x.shape))._data)


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    key = _random.next_key() if not seed else jax.random.key(seed)
    return Tensor(
        mean + std * jax.random.normal(key, _shape(shape), _dt(dtype)), _internal=True
    )


def standard_normal(shape, dtype=None, name=None):
    return gaussian(shape, 0.0, 1.0, 0, dtype)


def standard_gamma(alpha, name=None):
    def _f(a):
        return jax.random.gamma(_random.next_key(), a)

    return apply(_f, alpha, op_name="standard_gamma")


def randn(shape, dtype=None, name=None):
    return standard_normal(shape, dtype)


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    key = _random.next_key()
    return Tensor(
        jax.random.randint(key, _shape(shape), low, high, _dt(dtype, np.dtype("int64"))),
        _internal=True,
    )


def randint_like(x, low=0, high=None, dtype=None, name=None):
    return randint(low, high, tuple(x.shape), dtype or x.dtype)


def randperm(n, dtype="int64", name=None):
    key = _random.next_key()
    return Tensor(
        jax.random.permutation(key, n).astype(_dt(dtype, np.dtype("int64"))),
        _internal=True,
    )


def bernoulli(x, name=None):
    def _f(p):
        return jax.random.bernoulli(_random.next_key(), p).astype(p.dtype)

    return apply(_f, x.detach() if isinstance(x, Tensor) else x, op_name="bernoulli")


def bernoulli_(x, p=0.5, name=None):
    key = _random.next_key()
    return x.set_value(jax.random.bernoulli(key, p, tuple(x.shape)).astype(x._data.dtype))


def binomial(count, prob, name=None):
    def _f(n, p):
        return jax.random.binomial(_random.next_key(), n, p).astype(_cint())

    return apply(_f, count, prob, op_name="binomial")


def poisson(x, name=None):
    def _f(lam):
        return jax.random.poisson(_random.next_key(), lam).astype(lam.dtype)

    return apply(_f, x.detach() if isinstance(x, Tensor) else x, op_name="poisson")


def multinomial(x, num_samples=1, replacement=False, name=None):
    key = _random.next_key()
    a = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    logits = jnp.log(jnp.maximum(a, 1e-30))
    if replacement:
        out = jax.random.categorical(key, logits, axis=-1, shape=(*a.shape[:-1], num_samples) if a.ndim > 1 else (num_samples,))
        if a.ndim > 1:
            out = out.reshape(*a.shape[:-1], num_samples)
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(key, a.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(_cint()), _internal=True)


def exponential_(x, lam=1.0, name=None):
    key = _random.next_key()
    return x.set_value(jax.random.exponential(key, tuple(x.shape), x._data.dtype) / lam)


def cauchy_(x, loc=0, scale=1, name=None):
    key = _random.next_key()
    return x.set_value(loc + scale * jax.random.cauchy(key, tuple(x.shape), x._data.dtype))


def geometric_(x, probs, name=None):
    key = _random.next_key()
    u = jax.random.uniform(key, tuple(x.shape), jnp.float32, 1e-7, 1.0)
    return x.set_value((jnp.ceil(jnp.log(u) / jnp.log1p(-probs))).astype(x._data.dtype))


def log_normal_(x, mean=1.0, std=2.0, name=None):
    key = _random.next_key()
    return x.set_value(jnp.exp(mean + std * jax.random.normal(key, tuple(x.shape), x._data.dtype)))


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    """Sample exp(N(mean, std)) (ref: python/paddle/tensor/random.py
    log_normal)."""
    key = _random.next_key()
    return Tensor(
        jnp.exp(mean + std * jax.random.normal(key, _shape(shape), _dt(None))),
        _internal=True,
    )
