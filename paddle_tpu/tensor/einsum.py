"""einsum (ref: python/paddle/tensor/einsum.py) — direct jnp.einsum,
which XLA maps onto MXU dot_generals."""
from __future__ import annotations

import jax.numpy as jnp

from ..base.tape import apply


def einsum(equation, *operands, name=None):
    if not isinstance(equation, str):
        raise TypeError("einsum equation must be a string")
    return apply(
        lambda *arrs: jnp.einsum(equation, *arrs), *operands, op_name="einsum"
    )
