"""Math ops (ref: python/paddle/tensor/math.py, ops.py).

Every op lowers to jnp/lax through the tape dispatch (base/tape.apply),
which records vjp closures when grads are needed. XLA fuses chains of
these elementwise ops into single kernels — the role phi's fused
elementwise CUDA kernels play in the reference.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import dtype as dtypes
from ..base.tape import apply


def _cint():
    from ..base.dtype import canonical_int

    return canonical_int()
from ..base.tensor import Tensor


def _unary(jfn, opname):
    def op(x, name=None):
        return apply(jfn, x, op_name=opname)

    op.__name__ = opname
    return op


def _binary(jfn, opname):
    def op(x, y, name=None):
        return apply(jfn, x, y, op_name=opname)

    op.__name__ = opname
    return op


# -- elementwise binary ------------------------------------------------------
add = _binary(jnp.add, "add")
subtract = _binary(jnp.subtract, "subtract")
multiply = _binary(jnp.multiply, "multiply")
divide = _binary(jnp.divide, "divide")
floor_divide = _binary(jnp.floor_divide, "floor_divide")
remainder = _binary(jnp.remainder, "remainder")
mod = remainder
floor_mod = remainder
fmax = _binary(jnp.fmax, "fmax")
fmin = _binary(jnp.fmin, "fmin")
maximum = _binary(jnp.maximum, "maximum")
minimum = _binary(jnp.minimum, "minimum")
logaddexp = _binary(jnp.logaddexp, "logaddexp")
atan2 = _binary(jnp.arctan2, "atan2")
hypot = _binary(jnp.hypot, "hypot")
nextafter = _binary(jnp.nextafter, "nextafter")
copysign = _binary(jnp.copysign, "copysign")
heaviside = _binary(jnp.heaviside, "heaviside")
gcd = _binary(jnp.gcd, "gcd")
lcm = _binary(jnp.lcm, "lcm")
inner = _binary(jnp.inner, "inner")
ldexp = _binary(jnp.ldexp, "ldexp")


def pow(x, y, name=None):  # noqa: A001
    return apply(jnp.power, x, y, op_name="pow")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    def _f(a, s, b):
        out = a * s + b if bias_after_scale else (a + b) * s
        return out

    out = apply(_f, x, scale, bias, op_name="scale")
    if act is not None:
        from ..nn import functional as F

        out = getattr(F, act)(out)
    return out


# -- elementwise unary -------------------------------------------------------
abs = _unary(jnp.abs, "abs")  # noqa: A001
sqrt = _unary(jnp.sqrt, "sqrt")
rsqrt = _unary(lambda x: jax.lax.rsqrt(x), "rsqrt")
square = _unary(jnp.square, "square")
exp = _unary(jnp.exp, "exp")
expm1 = _unary(jnp.expm1, "expm1")
log = _unary(jnp.log, "log")
log2 = _unary(jnp.log2, "log2")
log10 = _unary(jnp.log10, "log10")
log1p = _unary(jnp.log1p, "log1p")
sin = _unary(jnp.sin, "sin")
cos = _unary(jnp.cos, "cos")
tan = _unary(jnp.tan, "tan")
asin = _unary(jnp.arcsin, "asin")
acos = _unary(jnp.arccos, "acos")
atan = _unary(jnp.arctan, "atan")
sinh = _unary(jnp.sinh, "sinh")
cosh = _unary(jnp.cosh, "cosh")
tanh = _unary(jnp.tanh, "tanh")
asinh = _unary(jnp.arcsinh, "asinh")
acosh = _unary(jnp.arccosh, "acosh")
atanh = _unary(jnp.arctanh, "atanh")
floor = _unary(jnp.floor, "floor")
ceil = _unary(jnp.ceil, "ceil")
round = _unary(jnp.round, "round")  # noqa: A001
trunc = _unary(jnp.trunc, "trunc")
frac = _unary(lambda x: x - jnp.trunc(x), "frac")
sign = _unary(jnp.sign, "sign")
sgn = _unary(jnp.sign, "sgn")
reciprocal = _unary(jnp.reciprocal, "reciprocal")
neg = _unary(jnp.negative, "neg")
erf = _unary(jax.scipy.special.erf, "erf")
erfinv = _unary(jax.scipy.special.erfinv, "erfinv")
lgamma = _unary(jax.scipy.special.gammaln, "lgamma")
digamma = _unary(jax.scipy.special.digamma, "digamma")
i0 = _unary(jax.scipy.special.i0, "i0")
i0e = _unary(jax.scipy.special.i0e, "i0e")
i1 = _unary(jax.scipy.special.i1, "i1")
i1e = _unary(jax.scipy.special.i1e, "i1e")
angle = _unary(jnp.angle, "angle")
conj = _unary(jnp.conj, "conj")
real = _unary(jnp.real, "real")
imag = _unary(jnp.imag, "imag")
deg2rad = _unary(jnp.deg2rad, "deg2rad")
rad2deg = _unary(jnp.rad2deg, "rad2deg")
isnan = _unary(jnp.isnan, "isnan")
isinf = _unary(jnp.isinf, "isinf")
isfinite = _unary(jnp.isfinite, "isfinite")
isneginf = _unary(jnp.isneginf, "isneginf")
isposinf = _unary(jnp.isposinf, "isposinf")
isreal = _unary(jnp.isreal, "isreal")
exponent = _unary(lambda x: jnp.frexp(x)[1], "exponent")


def logit(x, eps=None, name=None):
    def _f(a):
        if eps is not None:
            a = jnp.clip(a, eps, 1.0 - eps)
        return jnp.log(a / (1.0 - a))

    return apply(_f, x, op_name="logit")


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply(lambda a: scale_b * jnp.tanh(scale_a * a), x, op_name="stanh")


def multiplex(inputs, index, name=None):
    return apply(
        lambda idx, *ins: jnp.stack(ins, 0)[idx.reshape(-1), jnp.arange(ins[0].shape[0])],
        index,
        *inputs,
        op_name="multiplex",
    )


def clip(x, min=None, max=None, name=None):  # noqa: A002
    lo = min.item() if isinstance(min, Tensor) else min
    hi = max.item() if isinstance(max, Tensor) else max
    return apply(lambda a: jnp.clip(a, lo, hi), x, op_name="clip")


def lerp(x, y, weight, name=None):
    return apply(lambda a, b, w: a + w * (b - a), x, y, weight, op_name="lerp")


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply(
        lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf),
        x,
        op_name="nan_to_num",
    )


# -- reductions --------------------------------------------------------------
def _norm_axis(axis):
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return axis if axis is None else int(axis)


def _reduce(jfn, opname):
    def op(x, axis=None, keepdim=False, name=None):
        return apply(
            lambda a: jfn(a, axis=_norm_axis(axis), keepdims=keepdim),
            x,
            op_name=opname,
        )

    op.__name__ = opname
    return op


sum = _reduce(jnp.sum, "sum")  # noqa: A001
mean = _reduce(jnp.mean, "mean")
prod = _reduce(jnp.prod, "prod")
max = _reduce(jnp.max, "max")  # noqa: A001
min = _reduce(jnp.min, "min")  # noqa: A001
amax = _reduce(jnp.max, "amax")
amin = _reduce(jnp.min, "amin")
nansum = _reduce(jnp.nansum, "nansum")
nanmean = _reduce(jnp.nanmean, "nanmean")
all = _reduce(jnp.all, "all")  # noqa: A001
any = _reduce(jnp.any, "any")  # noqa: A001


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply(
        lambda a: jax.scipy.special.logsumexp(a, axis=_norm_axis(axis), keepdims=keepdim),
        x,
        op_name="logsumexp",
    )


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply(
        lambda a: jnp.count_nonzero(a, axis=_norm_axis(axis), keepdims=keepdim),
        x,
        op_name="count_nonzero",
    )


def cumsum(x, axis=None, dtype=None, name=None):
    def _f(a):
        if axis is None:
            a = a.reshape(-1)
            return jnp.cumsum(a, dtype=dtypes.convert_dtype(dtype))
        return jnp.cumsum(a, axis=int(axis), dtype=dtypes.convert_dtype(dtype))

    return apply(_f, x, op_name="cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    def _f(a):
        if dim is None:
            a = a.reshape(-1)
            return jnp.cumprod(a, dtype=dtypes.convert_dtype(dtype))
        return jnp.cumprod(a, axis=int(dim), dtype=dtypes.convert_dtype(dtype))

    return apply(_f, x, op_name="cumprod")


def cummax(x, axis=None, dtype="int64", name=None):
    vals = apply(
        lambda a: jax.lax.associative_scan(
            jnp.maximum, a.reshape(-1) if axis is None else a, axis=0 if axis is None else int(axis)
        ),
        x,
        op_name="cummax",
    )
    a = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    ax = 0 if axis is None else int(axis)
    if axis is None:
        a = a.reshape(-1)
    return vals, Tensor(_prefix_arg(a, ax, jnp.maximum), _internal=True)


def cummin(x, axis=None, dtype="int64", name=None):
    vals = apply(
        lambda a: jax.lax.associative_scan(
            jnp.minimum, a.reshape(-1) if axis is None else a, axis=0 if axis is None else int(axis)
        ),
        x,
        op_name="cummin",
    )
    a = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    ax = 0 if axis is None else int(axis)
    if axis is None:
        a = a.reshape(-1)
    return vals, Tensor(_prefix_arg(a, ax, jnp.minimum), _internal=True)


def _prefix_arg(a, ax, cmp):
    """Indices of the running max/min along ax (associative scan on pairs)."""
    idx = jnp.broadcast_to(
        jnp.arange(a.shape[ax]).reshape([-1 if i == ax else 1 for i in range(a.ndim)]),
        a.shape,
    ).astype(_cint() if jax.config.jax_enable_x64 else jnp.int32)

    def combine(p, q):
        pv, pi = p
        qv, qi = q
        take_q = cmp(pv, qv) == qv
        return cmp(pv, qv), jnp.where(take_q, qi, pi)

    _, ind = jax.lax.associative_scan(combine, (a, idx), axis=ax)
    return ind


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(
        lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2),
        x,
        op_name="trace",
    )


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(
        lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2),
        x,
        op_name="diagonal",
    )


def kron(x, y, name=None):
    return apply(jnp.kron, x, y, op_name="kron")


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    def _f(a, *extras):
        pre = extras[0] if prepend is not None else None
        app = extras[-1] if append is not None else None
        return jnp.diff(a, n=n, axis=axis, prepend=pre, append=app)

    extras = [e for e in (prepend, append) if e is not None]
    return apply(_f, x, *extras, op_name="diff")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    return apply(
        lambda i, a, b: beta * i + alpha * (a @ b), input, x, y, op_name="addmm"
    )


def outer(x, y, name=None):
    return apply(lambda a, b: jnp.outer(a, b), x, y, op_name="outer")


def vander(x, n=None, increasing=False, name=None):
    return apply(lambda a: jnp.vander(a, N=n, increasing=increasing), x, op_name="vander")


def renorm(x, p, axis, max_norm, name=None):
    def _f(a):
        dims = tuple(i for i in range(a.ndim) if i != axis % a.ndim)
        norms = jnp.sum(jnp.abs(a) ** p, axis=dims, keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return a * factor

    return apply(_f, x, op_name="renorm")


def take(x, index, mode="raise", name=None):
    def _f(a, i):
        flat = a.reshape(-1)
        if mode == "wrap":
            i = i % flat.shape[0]
        elif mode == "clip":
            i = jnp.clip(i, 0, flat.shape[0] - 1)
        return flat[i]

    return apply(_f, x, index, op_name="take")


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def increment(x, value=1.0, name=None):
    return x._inplace_from(apply(lambda a: a + value, x, op_name="increment"))


# in-place variants (functional rebinding; see base/tensor.py docstring)
def _make_inplace(fn):
    def inplace(x, *args, **kwargs):
        return x._inplace_from(fn(x, *args, **kwargs))

    inplace.__name__ = fn.__name__ + "_"
    return inplace


add_ = _make_inplace(add)
subtract_ = _make_inplace(subtract)
multiply_ = _make_inplace(multiply)
divide_ = _make_inplace(divide)
scale_ = _make_inplace(scale)
clip_ = _make_inplace(clip)
exp_ = _make_inplace(exp)
sqrt_ = _make_inplace(sqrt)
rsqrt_ = _make_inplace(rsqrt)
reciprocal_ = _make_inplace(reciprocal)
round_ = _make_inplace(round)
floor_ = _make_inplace(floor)
ceil_ = _make_inplace(ceil)
tanh_ = _make_inplace(tanh)
abs_ = _make_inplace(abs)
neg_ = _make_inplace(neg)


# -- parity sweep: special functions & reductions ---------------------------
# (ref: python/paddle/tensor/math.py entries added for torch-parity APIs)

sinc = _unary(jnp.sinc, "sinc")
signbit = _unary(jnp.signbit, "signbit")
gammaln = _unary(jax.scipy.special.gammaln, "gammaln")


def gammainc(x, y, name=None):
    """Regularized lower incomplete gamma P(x, y) (ref math.py gammainc)."""
    return apply(jax.scipy.special.gammainc, x, y, op_name="gammainc")


def gammaincc(x, y, name=None):
    """Regularized upper incomplete gamma Q(x, y)."""
    return apply(jax.scipy.special.gammaincc, x, y, op_name="gammaincc")


def multigammaln(x, p, name=None):
    """log multivariate gamma: sum_i gammaln(x - i/2) + const (ref math.py)."""

    def _f(a):
        a = a.astype(jnp.float32) if a.dtype not in (jnp.float32, jnp.float64) else a
        const = 0.25 * p * (p - 1) * np.log(np.pi)
        i = jnp.arange(p, dtype=a.dtype)
        return const + jnp.sum(
            jax.scipy.special.gammaln(a[..., None] - i / 2.0), axis=-1
        )

    return apply(_f, x, op_name="multigammaln")


def polygamma(x, n, name=None):
    """n-th derivative of digamma (ref math.py polygamma)."""
    return apply(lambda a: jax.scipy.special.polygamma(n, a), x, op_name="polygamma")


def logcumsumexp(x, axis=None, dtype=None, name=None):
    """Cumulative logsumexp (ref math.py logcumsumexp)."""

    def _f(a):
        if axis is None:
            return jax.lax.cumlogsumexp(a.reshape(-1), axis=0)
        return jax.lax.cumlogsumexp(a, axis=axis)

    out = apply(_f, x, op_name="logcumsumexp")
    return out.astype(dtype) if dtype is not None else out


def frexp(x, name=None):
    """Mantissa/exponent decomposition (ref math.py frexp)."""
    return apply(lambda a: jnp.frexp(a), x, op_name="frexp")


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Trapezoidal integration (ref math.py trapezoid)."""
    if x is not None:
        return apply(
            lambda yy, xx: jnp.trapezoid(yy, xx, axis=axis), y, x, op_name="trapezoid"
        )
    step = 1.0 if dx is None else dx
    return apply(lambda yy: jnp.trapezoid(yy, dx=step, axis=axis), y, op_name="trapezoid")


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Cumulative trapezoidal integration (ref math.py)."""

    def _with_x(yy, xx):
        d = jnp.diff(xx, axis=axis) if xx.ndim > 1 else jnp.diff(xx)
        if xx.ndim == 1 and yy.ndim > 1:
            shape = [1] * yy.ndim
            shape[axis] = d.shape[0]
            d = d.reshape(shape)
        avg = (_take_slice(yy, 1, None) + _take_slice(yy, None, -1)) / 2.0
        return jnp.cumsum(d * avg, axis=axis)

    def _take_slice(a, lo, hi):
        idx = [slice(None)] * a.ndim
        idx[axis if axis >= 0 else a.ndim + axis] = slice(lo, hi)
        return a[tuple(idx)]

    if x is not None:
        return apply(_with_x, y, x, op_name="cumulative_trapezoid")
    step = 1.0 if dx is None else dx

    def _no_x(yy):
        avg = (_take_slice(yy, 1, None) + _take_slice(yy, None, -1)) / 2.0
        return jnp.cumsum(step * avg, axis=axis)

    return apply(_no_x, y, op_name="cumulative_trapezoid")


def reduce_as(x, target, name=None):
    """Sum-reduce x to target's shape (ref math.py reduce_as)."""

    def _f(a, t):
        extra = a.ndim - t.ndim
        if extra:
            a = a.sum(axis=tuple(range(extra)))
        axes = tuple(i for i, (s, ts) in enumerate(zip(a.shape, t.shape)) if s != ts)
        return a.sum(axis=axes, keepdims=True) if axes else a

    return apply(_f, x, target, op_name="reduce_as")


def add_n(inputs, name=None):
    """Elementwise sum of a list of tensors (ref math.py add_n)."""
    import functools
    import operator

    if isinstance(inputs, Tensor):
        return inputs
    return apply(
        lambda *xs: functools.reduce(operator.add, xs), *inputs, op_name="add_n"
    )


def block_diag(inputs, name=None):
    """Block-diagonal matrix from a list (ref math.py block_diag)."""
    return apply(
        lambda *xs: jax.scipy.linalg.block_diag(*[jnp.atleast_2d(x) for x in xs]),
        *inputs,
        op_name="block_diag",
    )


def cartesian_prod(x, name=None):
    """Cartesian product of 1-D tensors (ref math.py cartesian_prod)."""
    xs = x if isinstance(x, (list, tuple)) else [x]

    def _f(*arrs):
        grids = jnp.meshgrid(*arrs, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)

    out = apply(_f, *xs, op_name="cartesian_prod")
    return out


def combinations(x, r=2, with_replacement=False, name=None):
    """r-length combinations of a 1-D tensor (ref math.py combinations)."""
    import itertools

    n = x.shape[0]
    pool = (
        itertools.combinations_with_replacement(range(n), r)
        if with_replacement
        else itertools.combinations(range(n), r)
    )
    idx = np.array(list(pool), np.int32).reshape(-1, r)

    def _f(a):
        return a[jnp.asarray(idx)]

    return apply(_f, x, op_name="combinations")


def pdist(x, p=2.0, name=None):
    """Condensed pairwise distance of [N, D] rows (ref math.py pdist)."""
    n = x.shape[0]
    iu = np.triu_indices(n, k=1)

    def _f(a):
        diff = a[jnp.asarray(iu[0])] - a[jnp.asarray(iu[1])]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(diff * diff, axis=-1))
        return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)

    return apply(_f, x, op_name="pdist")


def sigmoid(x, name=None):
    """ref: tensor/ops.py sigmoid (also exposed as a Tensor method)."""
    return apply(jax.nn.sigmoid, x, op_name="sigmoid")


sigmoid_ = _make_inplace(sigmoid)


def histogram_bin_edges(input, bins=100, min=0.0, max=0.0, name=None):  # noqa: A002
    """ref: linalg.py histogram_bin_edges — min==max (data-derived OR
    user-given) widens the range by +-0.5; max < min raises."""
    if max < min:
        raise ValueError("max must be larger than min in range parameter")

    def _f(a):
        if min == 0 and max == 0:
            lo, hi = jnp.min(a), jnp.max(a)
        else:
            lo = jnp.asarray(min, jnp.float32)
            hi = jnp.asarray(max, jnp.float32)
        same = lo == hi
        lo = jnp.where(same, lo - 0.5, lo)
        hi = jnp.where(same, hi + 0.5, hi)
        return jnp.linspace(lo, hi, bins + 1).astype(jnp.float32)

    return apply(_f, input, op_name="histogram_bin_edges")
