"""Shape/layout manipulation ops (ref: python/paddle/tensor/manipulation.py).

On TPU all of these are XLA reshapes/transposes/gathers; "views" do not
exist (arrays are immutable), so view-style APIs return new Tensors and
the in-place variants rebind (see base/tensor.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import dtype as dtypes
from ..base.tape import apply
from ..base.tensor import Tensor


def _ints(v):
    if isinstance(v, Tensor):
        return tuple(int(i) for i in v.numpy())
    if isinstance(v, (int, np.integer)):
        return (int(v),)
    return tuple(int(i._data if isinstance(i, Tensor) else i) for i in v)


def cast(x, dtype):
    dt = dtypes.convert_dtype(dtype)
    return apply(lambda a: a.astype(dt), x, op_name="cast")


def reshape(x, shape, name=None):
    shape = _ints(shape)
    return apply(lambda a: jnp.reshape(a, shape), x, op_name="reshape")


def reshape_(x, shape, name=None):
    return x._inplace_from(reshape(x, shape))


view = reshape


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def _f(a):
        nd = a.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1 :]
        return jnp.reshape(a, new_shape)

    return apply(_f, x, op_name="flatten")


def transpose(x, perm=None, name=None):
    perm = None if perm is None else _ints(perm)
    return apply(lambda a: jnp.transpose(a, perm), x, op_name="transpose")


def t(x, name=None):
    def _f(a):
        if a.ndim < 2:
            return a
        return a.T

    return apply(_f, x, op_name="t")


def moveaxis(x, source, destination, name=None):
    return apply(
        lambda a: jnp.moveaxis(a, _ints(source), _ints(destination)),
        x,
        op_name="moveaxis",
    )


def swapaxes(x, axis0, axis1, name=None):
    return apply(lambda a: jnp.swapaxes(a, axis0, axis1), x, op_name="swapaxes")


transpose_ = lambda x, perm=None, name=None: x._inplace_from(transpose(x, perm))  # noqa: E731


def squeeze(x, axis=None, name=None):
    def _f(a):
        if axis is None:
            return jnp.squeeze(a)
        axes = _ints(axis)
        axes = tuple(ax % a.ndim for ax in axes)
        axes = tuple(ax for ax in axes if a.shape[ax] == 1)
        return jnp.squeeze(a, axis=axes) if axes else a

    return apply(_f, x, op_name="squeeze")


def squeeze_(x, axis=None, name=None):
    return x._inplace_from(squeeze(x, axis))


def unsqueeze(x, axis, name=None):
    axes = _ints(axis)
    return apply(lambda a: jnp.expand_dims(a, axes), x, op_name="unsqueeze")


def unsqueeze_(x, axis, name=None):
    return x._inplace_from(unsqueeze(x, axis))


def concat(x, axis=0, name=None):
    axis = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    xs = list(x)
    return apply(lambda *arrs: jnp.concatenate(arrs, axis=axis), *xs, op_name="concat")


def stack(x, axis=0, name=None):
    xs = list(x)
    return apply(lambda *arrs: jnp.stack(arrs, axis=axis), *xs, op_name="stack")


def split(x, num_or_sections, axis=0, name=None):
    axis = int(axis.item()) if isinstance(axis, Tensor) else int(axis)

    def _f(a):
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(a, num_or_sections, axis=axis))
        secs = [
            int(s._data if isinstance(s, Tensor) else s) for s in num_or_sections
        ]
        # paddle allows one -1 section
        if -1 in secs:
            known = sum(s for s in secs if s != -1)
            secs[secs.index(-1)] = a.shape[axis] - known
        idx = np.cumsum(secs)[:-1]
        return tuple(jnp.split(a, idx, axis=axis))

    return list(apply(_f, x, op_name="split"))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    n = (x._data if isinstance(x, Tensor) else np.asarray(x)).shape[axis]
    outs = apply(
        lambda a: tuple(jnp.take(a, i, axis=axis) for i in range(n)),
        x,
        op_name="unbind",
    )
    return list(outs)


def unstack(x, axis=0, num=None, name=None):
    return unbind(x, axis)


def expand(x, shape, name=None):
    shape = _ints(shape)

    def _f(a):
        tgt = list(shape)
        # paddle: -1 keeps original dim; leading dims may be added
        off = len(tgt) - a.ndim
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = a.shape[i - off] if i >= off else 1
        return jnp.broadcast_to(a, tuple(tgt))

    return apply(_f, x, op_name="expand")


broadcast_to = expand


def expand_as(x, y, name=None):
    tgt = tuple((y._data if isinstance(y, Tensor) else np.asarray(y)).shape)
    return apply(lambda a: jnp.broadcast_to(a, tgt), x, op_name="expand_as")


def broadcast_tensors(inputs, name=None):
    outs = apply(lambda *arrs: tuple(jnp.broadcast_arrays(*arrs)), *inputs, op_name="broadcast_tensors")
    return list(outs)


def tile(x, repeat_times, name=None):
    reps = _ints(repeat_times)
    return apply(lambda a: jnp.tile(a, reps), x, op_name="tile")


def flip(x, axis, name=None):
    axes = _ints(axis)
    return apply(lambda a: jnp.flip(a, axis=axes), x, op_name="flip")


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x, op_name="rot90")


def roll(x, shifts, axis=None, name=None):
    shifts = _ints(shifts)
    axes = None if axis is None else _ints(axis)

    def _f(a):
        if axes is None:
            return jnp.roll(a, shifts if len(shifts) > 1 else shifts[0])
        return jnp.roll(a, shifts, axis=axes)

    return apply(_f, x, op_name="roll")


def gather(x, index, axis=0, name=None):
    axis = int(axis.item()) if isinstance(axis, Tensor) else int(axis)

    def _f(a, idx):
        if idx.ndim == 0:
            idx = idx[None]
        return jnp.take(a, idx, axis=axis)

    return apply(_f, x, index, op_name="gather")


def gather_nd(x, index, name=None):
    def _f(a, idx):
        k = idx.shape[-1]
        out = a[tuple(jnp.moveaxis(idx, -1, 0))] if k == a.ndim else a[
            tuple(jnp.moveaxis(idx, -1, 0))
        ]
        return out

    return apply(_f, x, index, op_name="gather_nd")


def scatter(x, index, updates, overwrite=True, name=None):
    def _f(a, idx, upd):
        if idx.ndim == 2 and idx.shape[1] == 1:
            idx = idx[:, 0]
        if overwrite:
            return a.at[idx].set(upd)
        # paddle: overwrite=False means zero destination rows then add
        zeroed = a.at[idx].set(jnp.zeros_like(upd))
        return zeroed.at[idx].add(upd)

    return apply(_f, x, index, updates, op_name="scatter")


def scatter_(x, index, updates, overwrite=True, name=None):
    return x._inplace_from(scatter(x, index, updates, overwrite))


def scatter_nd_add(x, index, updates, name=None):
    def _f(a, idx, upd):
        return a.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)

    return apply(_f, x, index, updates, op_name="scatter_nd_add")


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros

    upd_dtype = updates.dtype if isinstance(updates, Tensor) else np.result_type(updates)
    return scatter_nd_add(zeros(shape, dtype=upd_dtype), index, updates)


def index_select(x, index, axis=0, name=None):
    return apply(lambda a, i: jnp.take(a, i, axis=axis), x, index, op_name="index_select")


def index_sample(x, index, name=None):
    return apply(
        lambda a, i: jnp.take_along_axis(a, i, axis=1), x, index, op_name="index_sample"
    )


def index_add(x, index, axis, value, name=None):
    def _f(a, i, v):
        perm = None
        if axis % a.ndim != 0:
            a_m = jnp.moveaxis(a, axis, 0)
            v_m = jnp.moveaxis(v, axis, 0)
            out = a_m.at[i].add(v_m)
            return jnp.moveaxis(out, 0, axis)
        return a.at[i].add(v)

    return apply(_f, x, index, value, op_name="index_add")


def index_put(x, indices, value, accumulate=False, name=None):
    def _f(a, v, *idx):
        ref = a.at[tuple(idx)]
        return ref.add(v) if accumulate else ref.set(v)

    return apply(_f, x, value, *indices, op_name="index_put")


def index_fill(x, index, axis, fill_value, name=None):
    def _f(a, i):
        a_m = jnp.moveaxis(a, axis, 0)
        out = a_m.at[i].set(jnp.asarray(fill_value, a.dtype))
        return jnp.moveaxis(out, 0, axis)

    return apply(_f, x, index, op_name="index_fill")


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply(
        lambda a, i: jnp.take_along_axis(a, i, axis=axis),
        arr,
        indices,
        op_name="take_along_axis",
    )


def put_along_axis(arr, indices, values, axis, reduce="assign", include_self=True, broadcast=True, name=None):  # noqa: A002
    def _f(a, i, v):
        v = jnp.broadcast_to(jnp.asarray(v, a.dtype), i.shape) if not hasattr(v, "shape") or v.shape != i.shape else v
        return jnp.put_along_axis(a, i, v, axis=axis, inplace=False, mode="fill" if False else None) if False else _put(a, i, v)

    def _put(a, i, v):
        dims = [jnp.arange(s).reshape([-1 if d == k else 1 for k in range(i.ndim)]) for d, s in enumerate(i.shape)]
        idx = tuple(i if d == axis % a.ndim else jnp.broadcast_to(dims[d], i.shape) for d in range(a.ndim))
        ref = a.at[idx]
        if reduce == "assign":
            return ref.set(v)
        if reduce in ("add",):
            return ref.add(v)
        if reduce in ("mul", "multiply"):
            return ref.multiply(v)
        if reduce == "amax":
            return ref.max(v)
        if reduce == "amin":
            return ref.min(v)
        raise ValueError(f"unknown reduce {reduce!r}")

    return apply(_f, arr, indices, values, op_name="put_along_axis")


def slice(x, axes, starts, ends, name=None):  # noqa: A001
    import builtins

    axes = _ints(axes)
    starts = _ints(starts)
    ends = _ints(ends)

    def _f(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            idx[ax] = builtins.slice(s, e)
        return a[tuple(idx)]

    return apply(_f, x, op_name="slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    import builtins

    axes, starts, ends, strides = _ints(axes), _ints(starts), _ints(ends), _ints(strides)

    def _f(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = builtins.slice(s, e, st)
        return a[tuple(idx)]

    return apply(_f, x, op_name="strided_slice")


def crop(x, shape=None, offsets=None, name=None):
    import builtins

    shape = _ints(shape)
    offsets = _ints(offsets) if offsets is not None else (0,) * len(shape)

    def _f(a):
        idx = []
        for d in range(a.ndim):
            size = shape[d] if shape[d] != -1 else a.shape[d] - offsets[d]
            idx.append(builtins.slice(offsets[d], offsets[d] + size))
        return a[tuple(idx)]

    return apply(_f, x, op_name="crop")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    from ..nn.functional import pad as _nnpad

    return _nnpad(x, pad, mode=mode, value=value, data_format=data_format)


def repeat_interleave(x, repeats, axis=None, name=None):
    def _f(a, *maybe_r):
        r = maybe_r[0] if maybe_r else repeats
        if axis is None:
            a = a.reshape(-1)
            return jnp.repeat(a, r, total_repeat_length=None if isinstance(r, int) else int(np.sum(np.asarray(r))))
        return jnp.repeat(a, r, axis=axis, total_repeat_length=None if isinstance(r, int) else int(np.sum(np.asarray(r))))

    if isinstance(repeats, Tensor):
        return apply(_f, x, repeats, op_name="repeat_interleave")
    return apply(_f, x, op_name="repeat_interleave")


def as_strided(x, shape, stride, offset=0, name=None):
    """Limited as_strided: materializes via flat gather (no aliasing on TPU)."""

    def _f(a):
        flat = a.reshape(-1)
        idx = np.full(tuple(shape), offset, dtype=np.int64)
        for d, (s, st) in enumerate(zip(shape, stride)):
            ar = np.arange(s) * st
            idx = idx + ar.reshape([-1 if k == d else 1 for k in range(len(shape))])
        return flat[jnp.asarray(idx)]

    return apply(_f, x, op_name="as_strided")


def unfold(x, axis, size, step, name=None):
    def _f(a):
        ax = axis % a.ndim
        n = (a.shape[ax] - size) // step + 1
        starts = np.arange(n) * step
        # window-content dim goes LAST (reference layout: view_as_windows)
        slices = [
            jnp.moveaxis(jnp.take(a, jnp.arange(s, s + size), axis=ax), ax, -1)
            for s in starts
        ]
        return jnp.stack(slices, axis=ax)

    return apply(_f, x, op_name="unfold")


def masked_select(x, mask, name=None):
    """Dynamic-shape op: eager only (under jit, use where/masked ops).

    ref: python/paddle/tensor/search.py masked_select. XLA requires static
    shapes, so under trace this raises with guidance — same stance jax
    takes (jnp.extract).
    """
    _require_eager("masked_select", x, mask)
    a = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    m = mask._data if isinstance(mask, Tensor) else jnp.asarray(mask)
    m = np.broadcast_to(np.asarray(m), a.shape)
    idx = np.nonzero(m)
    flat_idx = jnp.asarray(np.ravel_multi_index(idx, m.shape))
    # gather keeps the op differentiable w.r.t. x
    return apply(lambda arr: arr.reshape(-1)[flat_idx], x, op_name="masked_select")


def masked_fill(x, mask, value, name=None):
    def _f(a, m):
        return jnp.where(m, jnp.asarray(value.item() if isinstance(value, Tensor) else value, a.dtype), a)

    return apply(_f, x, mask, op_name="masked_fill")


def masked_fill_(x, mask, value, name=None):
    return x._inplace_from(masked_fill(x, mask, value))


def masked_scatter(x, mask, value, name=None):
    _require_eager("masked_scatter", x, mask)
    shape = tuple((x._data if isinstance(x, Tensor) else x).shape)
    m = np.broadcast_to(
        np.asarray(mask._data if isinstance(mask, Tensor) else mask), shape
    ).astype(bool)
    idx = np.nonzero(m)  # concrete mask -> static scatter positions
    n = len(idx[0])

    def f(a, v):
        return a.at[idx].set(v.reshape(-1)[:n])

    # differentiable: x's grad is zeroed at scattered slots, value's
    # grad collects from them
    return apply(f, x, value if isinstance(value, Tensor) else Tensor(jnp.asarray(value), _internal=True), op_name="masked_scatter")


def _require_eager(opname, *tensors):
    import jax.core as jcore

    for t in tensors:
        d = t._data if isinstance(t, Tensor) else t
        if isinstance(d, jcore.Tracer):
            raise RuntimeError(
                f"{opname} produces a data-dependent shape and cannot run under "
                f"jit/to_static on TPU; restructure with where/masks, or run eagerly."
            )


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    _require_eager("unique", x)
    a = np.asarray(x._data if isinstance(x, Tensor) else x)
    res = np.unique(a, return_index=return_index, return_inverse=return_inverse, return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        res = (res,)
    outs = tuple(Tensor(jnp.asarray(r), _internal=True) for r in res)
    return outs if len(outs) > 1 else outs[0]


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    _require_eager("unique_consecutive", x)
    a = np.asarray(x._data if isinstance(x, Tensor) else x)
    if axis is None:
        a = a.reshape(-1)
        keep = np.concatenate([[True], a[1:] != a[:-1]])
        n = a.shape[0]
    else:
        axis = axis % a.ndim
        a = np.moveaxis(a, axis, 0)
        n = a.shape[0]
        if n == 0:
            keep = np.zeros((0,), dtype=bool)
        else:
            diff = a[1:] != a[:-1]
            keep = np.concatenate(
                [[True], diff.reshape(n - 1, -1).any(axis=1) if n > 1 else np.zeros((0,), bool)]
            )
    vals = a[keep]
    if axis is not None:
        vals = np.moveaxis(vals, 0, axis)
    outs = [Tensor(jnp.asarray(vals), _internal=True)]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(Tensor(jnp.asarray(inv), _internal=True))
    if return_counts:
        idx = np.nonzero(keep)[0]
        counts = np.diff(np.append(idx, n))
        outs.append(Tensor(jnp.asarray(counts), _internal=True))
    return tuple(outs) if len(outs) > 1 else outs[0]


def chunk_eval(*args, **kwargs):
    raise NotImplementedError("chunk_eval is a legacy sequence op; not provided")


def tensordot(x, y, axes=2, name=None):
    def _norm(ax):
        if isinstance(ax, Tensor):
            return ax.tolist()
        return ax

    return apply(lambda a, b: jnp.tensordot(a, b, axes=_norm(axes)), x, y, op_name="tensordot")


def atleast_1d(*inputs, name=None):
    outs = [apply(jnp.atleast_1d, x, op_name="atleast_1d") for x in inputs]
    return outs if len(outs) > 1 else outs[0]


def atleast_2d(*inputs, name=None):
    outs = [apply(jnp.atleast_2d, x, op_name="atleast_2d") for x in inputs]
    return outs if len(outs) > 1 else outs[0]


def atleast_3d(*inputs, name=None):
    outs = [apply(jnp.atleast_3d, x, op_name="atleast_3d") for x in inputs]
    return outs if len(outs) > 1 else outs[0]


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def as_complex(x, name=None):
    return apply(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x, op_name="as_complex")


def as_real(x, name=None):
    return apply(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), x, op_name="as_real")


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size if isinstance(x, Tensor) else np.asarray(x).size, jnp.int64), _internal=True)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):  # noqa: A002
    def _f(a):
        shard_size = (index_num + nshards - 1) // nshards
        lo = shard_id * shard_size
        in_shard = (a >= lo) & (a < lo + shard_size)
        return jnp.where(in_shard, a - lo, ignore_value)

    return apply(_f, input, op_name="shard_index")


# -- parity sweep: stack/split conveniences & scatter variants --------------
# (ref: python/paddle/tensor/manipulation.py torch-parity additions)


def hstack(x, name=None):
    return apply(lambda *xs: jnp.hstack(xs), *x, op_name="hstack")


def vstack(x, name=None):
    return apply(lambda *xs: jnp.vstack(xs), *x, op_name="vstack")


def dstack(x, name=None):
    return apply(lambda *xs: jnp.dstack(xs), *x, op_name="dstack")


def column_stack(x, name=None):
    return apply(lambda *xs: jnp.column_stack(xs), *x, op_name="column_stack")


def row_stack(x, name=None):
    return vstack(x, name)


def tensor_split(x, num_or_indices, axis=0, name=None):
    """Uneven-capable split (ref manipulation.py tensor_split)."""
    n = x.shape[axis if axis >= 0 else x.ndim + axis]
    if isinstance(num_or_indices, int):
        k = num_or_indices
        base, rem = divmod(n, k)
        bounds = []
        pos = 0
        for i in range(k - 1):
            pos += base + (1 if i < rem else 0)
            bounds.append(pos)
    else:
        bounds = list(num_or_indices)
    outs = apply(
        lambda a: tuple(jnp.split(a, bounds, axis=axis)), x, op_name="tensor_split"
    )
    return list(outs)


def hsplit(x, num_or_indices, name=None):
    if x.ndim < 1:
        raise ValueError("hsplit expects at least 1-D input")
    return tensor_split(x, num_or_indices, axis=0 if x.ndim == 1 else 1)


def vsplit(x, num_or_indices, name=None):
    if x.ndim < 2:
        raise ValueError("vsplit expects at least 2-D input")
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    if x.ndim < 3:
        raise ValueError("dsplit expects at least 3-D input")
    return tensor_split(x, num_or_indices, axis=2)


def unflatten(x, axis, shape, name=None):
    """Expand one axis into the given shape (ref manipulation.py unflatten)."""
    ax = axis if axis >= 0 else x.ndim + axis
    shape = [int(s) for s in shape]

    def _f(a):
        new = list(a.shape[:ax]) + shape + list(a.shape[ax + 1:])
        # one -1 allowed
        return a.reshape(new)

    return apply(_f, x, op_name="unflatten")


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    """Write ``value`` into a strided slice of x (ref manipulation.py)."""
    import builtins as _b

    def _f(a, v):
        idx = [_b.slice(None)] * a.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[ax] = _b.slice(st, en, sd)
        return a.at[tuple(idx)].set(v)

    return apply(_f, x, value, op_name="slice_scatter")


def select_scatter(x, value, axis, index, name=None):
    """Write ``value`` into x at ``index`` along ``axis``."""
    import builtins as _b

    def _f(a, v):
        idx = [_b.slice(None)] * a.ndim
        idx[axis if axis >= 0 else a.ndim + axis] = index
        return a.at[tuple(idx)].set(v)

    return apply(_f, x, value, op_name="select_scatter")


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    """Write y onto a diagonal of x (ref manipulation.py diagonal_scatter)."""
    import builtins as _b

    def _f(a, v):
        n = _b.min(
            a.shape[axis1] - _b.max(-offset, 0),
            a.shape[axis2] - _b.max(offset, 0),
        )
        i = jnp.arange(n)
        idx = [_b.slice(None)] * a.ndim
        idx[axis1] = i + _b.max(-offset, 0)
        idx[axis2] = i + _b.max(offset, 0)
        # y follows x.diagonal()'s layout (diag dim LAST); advanced
        # indexing puts the diag dim first, so align v
        if v.ndim > 1:
            v = jnp.moveaxis(v, -1, 0)
        return a.at[tuple(idx)].set(v)

    return apply(_f, x, y, op_name="diagonal_scatter")


def reverse(x, axis, name=None):
    """Legacy alias of flip (ref manipulation.py reverse)."""
    return flip(x, axis)


def tolist(x):
    """Nested python list of values (ref tensor_patch_methods tolist)."""
    return np.asarray(x._data if isinstance(x, Tensor) else x).tolist()
