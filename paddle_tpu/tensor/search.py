"""Search / sort / indexing ops (ref: python/paddle/tensor/search.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base.tape import apply


def _cint():
    from ..base.dtype import canonical_int

    return canonical_int()
from ..base.tensor import Tensor
from .manipulation import _require_eager


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def _f(a):
        if axis is None:
            return jnp.argmax(a.reshape(-1)).astype(_cint())
        out = jnp.argmax(a, axis=int(axis), keepdims=keepdim)
        return out.astype(_cint())

    return apply(_f, x.detach() if isinstance(x, Tensor) else x, op_name="argmax")


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def _f(a):
        if axis is None:
            return jnp.argmin(a.reshape(-1)).astype(_cint())
        return jnp.argmin(a, axis=int(axis), keepdims=keepdim).astype(_cint())

    return apply(_f, x.detach() if isinstance(x, Tensor) else x, op_name="argmin")


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def _f(a):
        out = jnp.argsort(a, axis=axis, stable=stable, descending=descending)
        return out.astype(_cint())

    return apply(_f, x.detach() if isinstance(x, Tensor) else x, op_name="argsort")


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def _f(a):
        out = jnp.sort(a, axis=axis, stable=stable, descending=descending)
        return out

    return apply(_f, x, op_name="sort")


def topk(x, k, axis=None, largest=True, sorted=True, name=None):  # noqa: A002
    k = int(k.item()) if isinstance(k, Tensor) else int(k)

    def _f(a):
        ax = -1 if axis is None else int(axis)
        moved = jnp.moveaxis(a, ax, -1)
        src = moved if largest else -moved
        vals, idx = jax.lax.top_k(src, k)
        if not largest:
            vals = -vals
        return (
            jnp.moveaxis(vals, -1, ax),
            jnp.moveaxis(idx.astype(_cint()), -1, ax),
        )

    return apply(_f, x, op_name="topk")


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def _f(a):
        moved = jnp.moveaxis(a, axis, -1)
        s = jnp.sort(moved, axis=-1)
        si = jnp.argsort(moved, axis=-1)
        v = s[..., k - 1]
        i = si[..., k - 1].astype(_cint())
        if keepdim:
            v = jnp.expand_dims(v, axis)
            i = jnp.expand_dims(i, axis)
        return v, i

    return apply(_f, x, op_name="kthvalue")


def mode(x, axis=-1, keepdim=False, name=None):
    def _f(a):
        moved = jnp.moveaxis(a, axis, -1)
        s = jnp.sort(moved, axis=-1)
        n = s.shape[-1]
        # count run lengths in sorted order
        eq = s[..., :, None] == s[..., None, :]
        counts = eq.sum(-1)
        best = jnp.argmax(counts, axis=-1)
        vals = jnp.take_along_axis(s, best[..., None], axis=-1)[..., 0]
        idx = jnp.argmax(
            (moved == vals[..., None]) * jnp.arange(n, 0, -1), axis=-1
        )
        idx = (n - 1) - jnp.argmax(jnp.flip(moved == vals[..., None], -1), axis=-1)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals, idx.astype(_cint())

    return apply(_f, x, op_name="mode")


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply(lambda c, a, b: jnp.where(c, a, b), condition, x, y, op_name="where")


def where_(condition, x, y, name=None):
    return x._inplace_from(where(condition, x, y))


def nonzero(x, as_tuple=False):
    _require_eager("nonzero", x)
    a = np.asarray(x._data if isinstance(x, Tensor) else x)
    idx = np.nonzero(a)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i.reshape(-1, 1) if False else i), _internal=True) for i in idx)
    return Tensor(jnp.asarray(np.stack(idx, -1).astype(np.int64)), _internal=True)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    return apply(
        lambda s, v: jnp.searchsorted(s, v, side="right" if right else "left").astype(
            jnp.int32 if out_int32 else _cint()
        )
        if s.ndim == 1
        else jax.vmap(lambda ss, vv: jnp.searchsorted(ss, vv, side="right" if right else "left"))(
            s.reshape(-1, s.shape[-1]), v.reshape(-1, v.shape[-1])
        ).reshape(v.shape).astype(jnp.int32 if out_int32 else _cint()),
        sorted_sequence,
        values,
        op_name="searchsorted",
    )


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


def top_p_sampling(x, ps, threshold=None, topp_seed=None, seed=-1,
                   k=0, mode="truncated", return_top=False, name=None):
    """Nucleus sampling (ref: tensor/search.py top_p_sampling — the
    phi top_p_sampling CUDA kernel): per row, sample from the smallest
    prefix of the sorted distribution whose mass exceeds ps. With
    ``return_top`` also returns the top-k scores/ids like the
    reference; ``threshold`` drops probabilities below it; ``seed``
    >= 0 makes the draw reproducible independently of the generator."""
    from ..base import random as _random

    key = jax.random.PRNGKey(seed) if seed is not None and seed >= 0 else _random.next_key()
    int_dt = _cint()

    def _f(probs, p, *maybe_thresh):
        idx = jnp.argsort(-probs, axis=-1)
        srt = jnp.take_along_axis(probs, idx, axis=-1)
        cum = jnp.cumsum(srt, axis=-1)
        # keep tokens while cumulative mass (exclusive) < p
        keep = (cum - srt) < p.reshape(-1, 1)
        if maybe_thresh:
            keep = keep & (srt >= maybe_thresh[0].reshape(-1, 1))
        masked = jnp.where(keep, srt, 0.0)
        masked = masked / jnp.maximum(masked.sum(-1, keepdims=True), 1e-9)
        g = jax.random.categorical(key, jnp.log(jnp.maximum(masked, 1e-30)), axis=-1)
        tok = jnp.take_along_axis(idx, g[:, None], axis=-1)
        scr = jnp.take_along_axis(probs, tok, axis=-1)
        if return_top:
            kk = k if k and k > 0 else 1
            return (scr, tok.astype(int_dt),
                    srt[:, :kk], idx[:, :kk].astype(int_dt))
        return scr, tok.astype(int_dt)

    args = (x, ps) + ((threshold,) if threshold is not None else ())
    return apply(_f, *args, op_name="top_p_sampling")
