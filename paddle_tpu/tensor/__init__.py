"""paddle_tpu.tensor — op wrappers + Tensor method patching.

Mirrors python/paddle/tensor/__init__.py: every functional op is also
installed as a Tensor method, and operator dunders route through the same
tape dispatch so autograd sees everything.
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..base.tape import apply
from ..base.tensor import Tensor, to_tensor  # noqa: F401
from . import creation, einsum as einsum_mod, linalg, logic, manipulation, math, random, search, stat

from .creation import *  # noqa: F401,F403
from .einsum import einsum  # noqa: F401
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403


# ---------------------------------------------------------------------------
# indexing
# ---------------------------------------------------------------------------


def _has_bool_mask(idx):
    def _chk(i):
        if isinstance(i, Tensor):
            return i.dtype == np.bool_
        if isinstance(i, (np.ndarray, jax.Array)):
            return np.result_type(i) == np.bool_
        return False

    if isinstance(idx, tuple):
        return builtins.any(_chk(i) for i in idx)
    return _chk(idx)


def _tensor_getitem(self: Tensor, idx):
    if _has_bool_mask(idx) and not isinstance(idx, tuple):
        return manipulation.masked_select(self, idx if isinstance(idx, Tensor) else Tensor(idx))

    def _f(a, i):
        if isinstance(i, list):
            i = tuple(i) if builtins.any(isinstance(e, (slice, type(None), type(Ellipsis))) for e in i) else jnp.asarray(i)
        return a[i]

    return apply(_f, self, idx, op_name="getitem")


def _tensor_setitem(self: Tensor, idx, value):
    if _has_bool_mask(idx) and not isinstance(idx, tuple):
        res = apply(
            lambda a, m, v: jnp.where(m, jnp.asarray(v, a.dtype) if not hasattr(v, "dtype") else v.astype(a.dtype), a),
            self,
            idx,
            value,
            op_name="setitem_mask",
        )
        self._inplace_from(res)
        return

    def _f(a, i, v):
        if isinstance(i, list):
            i = jnp.asarray(i)
        v = jnp.asarray(v, a.dtype) if not hasattr(v, "astype") else v.astype(a.dtype)
        return a.at[i].set(v)

    self._inplace_from(apply(_f, self, idx, value, op_name="setitem"))


# ---------------------------------------------------------------------------
# method patching (ref: python/paddle/base/dygraph/tensor_patch_methods.py)
# ---------------------------------------------------------------------------

_METHOD_SOURCES = [creation, math, manipulation, linalg, logic, search, stat, random, einsum_mod]

_NON_METHODS = {
    "to_tensor", "zeros", "ones", "full", "empty", "arange", "linspace", "logspace",
    "eye", "meshgrid", "tril_indices", "triu_indices", "assign", "one_hot",
    "uniform", "randint", "randperm", "randn", "rand", "gaussian", "standard_normal",
    "normal", "scatter_nd", "broadcast_shape", "complex", "polar",
}


def _install_methods():
    for mod in _METHOD_SOURCES:
        for name in dir(mod):
            if name.startswith("_") or name in _NON_METHODS:
                continue
            fn = getattr(mod, name)
            if not callable(fn) or isinstance(fn, type):
                continue
            if getattr(fn, "__module__", "").startswith("jax"):
                continue
            if not hasattr(Tensor, name):
                setattr(Tensor, name, fn)

    Tensor.__getitem__ = _tensor_getitem
    Tensor.__setitem__ = _tensor_setitem

    # arithmetic dunders
    Tensor.__add__ = lambda s, o: math.add(s, o)
    Tensor.__radd__ = lambda s, o: math.add(s, o)
    Tensor.__sub__ = lambda s, o: math.subtract(s, o)
    Tensor.__rsub__ = lambda s, o: math.subtract(Tensor(o) if not isinstance(o, Tensor) else o, s)
    Tensor.__mul__ = lambda s, o: math.multiply(s, o)
    Tensor.__rmul__ = lambda s, o: math.multiply(s, o)
    Tensor.__truediv__ = lambda s, o: math.divide(s, o)
    Tensor.__rtruediv__ = lambda s, o: math.divide(Tensor(o) if not isinstance(o, Tensor) else o, s)
    Tensor.__floordiv__ = lambda s, o: math.floor_divide(s, o)
    Tensor.__rfloordiv__ = lambda s, o: math.floor_divide(Tensor(o) if not isinstance(o, Tensor) else o, s)
    Tensor.__mod__ = lambda s, o: math.remainder(s, o)
    Tensor.__rmod__ = lambda s, o: math.remainder(Tensor(o) if not isinstance(o, Tensor) else o, s)
    Tensor.__pow__ = lambda s, o: math.pow(s, o)
    Tensor.__rpow__ = lambda s, o: math.pow(Tensor(o) if not isinstance(o, Tensor) else o, s)
    Tensor.__matmul__ = lambda s, o: linalg.matmul(s, o)
    Tensor.__rmatmul__ = lambda s, o: linalg.matmul(Tensor(o) if not isinstance(o, Tensor) else o, s)
    Tensor.__neg__ = lambda s: math.neg(s)
    Tensor.__pos__ = lambda s: s
    Tensor.__abs__ = lambda s: math.abs(s)
    Tensor.__invert__ = lambda s: logic.logical_not(s) if s.dtype == np.bool_ else logic.bitwise_not(s)

    # comparisons (elementwise, paddle semantics)
    Tensor.__eq__ = lambda s, o: logic.equal(s, o)
    Tensor.__ne__ = lambda s, o: logic.not_equal(s, o)
    Tensor.__lt__ = lambda s, o: logic.less_than(s, o)
    Tensor.__le__ = lambda s, o: logic.less_equal(s, o)
    Tensor.__gt__ = lambda s, o: logic.greater_than(s, o)
    Tensor.__ge__ = lambda s, o: logic.greater_equal(s, o)

    def _and(s, o):
        return logic.logical_and(s, o) if s.dtype == np.bool_ else logic.bitwise_and(s, o)

    def _or(s, o):
        return logic.logical_or(s, o) if s.dtype == np.bool_ else logic.bitwise_or(s, o)

    def _xor(s, o):
        return logic.logical_xor(s, o) if s.dtype == np.bool_ else logic.bitwise_xor(s, o)

    Tensor.__and__ = _and
    Tensor.__rand__ = _and
    Tensor.__or__ = _or
    Tensor.__ror__ = _or
    Tensor.__xor__ = _xor
    Tensor.__rxor__ = _xor

    # iadd etc. rebind (functional in-place)
    Tensor.__iadd__ = lambda s, o: s._inplace_from(math.add(s, o))
    Tensor.__isub__ = lambda s, o: s._inplace_from(math.subtract(s, o))
    Tensor.__imul__ = lambda s, o: s._inplace_from(math.multiply(s, o))
    Tensor.__itruediv__ = lambda s, o: s._inplace_from(math.divide(s, o))

    # transpose property
    Tensor.T = property(lambda s: manipulation.t(s) if s.ndim <= 2 else manipulation.transpose(s, list(builtins.reversed(builtins.range(s.ndim)))))
    Tensor.mT = property(lambda s: manipulation.swapaxes(s, -1, -2))


_install_methods()


def _install_inplace_sweep():
    """Generate the reference's ``op_`` in-place variants for every op
    whose functional form exists (ref: python/paddle/tensor/__init__.py
    inplace_apis listing; functional rebinding via Tensor._inplace_from)."""
    import sys

    mod = sys.modules[__name__]
    names = [
        # math
        "cumsum", "cumprod", "logit", "cos", "tan", "sin", "acos", "asin",
        "atan", "cosh", "sinh", "expm1", "lgamma", "square", "gcd", "lcm",
        "erf", "log", "log2", "log10", "log1p", "trunc", "frac", "digamma",
        "renorm", "nan_to_num", "i0", "polygamma", "copysign", "hypot",
        "ldexp", "multigammaln", "gammainc", "gammaincc", "gammaln", "sinc",
        "pow", "mod", "floor_divide", "remainder", "floor_mod", "addmm",
        "logical_and", "logical_or", "logical_xor", "logical_not",
        "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
        "bitwise_left_shift", "bitwise_right_shift",
        # comparisons (reference defines in-place forms of these too)
        "equal", "not_equal", "less_than", "less_equal", "greater_than",
        "greater_equal",
        # manipulation / indexing
        "t", "flatten", "triu", "tril", "cast", "index_add", "index_put",
        "index_fill", "masked_scatter",
        "atanh", "acosh", "asinh", "lerp", "erfinv", "put_along_axis",
    ]
    for base in names:
        fn = getattr(mod, base, None)
        if fn is None or hasattr(mod, base + "_"):
            continue
        ip = math._make_inplace(fn)
        setattr(mod, base + "_", ip)
        if not hasattr(Tensor, base + "_"):
            setattr(Tensor, base + "_", ip)


_install_inplace_sweep()


def _install_extra_methods():
    """Methods the reference patches from outside the tensor package
    (ref tensor_method_func): signal stft/istft and the top-level
    create_parameter."""
    from ..signal import istft as _istft, stft as _stft

    for name, fn in (("stft", _stft), ("istft", _istft)):
        if not hasattr(Tensor, name):
            setattr(Tensor, name, fn)
    # the reference's tensor_method_func also binds these free functions
    # (self becomes the first positional arg, e.g. x.scatter_nd(updates,
    # shape) uses x as the index — same binding as the reference)
    for name in ("scatter_nd", "polar"):
        for mod in _METHOD_SOURCES:
            fn = getattr(mod, name, None)
            if fn is not None and not hasattr(Tensor, name):
                setattr(Tensor, name, fn)

    # broadcast_shape takes SHAPES; a tensor self contributes its .shape
    # (binding the raw function would iterate the Tensor itself, which
    # never raises IndexError under jax index clipping -> infinite loop)
    if not hasattr(Tensor, "broadcast_shape"):
        Tensor.broadcast_shape = lambda self, y_shape: math.broadcast_shape(
            list(self.shape), y_shape
        )

    def _create_parameter_method(self, shape, dtype=None, **kw):
        import paddle_tpu as _p

        return _p.create_parameter(shape, dtype or str(self.dtype), **kw)

    if not hasattr(Tensor, "create_parameter"):
        Tensor.create_parameter = _create_parameter_method


_install_extra_methods()

from . import array  # noqa: F401
from .array import array_length, array_read, array_write, create_array  # noqa: F401
