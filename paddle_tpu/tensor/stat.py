"""Statistics ops (ref: python/paddle/tensor/stat.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..base.tape import apply
from .math import _norm_axis


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(
        lambda a: jnp.std(a, axis=_norm_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        x,
        op_name="std",
    )


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(
        lambda a: jnp.var(a, axis=_norm_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        x,
        op_name="var",
    )


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    def _f(a):
        if mode == "avg":
            return jnp.median(a, axis=_norm_axis(axis), keepdims=keepdim)
        # 'min' mode: lower of the two middle values
        ax = _norm_axis(axis)
        if ax is None:
            flat = jnp.sort(a.reshape(-1))
            return flat[(flat.shape[0] - 1) // 2]
        s = jnp.sort(a, axis=ax)
        k = (a.shape[ax] - 1) // 2
        out = jnp.take(s, k, axis=ax)
        return jnp.expand_dims(out, ax) if keepdim else out

    return apply(_f, x, op_name="median")


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    return apply(
        lambda a: jnp.nanmedian(a, axis=_norm_axis(axis), keepdims=keepdim),
        x,
        op_name="nanmedian",
    )


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    def _f(a):
        qq = jnp.asarray(q)
        return jnp.quantile(a, qq, axis=_norm_axis(axis), keepdims=keepdim, method=interpolation)

    return apply(_f, x, op_name="quantile")


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    def _f(a):
        return jnp.nanquantile(a, jnp.asarray(q), axis=_norm_axis(axis), keepdims=keepdim, method=interpolation)

    return apply(_f, x, op_name="nanquantile")
