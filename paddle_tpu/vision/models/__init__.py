"""vision.models — the reference model zoo re-expressed as nn.Layers.

ref: python/paddle/vision/models/ (lenet.py, alexnet.py, vgg.py,
resnet.py, mobilenetv1.py, mobilenetv2.py). Pretrained-weight download
is not available (no egress); ``pretrained=True`` raises with guidance
to load a converted state_dict via set_state_dict.
"""
from .lenet import LeNet  # noqa: F401
from .alexnet import AlexNet, alexnet  # noqa: F401
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401
from .resnet import (  # noqa: F401
    BasicBlock,
    BottleneckBlock,
    ResNet,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
    wide_resnet50_2,
    wide_resnet101_2,
)
from .mobilenet import MobileNetV1, MobileNetV2, mobilenet_v1, mobilenet_v2  # noqa: F401

__all__ = [
    "LeNet", "AlexNet", "alexnet", "VGG", "vgg11", "vgg13", "vgg16", "vgg19",
    "ResNet", "BasicBlock", "BottleneckBlock", "resnet18", "resnet34",
    "resnet50", "resnet101", "resnet152", "wide_resnet50_2",
    "wide_resnet101_2", "MobileNetV1", "MobileNetV2", "mobilenet_v1",
    "mobilenet_v2",
]


def _no_pretrained(name: str, pretrained: bool):
    if pretrained:
        raise ValueError(
            f"pretrained weights for {name} are not bundled (no network "
            "egress); convert the reference checkpoint and use "
            "set_state_dict instead"
        )
from .extra_nets import *  # noqa: F401,F403
from .resnet import (  # noqa: F401
    resnext50_32x4d, resnext50_64x4d, resnext101_32x4d,
    resnext101_64x4d, resnext152_32x4d, resnext152_64x4d,
)
