"""MobileNet V1/V2 (ref: python/paddle/vision/models/mobilenetv1.py,
mobilenetv2.py — depthwise-separable stacks / inverted residuals)."""
from __future__ import annotations

from ... import nn

__all__ = ["MobileNetV1", "MobileNetV2", "mobilenet_v1", "mobilenet_v2"]


def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _ConvBNReLU(nn.Sequential):
    def __init__(self, in_c, out_c, kernel=3, stride=1, groups=1, relu6=False):
        padding = (kernel - 1) // 2
        super().__init__(
            nn.Conv2D(in_c, out_c, kernel, stride=stride, padding=padding,
                      groups=groups, bias_attr=False),
            nn.BatchNorm2D(out_c),
            nn.ReLU6() if relu6 else nn.ReLU(),
        )


class _DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.depthwise = _ConvBNReLU(in_c, in_c, 3, stride=stride, groups=in_c)
        self.pointwise = _ConvBNReLU(in_c, out_c, 1)

    def forward(self, x):
        return self.pointwise(self.depthwise(x))


class MobileNetV1(nn.Layer):
    """ref: mobilenetv1.py MobileNetV1 — 13 depthwise-separable stages."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        s = lambda c: int(c * scale)
        cfg = [  # (in, out, stride)
            (s(32), s(64), 1), (s(64), s(128), 2), (s(128), s(128), 1),
            (s(128), s(256), 2), (s(256), s(256), 1), (s(256), s(512), 2),
            *[(s(512), s(512), 1)] * 5,
            (s(512), s(1024), 2), (s(1024), s(1024), 1),
        ]
        layers = [_ConvBNReLU(3, s(32), 3, stride=2)]
        layers += [_DepthwiseSeparable(i, o, st) for i, o, st in cfg]
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...tensor.manipulation import flatten

            x = self.fc(flatten(x, 1))
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNReLU(inp, hidden, 1, relu6=True))
        layers += [
            _ConvBNReLU(hidden, hidden, 3, stride=stride, groups=hidden, relu6=True),
            nn.Conv2D(hidden, oup, 1, bias_attr=False),
            nn.BatchNorm2D(oup),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    """ref: mobilenetv2.py MobileNetV2 — standard t/c/n/s table."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        input_channel = _make_divisible(32 * scale)
        last_channel = _make_divisible(1280 * max(1.0, scale))
        inverted = [
            # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        features = [_ConvBNReLU(3, input_channel, 3, stride=2, relu6=True)]
        for t, c, n, s in inverted:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                features.append(
                    InvertedResidual(input_channel, out_c, s if i == 0 else 1, t)
                )
                input_channel = out_c
        features.append(_ConvBNReLU(input_channel, last_channel, 1, relu6=True))
        self.features = nn.Sequential(*features)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(last_channel, num_classes)
            )
        self.last_channel = last_channel

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...tensor.manipulation import flatten

            x = self.classifier(flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    from . import _no_pretrained

    _no_pretrained("mobilenet_v1", pretrained)
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    from . import _no_pretrained

    _no_pretrained("mobilenet_v2", pretrained)
    return MobileNetV2(scale=scale, **kwargs)
