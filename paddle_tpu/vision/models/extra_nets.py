"""MobileNetV3, DenseNet, InceptionV3, SqueezeNet, GoogLeNet,
ShuffleNetV2 (ref: python/paddle/vision/models/{mobilenetv3,densenet,
inceptionv3,squeezenet,googlenet,shufflenetv2}.py — same stage layouts,
channel schedules and heads; NCHW)."""
from __future__ import annotations

from ... import nn
from ...tensor.manipulation import concat, flatten, reshape, split, transpose

__all__ = [
    "MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
    "mobilenet_v3_large", "DenseNet", "densenet121", "densenet161",
    "densenet169", "densenet201", "densenet264", "InceptionV3",
    "inception_v3", "SqueezeNet", "squeezenet1_0", "squeezenet1_1",
    "GoogLeNet", "googlenet", "ShuffleNetV2", "shufflenet_v2_x0_25",
    "shufflenet_v2_x0_33", "shufflenet_v2_x0_5", "shufflenet_v2_x1_0",
    "shufflenet_v2_x1_5", "shufflenet_v2_x2_0", "shufflenet_v2_swish",
]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


# ---------------------------------------------------------------------------
# MobileNetV3 (ref: mobilenetv3.py)
# ---------------------------------------------------------------------------


class _SqueezeExcite(nn.Layer):
    def __init__(self, ch, squeeze_factor=4):
        super().__init__()
        sq = _make_divisible(ch // squeeze_factor)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, sq, 1)
        self.fc2 = nn.Conv2D(sq, ch, 1)

    def forward(self, x):
        s = self.pool(x)
        s = nn.functional.relu(self.fc1(s))
        s = nn.functional.hardsigmoid(self.fc2(s))
        return x * s


class _InvertedResidual(nn.Layer):
    def __init__(self, in_ch, exp_ch, out_ch, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_ch == out_ch
        layers = []
        act_layer = nn.Hardswish if act == "hardswish" else nn.ReLU
        if exp_ch != in_ch:
            layers += [nn.Conv2D(in_ch, exp_ch, 1, bias_attr=False),
                       nn.BatchNorm2D(exp_ch), act_layer()]
        layers += [
            nn.Conv2D(exp_ch, exp_ch, kernel, stride=stride,
                      padding=kernel // 2, groups=exp_ch, bias_attr=False),
            nn.BatchNorm2D(exp_ch), act_layer(),
        ]
        if use_se:
            layers.append(_SqueezeExcite(exp_ch))
        layers += [nn.Conv2D(exp_ch, out_ch, 1, bias_attr=False), nn.BatchNorm2D(out_ch)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_V3_LARGE = [
    # k, exp, out, se, act, stride
    (3, 16, 16, False, "relu", 1),
    (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1),
    (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1),
    (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2),
    (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1),
    (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2),
    (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]

_V3_SMALL = [
    (3, 16, 16, True, "relu", 2),
    (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1),
    (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1),
    (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1),
    (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2),
    (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_ch, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_ch = _make_divisible(16 * scale)
        stem = [nn.Conv2D(3, in_ch, 3, stride=2, padding=1, bias_attr=False),
                nn.BatchNorm2D(in_ch), nn.Hardswish()]
        blocks = []
        for k, exp, out, se, act, s in cfg:
            exp_c = _make_divisible(exp * scale)
            out_c = _make_divisible(out * scale)
            blocks.append(_InvertedResidual(in_ch, exp_c, out_c, k, s, se, act))
            in_ch = out_c
        last_conv = _make_divisible(cfg[-1][1] * scale)
        head = [nn.Conv2D(in_ch, last_conv, 1, bias_attr=False),
                nn.BatchNorm2D(last_conv), nn.Hardswish()]
        self.features = nn.Sequential(*stem, *blocks, *head)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_conv, last_ch), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_ch, num_classes),
            )

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = flatten(x, 1)
            x = self.classifier(x)
        return x


class MobileNetV3Large(_MobileNetV3):
    """ref: mobilenetv3.py MobileNetV3Large."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 1280, scale, num_classes, with_pool)


class MobileNetV3Small(_MobileNetV3):
    """ref: mobilenetv3.py MobileNetV3Small."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 1024, scale, num_classes, with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    from . import _no_pretrained

    _no_pretrained("mobilenet_v3_small", pretrained)
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    from . import _no_pretrained

    _no_pretrained("mobilenet_v3_large", pretrained)
    return MobileNetV3Large(scale=scale, **kwargs)


# ---------------------------------------------------------------------------
# DenseNet (ref: densenet.py)
# ---------------------------------------------------------------------------


class _DenseLayer(nn.Layer):
    def __init__(self, in_ch, growth_rate, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_ch)
        self.conv1 = nn.Conv2D(in_ch, bn_size * growth_rate, 1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3, padding=1, bias_attr=False)
        self.dropout = dropout

    def forward(self, x):
        out = self.conv1(nn.functional.relu(self.bn1(x)))
        out = self.conv2(nn.functional.relu(self.bn2(out)))
        if self.dropout:
            out = nn.functional.dropout(out, self.dropout, training=self.training)
        return concat([x, out], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_ch, out_ch):
        super().__init__()
        self.bn = nn.BatchNorm2D(in_ch)
        self.conv = nn.Conv2D(in_ch, out_ch, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(nn.functional.relu(self.bn(x))))


_DENSE_CFG = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
    264: (64, 32, (6, 12, 64, 48)),
}


class DenseNet(nn.Layer):
    """ref: densenet.py DenseNet."""

    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        init_ch, growth, block_cfg = _DENSE_CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        feats = [nn.Conv2D(3, init_ch, 7, stride=2, padding=3, bias_attr=False),
                 nn.BatchNorm2D(init_ch), nn.ReLU(),
                 nn.MaxPool2D(3, stride=2, padding=1)]
        ch = init_ch
        for i, n in enumerate(block_cfg):
            for _ in range(n):
                feats.append(_DenseLayer(ch, growth, bn_size, dropout))
                ch += growth
            if i != len(block_cfg) - 1:
                feats.append(_Transition(ch, ch // 2))
                ch //= 2
        feats += [nn.BatchNorm2D(ch), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


def _densenet(layers, pretrained, **kwargs):
    from . import _no_pretrained

    _no_pretrained(f"densenet{layers}", pretrained)
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)


# ---------------------------------------------------------------------------
# SqueezeNet (ref: squeezenet.py)
# ---------------------------------------------------------------------------


class _Fire(nn.Layer):
    def __init__(self, in_ch, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(in_ch, squeeze, 1)
        self.e1 = nn.Conv2D(squeeze, e1, 1)
        self.e3 = nn.Conv2D(squeeze, e3, 3, padding=1)

    def forward(self, x):
        x = nn.functional.relu(self.squeeze(x))
        return concat([nn.functional.relu(self.e1(x)),
                       nn.functional.relu(self.e3(x))], axis=1)


class SqueezeNet(nn.Layer):
    """ref: squeezenet.py SqueezeNet (version '1.0'/'1.1')."""

    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64), _Fire(128, 32, 128, 128),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(512, 64, 256, 256),
            )
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256),
            )
        if num_classes > 0:
            self.classifier_conv = nn.Conv2D(512, num_classes, 1)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = nn.functional.relu(self.classifier_conv(
                nn.functional.dropout(x, 0.5, training=self.training)))
        if self.with_pool:
            x = self.pool(x)
        return flatten(x, 1)


def squeezenet1_0(pretrained=False, **kwargs):
    from . import _no_pretrained

    _no_pretrained("squeezenet1_0", pretrained)
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    from . import _no_pretrained

    _no_pretrained("squeezenet1_1", pretrained)
    return SqueezeNet("1.1", **kwargs)


# ---------------------------------------------------------------------------
# GoogLeNet (ref: googlenet.py)
# ---------------------------------------------------------------------------


class _Inception(nn.Layer):
    def __init__(self, in_ch, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = nn.Sequential(nn.Conv2D(in_ch, c1, 1), nn.ReLU())
        self.b2 = nn.Sequential(nn.Conv2D(in_ch, c3r, 1), nn.ReLU(),
                                nn.Conv2D(c3r, c3, 3, padding=1), nn.ReLU())
        self.b3 = nn.Sequential(nn.Conv2D(in_ch, c5r, 1), nn.ReLU(),
                                nn.Conv2D(c5r, c5, 5, padding=2), nn.ReLU())
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                nn.Conv2D(in_ch, proj, 1), nn.ReLU())

    def forward(self, x):
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)], axis=1)


class GoogLeNet(nn.Layer):
    """ref: googlenet.py GoogLeNet — returns (main, aux1, aux2) like the
    reference."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, ceil_mode=True),
            nn.Conv2D(64, 64, 1), nn.ReLU(),
            nn.Conv2D(64, 192, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, ceil_mode=True),
        )
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, ceil_mode=True)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, ceil_mode=True)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool5 = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(1024, num_classes)
            self.aux1_conv = nn.Conv2D(512, 128, 1)
            self.aux1_fc1 = nn.Linear(128 * 16, 1024)
            self.aux1_fc2 = nn.Linear(1024, num_classes)
            self.aux2_conv = nn.Conv2D(528, 128, 1)
            self.aux2_fc1 = nn.Linear(128 * 16, 1024)
            self.aux2_fc2 = nn.Linear(1024, num_classes)
            self.aux_pool = nn.AdaptiveAvgPool2D(4)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.i4a(x)
        aux1 = None
        aux2 = None
        if self.num_classes > 0:
            a = nn.functional.relu(self.aux1_conv(self.aux_pool(x)))
            a = nn.functional.relu(self.aux1_fc1(flatten(a, 1)))
            aux1 = self.aux1_fc2(nn.functional.dropout(a, 0.7, training=self.training))
        x = self.i4d(self.i4c(self.i4b(x)))
        if self.num_classes > 0:
            a = nn.functional.relu(self.aux2_conv(self.aux_pool(x)))
            a = nn.functional.relu(self.aux2_fc1(flatten(a, 1)))
            aux2 = self.aux2_fc2(nn.functional.dropout(a, 0.7, training=self.training))
        x = self.pool4(self.i4e(x))
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = self.pool5(x)
        if self.num_classes > 0:
            x = self.fc(nn.functional.dropout(flatten(x, 1), 0.4, training=self.training))
            return x, aux1, aux2
        return x


def googlenet(pretrained=False, **kwargs):
    from . import _no_pretrained

    _no_pretrained("googlenet", pretrained)
    return GoogLeNet(**kwargs)


# ---------------------------------------------------------------------------
# InceptionV3 (ref: inceptionv3.py — stage layout per the paper/ref impl)
# ---------------------------------------------------------------------------


class _ConvBN(nn.Layer):
    def __init__(self, in_ch, out_ch, k, **kw):
        super().__init__()
        self.conv = nn.Conv2D(in_ch, out_ch, k, bias_attr=False, **kw)
        self.bn = nn.BatchNorm2D(out_ch)

    def forward(self, x):
        return nn.functional.relu(self.bn(self.conv(x)))


class _IncA(nn.Layer):
    def __init__(self, in_ch, pool_feat):
        super().__init__()
        self.b1 = _ConvBN(in_ch, 64, 1)
        self.b5 = nn.Sequential(_ConvBN(in_ch, 48, 1), _ConvBN(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_ConvBN(in_ch, 64, 1), _ConvBN(64, 96, 3, padding=1),
                                _ConvBN(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _ConvBN(in_ch, pool_feat, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)], axis=1)


class _IncB(nn.Layer):  # reduction
    def __init__(self, in_ch):
        super().__init__()
        self.b3 = _ConvBN(in_ch, 384, 3, stride=2)
        self.b3d = nn.Sequential(_ConvBN(in_ch, 64, 1), _ConvBN(64, 96, 3, padding=1),
                                 _ConvBN(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.b3d(x), self.pool(x)], axis=1)


class _IncC(nn.Layer):
    def __init__(self, in_ch, c7):
        super().__init__()
        self.b1 = _ConvBN(in_ch, 192, 1)
        self.b7 = nn.Sequential(_ConvBN(in_ch, c7, 1),
                                _ConvBN(c7, c7, (1, 7), padding=(0, 3)),
                                _ConvBN(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(_ConvBN(in_ch, c7, 1),
                                 _ConvBN(c7, c7, (7, 1), padding=(3, 0)),
                                 _ConvBN(c7, c7, (1, 7), padding=(0, 3)),
                                 _ConvBN(c7, c7, (7, 1), padding=(3, 0)),
                                 _ConvBN(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1), _ConvBN(in_ch, 192, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b7(x), self.b7d(x), self.bp(x)], axis=1)


class _IncD(nn.Layer):  # reduction
    def __init__(self, in_ch):
        super().__init__()
        self.b3 = nn.Sequential(_ConvBN(in_ch, 192, 1), _ConvBN(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(_ConvBN(in_ch, 192, 1),
                                _ConvBN(192, 192, (1, 7), padding=(0, 3)),
                                _ConvBN(192, 192, (7, 1), padding=(3, 0)),
                                _ConvBN(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.b7(x), self.pool(x)], axis=1)


class _IncE(nn.Layer):
    def __init__(self, in_ch):
        super().__init__()
        self.b1 = _ConvBN(in_ch, 320, 1)
        self.b3_stem = _ConvBN(in_ch, 384, 1)
        self.b3_a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.b3d_stem = nn.Sequential(_ConvBN(in_ch, 448, 1), _ConvBN(448, 384, 3, padding=1))
        self.b3d_a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3d_b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1), _ConvBN(in_ch, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        d = self.b3d_stem(x)
        return concat([
            self.b1(x), self.b3_a(s), self.b3_b(s),
            self.b3d_a(d), self.b3d_b(d), self.bp(x),
        ], axis=1)


class InceptionV3(nn.Layer):
    """ref: inceptionv3.py InceptionV3 (299x299 input)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _ConvBN(3, 32, 3, stride=2), _ConvBN(32, 32, 3), _ConvBN(32, 64, 3, padding=1),
            nn.MaxPool2D(3, stride=2),
            _ConvBN(64, 80, 1), _ConvBN(80, 192, 3), nn.MaxPool2D(3, stride=2),
        )
        self.blocks = nn.Sequential(
            _IncA(192, 32), _IncA(256, 64), _IncA(288, 64),
            _IncB(288),
            _IncC(768, 128), _IncC(768, 160), _IncC(768, 160), _IncC(768, 192),
            _IncD(768),
            _IncE(1280), _IncE(2048),
        )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(flatten(x, 1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    from . import _no_pretrained

    _no_pretrained("inception_v3", pretrained)
    return InceptionV3(**kwargs)


# ---------------------------------------------------------------------------
# ShuffleNetV2 (ref: shufflenetv2.py)
# ---------------------------------------------------------------------------


def _channel_shuffle(x, groups):
    n, c, h, w = x.shape
    x = reshape(x, [n, groups, c // groups, h, w])
    x = transpose(x, [0, 2, 1, 3, 4])
    return reshape(x, [n, c, h, w])


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_ch, out_ch, stride, act):
        super().__init__()
        self.stride = stride
        branch = out_ch // 2
        act_layer = nn.Swish if act == "swish" else nn.ReLU
        if stride > 1:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_ch, in_ch, 3, stride=stride, padding=1, groups=in_ch, bias_attr=False),
                nn.BatchNorm2D(in_ch),
                nn.Conv2D(in_ch, branch, 1, bias_attr=False), nn.BatchNorm2D(branch), act_layer(),
            )
            b2_in = in_ch
        else:
            self.branch1 = None
            b2_in = in_ch // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(b2_in, branch, 1, bias_attr=False), nn.BatchNorm2D(branch), act_layer(),
            nn.Conv2D(branch, branch, 3, stride=stride, padding=1, groups=branch, bias_attr=False),
            nn.BatchNorm2D(branch),
            nn.Conv2D(branch, branch, 1, bias_attr=False), nn.BatchNorm2D(branch), act_layer(),
        )

    def forward(self, x):
        if self.stride > 1:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        else:
            x1, x2 = split(x, 2, axis=1)
            out = concat([x1, self.branch2(x2)], axis=1)
        return _channel_shuffle(out, 2)


_SHUFFLE_CH = {
    0.25: (24, 24, 48, 96, 512),
    0.33: (24, 32, 64, 128, 512),
    0.5: (24, 48, 96, 192, 1024),
    1.0: (24, 116, 232, 464, 1024),
    1.5: (24, 176, 352, 704, 1024),
    2.0: (24, 244, 488, 976, 2048),
}


class ShuffleNetV2(nn.Layer):
    """ref: shufflenetv2.py ShuffleNetV2."""

    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        chs = _SHUFFLE_CH[scale]
        act_layer = nn.Swish if act == "swish" else nn.ReLU
        self.stem = nn.Sequential(
            nn.Conv2D(3, chs[0], 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(chs[0]), act_layer(),
            nn.MaxPool2D(3, stride=2, padding=1),
        )
        stages = []
        in_ch = chs[0]
        for stage_i, repeat in enumerate((4, 8, 4)):
            out_ch = chs[stage_i + 1]
            stages.append(_ShuffleUnit(in_ch, out_ch, 2, act))
            for _ in range(repeat - 1):
                stages.append(_ShuffleUnit(out_ch, out_ch, 1, act))
            in_ch = out_ch
        self.stages = nn.Sequential(*stages)
        self.tail = nn.Sequential(
            nn.Conv2D(in_ch, chs[4], 1, bias_attr=False),
            nn.BatchNorm2D(chs[4]), act_layer(),
        )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(chs[4], num_classes)

    def forward(self, x):
        x = self.tail(self.stages(self.stem(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def _shufflenet(scale, act, name, pretrained, **kwargs):
    from . import _no_pretrained

    _no_pretrained(name, pretrained)
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, "relu", "shufflenet_v2_x0_25", pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, "relu", "shufflenet_v2_x0_33", pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, "relu", "shufflenet_v2_x0_5", pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, "relu", "shufflenet_v2_x1_0", pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, "relu", "shufflenet_v2_x1_5", pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, "relu", "shufflenet_v2_x2_0", pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, "swish", "shufflenet_v2_swish", pretrained, **kwargs)
