"""MNIST / FashionMNIST (ref: python/paddle/vision/datasets/mnist.py —
same idx3-ubyte/idx1-ubyte parsing, gzip-compressed files)."""
from __future__ import annotations

import gzip
import os
import struct
from typing import Optional

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST"]


class MNIST(Dataset):
    """mode: 'train' | 'test'. image_path/label_path override the
    default ``{root}/{name}-images-idx3-ubyte.gz`` layout."""

    NAME = "mnist"

    def __init__(self, image_path: Optional[str] = None,
                 label_path: Optional[str] = None, mode: str = "train",
                 transform=None, download: bool = True,
                 backend: Optional[str] = None):
        if mode not in ("train", "test"):
            raise ValueError("mode must be 'train' or 'test'")
        self.mode = mode
        self.transform = transform
        self.backend = backend or "numpy"
        prefix = "train" if mode == "train" else "t10k"
        if image_path is None or label_path is None:
            root = os.path.expanduser(f"~/.cache/paddle_tpu/{self.NAME}")
            image_path = image_path or os.path.join(root, f"{prefix}-images-idx3-ubyte.gz")
            label_path = label_path or os.path.join(root, f"{prefix}-labels-idx1-ubyte.gz")
        if not (os.path.exists(image_path) and os.path.exists(label_path)):
            raise RuntimeError(
                f"{type(self).__name__} files not found at {image_path} / "
                f"{label_path}; automatic download is unavailable (no "
                "network egress) — place the idx-ubyte(.gz) files there "
                "or pass image_path/label_path"
            )
        self.images, self.labels = self._load(image_path, label_path)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")

    def _load(self, image_path, label_path):
        with self._open(image_path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise ValueError(f"bad idx3 magic {magic} in {image_path}")
            images = np.frombuffer(f.read(n * rows * cols), np.uint8)
            images = images.reshape(n, rows, cols)
        with self._open(label_path) as f:
            magic, n2 = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise ValueError(f"bad idx1 magic {magic} in {label_path}")
            labels = np.frombuffer(f.read(n2), np.uint8).astype(np.int64)
        if n != n2:
            raise ValueError("image/label count mismatch")
        return images, labels

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"
