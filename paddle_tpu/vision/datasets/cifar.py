"""Cifar10 / Cifar100 (ref: python/paddle/vision/datasets/cifar.py —
same tar.gz of pickled batches with b'data' + b'labels'/b'fine_labels')."""
from __future__ import annotations

import os
import pickle
import tarfile
from typing import Optional

import numpy as np

from ...io import Dataset

__all__ = ["Cifar10", "Cifar100"]


class Cifar10(Dataset):
    _archive = "cifar-10-python.tar.gz"
    _train_members = [f"data_batch_{i}" for i in range(1, 6)]
    _test_members = ["test_batch"]
    _label_key = b"labels"

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform=None, download: bool = True,
                 backend: Optional[str] = None):
        if mode not in ("train", "test"):
            raise ValueError("mode must be 'train' or 'test'")
        self.mode = mode
        self.transform = transform
        if data_file is None:
            data_file = os.path.expanduser(
                f"~/.cache/paddle_tpu/{self._archive}"
            )
        if not os.path.exists(data_file):
            raise RuntimeError(
                f"{type(self).__name__} archive not found at {data_file}; "
                "automatic download is unavailable (no network egress) — "
                "place the tar.gz there or pass data_file"
            )
        self.data, self.labels = self._load(data_file)

    def _load(self, data_file):
        members = self._train_members if self.mode == "train" else self._test_members
        images, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            names = {os.path.basename(n): n for n in tf.getnames()}
            for m in members:
                if m not in names:
                    raise ValueError(f"member {m} missing from {data_file}")
                with tf.extractfile(names[m]) as f:
                    batch = pickle.load(f, encoding="bytes")
                images.append(batch[b"data"])
                labels.extend(batch[self._label_key])
        data = np.concatenate(images).reshape(-1, 3, 32, 32)
        data = np.transpose(data, (0, 2, 3, 1))  # HWC like the reference
        return data, np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        img = self.data[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    _archive = "cifar-100-python.tar.gz"
    _train_members = ["train"]
    _test_members = ["test"]
    _label_key = b"fine_labels"
