"""vision.datasets (ref: python/paddle/vision/datasets/ — mnist.py,
cifar.py). File-format parsers are faithful (MNIST idx-ubyte, CIFAR
pickle batches); automatic download is unavailable (no egress), so
``download=True`` raises with the expected file layout instead.
"""
from .mnist import MNIST, FashionMNIST  # noqa: F401
from .cifar import Cifar10, Cifar100  # noqa: F401

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100"]
