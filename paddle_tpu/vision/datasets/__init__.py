"""vision.datasets (ref: python/paddle/vision/datasets/ — mnist.py,
cifar.py). File-format parsers are faithful (MNIST idx-ubyte, CIFAR
pickle batches); automatic download is unavailable (no egress), so
``download=True`` raises with the expected file layout instead.
"""
from ...io import Dataset
from .mnist import MNIST, FashionMNIST  # noqa: F401
from .cifar import Cifar10, Cifar100  # noqa: F401

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100",
           "DatasetFolder", "ImageFolder", "Flowers", "VOC2012"]


class DatasetFolder(Dataset):
    """Generic folder-of-class-subfolders dataset (ref:
    vision/datasets/folder.py DatasetFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os

        self.root = root
        self.transform = transform
        self.loader = loader or self._pil_loader
        exts = tuple(e.lower() for e in (extensions or (
            ".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif", ".tiff", ".webp"
        )))
        classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        )
        if not classes:
            raise RuntimeError(f"no class folders under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for f in sorted(files):
                    path = os.path.join(dirpath, f)
                    ok = (is_valid_file(path) if is_valid_file
                          else f.lower().endswith(exts))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"no valid files under {root}")

    @staticmethod
    def _pil_loader(path):
        from PIL import Image

        with open(path, "rb") as f:
            return Image.open(f).convert("RGB")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat folder of images, no labels (ref: folder.py ImageFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os

        self.root = root
        self.transform = transform
        self.loader = loader or DatasetFolder._pil_loader
        exts = tuple(e.lower() for e in (extensions or (
            ".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif", ".tiff", ".webp"
        )))
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for f in sorted(files):
                path = os.path.join(dirpath, f)
                ok = (is_valid_file(path) if is_valid_file
                      else f.lower().endswith(exts))
                if ok:
                    self.samples.append(path)

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """Oxford-102 Flowers (ref: vision/datasets/flowers.py). No network
    egress in this environment: pass data_file/label_file/setid_file
    paths to pre-downloaded archives."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        if data_file is None or label_file is None or setid_file is None:
            raise RuntimeError(
                "Flowers: automatic download is unavailable (no network "
                "egress); pass data_file=, label_file= and setid_file= "
                "pointing at the Oxford-102 archives."
            )
        import scipy.io as sio

        self.transform = transform
        self.mode = mode
        labels = sio.loadmat(label_file)["labels"][0]
        setid = sio.loadmat(setid_file)
        key = {"train": "trnid", "valid": "valid", "test": "tstid"}[mode]
        self.indexes = setid[key][0]
        self.data_file = data_file
        self.labels = labels

    def _tar(self):
        # one handle per process (lazy: survives DataLoader worker
        # pickling, avoids re-scanning the archive per sample)
        import tarfile

        tf = getattr(self, "_tf", None)
        if tf is None:
            tf = tarfile.open(self.data_file)
            self._tf = tf
        return tf

    def __getstate__(self):
        d = dict(self.__dict__)
        d.pop("_tf", None)
        return d

    def __getitem__(self, idx):
        from PIL import Image

        flower_id = int(self.indexes[idx])
        name = f"jpg/image_{flower_id:05d}.jpg"
        img = Image.open(self._tar().extractfile(name)).convert("RGB")
        label = int(self.labels[flower_id - 1]) - 1
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.indexes)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation (ref: vision/datasets/voc2012.py).
    Pass data_file= pointing at the pre-downloaded VOCtrainval tar."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        if data_file is None:
            raise RuntimeError(
                "VOC2012: automatic download is unavailable (no network "
                "egress); pass data_file= pointing at VOCtrainval_11-May-2012.tar."
            )
        import tarfile

        self.transform = transform
        self.data_file = data_file
        seg_list = {
            "train": "VOCdevkit/VOC2012/ImageSets/Segmentation/train.txt",
            "valid": "VOCdevkit/VOC2012/ImageSets/Segmentation/val.txt",
            "test": "VOCdevkit/VOC2012/ImageSets/Segmentation/trainval.txt",
        }[mode]
        with tarfile.open(data_file) as tf:
            names = tf.extractfile(seg_list).read().decode().split()
        self.names = names

    _tar = Flowers._tar
    __getstate__ = Flowers.__getstate__

    def __getitem__(self, idx):
        import numpy as np
        from PIL import Image

        name = self.names[idx]
        tf = self._tar()
        img = Image.open(tf.extractfile(
            f"VOCdevkit/VOC2012/JPEGImages/{name}.jpg")).convert("RGB")
        lab = Image.open(tf.extractfile(
            f"VOCdevkit/VOC2012/SegmentationClass/{name}.png"))
        img = np.asarray(img)
        lab = np.asarray(lab)
        if self.transform is not None:
            img = self.transform(img)
        return img, lab

    def __len__(self):
        return len(self.names)
