"""paddle_tpu.vision — transforms, datasets, model zoo.

ref: python/paddle/vision/ — transforms/ (functional + class
transforms), datasets/ (MNIST/FashionMNIST/Cifar...), models/ (LeNet,
AlexNet, VGG, ResNet, MobileNet...). Host-side image code is numpy/PIL
(it runs in dataloader workers, not on the TPU); models are nn.Layers
whose compute lowers to XLA convs on the MXU.
"""
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
from .models import (  # noqa: F401
    LeNet,
    AlexNet,
    VGG,
    ResNet,
    MobileNetV1,
    MobileNetV2,
    alexnet,
    mobilenet_v1,
    mobilenet_v2,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
    vgg11,
    vgg13,
    vgg16,
    vgg19,
)

__all__ = ["transforms", "datasets", "models", "ops"]


def set_image_backend(backend: str):
    """ref: vision/image.py set_image_backend — 'pil' | 'cv2' | 'tensor'.
    Only pil/numpy are meaningful here; recorded for get_image_backend."""
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unknown image backend {backend!r}")
    _image_backend = backend


_image_backend = "pil"


def get_image_backend() -> str:
    return _image_backend


def image_load(path, backend=None):
    """ref: vision/image.py image_load. backend 'pil' -> PIL Image;
    'cv2' -> BGR ndarray (cv2 itself is not bundled; decoded via PIL);
    'tensor' -> CHW paddle Tensor."""
    import numpy as _np
    from PIL import Image

    b = backend or get_image_backend()
    img = Image.open(path)
    if b == "pil":
        return img
    arr = _np.asarray(img.convert("RGB"))
    if b == "cv2":
        return arr[..., ::-1].copy()  # BGR, matching the cv2 backend
    from .. import to_tensor

    return to_tensor(arr.transpose(2, 0, 1))
