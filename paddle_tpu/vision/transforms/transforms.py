"""Class transforms (ref: python/paddle/vision/transforms/transforms.py).

Random parameters draw from a host numpy RNG seeded off the framework
generator (reproducible via paddle.seed, cheap in dataloader threads).
"""
from __future__ import annotations

import numbers
import random as _pyrandom
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from . import functional as F

__all__ = [
    "BaseTransform", "Compose", "ToTensor", "Resize", "RandomResizedCrop",
    "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip", "Normalize",
    "Transpose", "RandomCrop", "Pad", "RandomRotation", "ColorJitter",
    "Grayscale", "BrightnessTransform", "ContrastTransform",
    "SaturationTransform", "HueTransform", "RandomErasing",
]


def _rng() -> np.random.Generator:
    import jax

    from ...base import random as _random

    key_data = np.asarray(jax.random.key_data(_random.next_key()))
    return np.random.default_rng(key_data.astype(np.uint32))


class BaseTransform:
    """ref: transforms.py BaseTransform — keys-based multi-field
    dispatch collapsed to: apply to image (or each image in a tuple)."""

    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        if isinstance(inputs, tuple):
            return tuple(self._apply_image(x) for x in inputs)
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        if self.padding is not None:
            img = F.pad(img, self.padding, self.fill, self.padding_mode)
        h, w = F._size_hw(img)
        th, tw = self.size
        if self.pad_if_needed and w < tw:
            img = F.pad(img, (tw - w, 0), self.fill, self.padding_mode)
        if self.pad_if_needed and h < th:
            img = F.pad(img, (0, th - h), self.fill, self.padding_mode)
        h, w = F._size_hw(img)
        rng = _rng()
        top = int(rng.integers(0, h - th + 1))
        left = int(rng.integers(0, w - tw + 1))
        return F.crop(img, top, left, th, tw)


class RandomResizedCrop(BaseTransform):
    """ref: transforms.py RandomResizedCrop — scale/ratio sampling with
    10 tries then center fallback."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        h, w = F._size_hw(img)
        area = h * w
        rng = _rng()
        for _ in range(10):
            target_area = area * rng.uniform(*self.scale)
            log_ratio = (np.log(self.ratio[0]), np.log(self.ratio[1]))
            aspect = np.exp(rng.uniform(*log_ratio))
            cw = int(round(np.sqrt(target_area * aspect)))
            ch = int(round(np.sqrt(target_area / aspect)))
            if 0 < cw <= w and 0 < ch <= h:
                top = int(rng.integers(0, h - ch + 1))
                left = int(rng.integers(0, w - cw + 1))
                img = F.crop(img, top, left, ch, cw)
                return F.resize(img, self.size, self.interpolation)
        return F.resize(F.center_crop(img, min(h, w)), self.size, self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if _rng().uniform() < self.prob:
            return F.hflip(img)
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if _rng().uniform() < self.prob:
            return F.vflip(img)
        return img


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format)


class Transpose(BaseTransform):
    """HWC → CHW by default (ref: transforms.py Transpose)."""

    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = F._to_np(img)
        return np.transpose(arr, self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = float(_rng().uniform(*self.degrees))
        return F.rotate(img, angle, self.interpolation, self.expand, self.center, self.fill)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _factor(self):
        lo, hi = max(0, 1 - self.value), 1 + self.value
        return float(_rng().uniform(lo, hi))

    def _apply_image(self, img):
        return F.adjust_brightness(img, self._factor())


class ContrastTransform(BrightnessTransform):
    def _apply_image(self, img):
        return F.adjust_contrast(img, self._factor())


class SaturationTransform(BrightnessTransform):
    def _apply_image(self, img):
        return F.adjust_saturation(img, self._factor())


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        return F.adjust_hue(img, float(_rng().uniform(-self.value, self.value)))


class ColorJitter(BaseTransform):
    """ref: transforms.py ColorJitter — random order of the four."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        super().__init__(keys)
        self.transforms = []
        if brightness:
            self.transforms.append(BrightnessTransform(brightness))
        if contrast:
            self.transforms.append(ContrastTransform(contrast))
        if saturation:
            self.transforms.append(SaturationTransform(saturation))
        if hue:
            self.transforms.append(HueTransform(hue))

    def _apply_image(self, img):
        order = _rng().permutation(len(self.transforms))
        for i in order:
            img = self.transforms[int(i)]._apply_image(img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class RandomErasing(BaseTransform):
    """ref: transforms.py RandomErasing — on CHW Tensor/ndarray."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        rng = _rng()
        if rng.uniform() >= self.prob:
            return img
        from ...base.tensor import Tensor

        if isinstance(img, Tensor):  # CHW
            h, w = img.shape[-2], img.shape[-1]
        else:  # ndarray/PIL: HWC
            h, w = F._size_hw(img)
        area = h * w
        for _ in range(10):
            target = area * rng.uniform(*self.scale)
            aspect = np.exp(rng.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target / aspect)))
            ew = int(round(np.sqrt(target * aspect)))
            if eh < h and ew < w:
                top = int(rng.integers(0, h - eh + 1))
                left = int(rng.integers(0, w - ew + 1))
                return F.erase(img, top, left, eh, ew, self.value, self.inplace)
        return img


class RandomAffine(BaseTransform):
    """ref: transforms.py RandomAffine — random rotation/translate/
    scale/shear."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if isinstance(degrees, (int, float)) else tuple(degrees)
        self.translate, self.scale_rng, self.shear = translate, scale, shear
        self.interpolation, self.fill, self.center = interpolation, fill, center

    def _apply_image(self, img):
        from . import functional as F

        r = _rng()  # framework-seeded: paddle.seed reproduces pipelines
        angle = r.uniform(*self.degrees)
        w, h = (img.size if hasattr(img, "size") else (img.shape[1], img.shape[0]))
        if self.translate is not None:
            tx = r.uniform(-self.translate[0], self.translate[0]) * w
            ty = r.uniform(-self.translate[1], self.translate[1]) * h
        else:
            tx = ty = 0.0
        scale = r.uniform(*self.scale_rng) if self.scale_rng else 1.0
        if self.shear is not None:
            sh = self.shear if isinstance(self.shear, (list, tuple)) else (-self.shear, self.shear)
            shear = r.uniform(sh[0], sh[1])
        else:
            shear = 0.0
        return F.affine(img, angle, (tx, ty), scale, shear,
                        self.interpolation, self.fill, self.center)


class RandomPerspective(BaseTransform):
    """ref: transforms.py RandomPerspective."""

    def __init__(self, prob=0.5, distortion_scale=0.5, interpolation="nearest",
                 fill=0, keys=None):
        super().__init__(keys)
        self.prob, self.distortion_scale = prob, distortion_scale
        self.interpolation, self.fill = interpolation, fill

    def _apply_image(self, img):
        from . import functional as F

        r = _rng()
        if r.random() >= self.prob:
            return img
        w, h = (img.size if hasattr(img, "size") else (img.shape[1], img.shape[0]))
        d = self.distortion_scale
        half_w, half_h = w // 2, h // 2
        ri = lambda hi: int(r.integers(0, hi + 1))  # inclusive, like randint
        tl = (ri(int(d * half_w)), ri(int(d * half_h)))
        tr = (w - 1 - ri(int(d * half_w)), ri(int(d * half_h)))
        br = (w - 1 - ri(int(d * half_w)), h - 1 - ri(int(d * half_h)))
        bl = (ri(int(d * half_w)), h - 1 - ri(int(d * half_h)))
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [tl, tr, br, bl]
        return F.perspective(img, start, end, self.interpolation, self.fill)
